"""Build/install horovod_tpu (parity: the reference's setup.py compiles its
native core into the wheel, setup.py:336-338; here the native coordination
core builds via its Makefile into a packaged shared library).

    pip install -e .        # or: python setup.py build

No TF/MPI/CUDA probing is needed: the data plane is jax/XLA (pure Python
deps) and the native core is dependency-free C++14 over POSIX sockets.
"""

import os
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py


class BuildWithNativeCore(build_py):
    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        coord = os.path.join(here, "horovod_tpu", "coord")
        subprocess.run(["make", "-C", coord], check=True)
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description="TPU-native distributed training framework "
                "(Horovod v0.11.2 capability parity)",
    packages=find_packages(include=["horovod_tpu", "horovod_tpu.*"]),
    package_data={"horovod_tpu.coord": ["libhvdcoord.so", "coordinator.cc",
                                        "Makefile"]},
    python_requires=">=3.10",
    # jax floor: 0.9 is the version every CI leg verifies (this image
    # ships exactly one jax, so older floors would be untested claims).
    # The only cross-version API the package touches is
    # all_gather_invariant, shimmed for three jax generations in
    # utils/compat.py (README "Version matrix" states the coverage).
    install_requires=["jax>=0.9", "flax", "optax", "orbax-checkpoint",
                      "numpy"],
    # "digits" real-dataset loader (data.load_dataset) needs sklearn.
    extras_require={"datasets": ["scikit-learn"]},
    scripts=["bin/tpurun"],
    cmdclass={"build_py": BuildWithNativeCore},
)
