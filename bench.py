"""Benchmark: ResNet-50 synthetic training throughput (images/sec/chip).

Mirrors the reference's benchmark methodology — `tf_cnn_benchmarks.py
--variable_update horovod` with synthetic data (``docs/benchmarks.md:8-98``)
— on the flagship north-star workload (ResNet-50,
``examples/keras_imagenet_resnet50.py``). The baseline for ``vs_baseline``
is the reference's only published absolute throughput: ResNet-101 at
1656.82 images/sec across 16 Pascal GPUs = 103.55 images/sec/GPU
(``docs/benchmarks.md:24-54``; see /root/repo/BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models, training

# Reference baseline: 1656.82 images/sec on 16 GPUs (docs/benchmarks.md:24-54).
BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16


def main() -> None:
    smoke = bool(int(os.environ.get("HVD_BENCH_SMOKE", "0")))
    on_tpu = jax.default_backend() == "tpu"

    if smoke or not on_tpu:
        image, batch_per_chip, warmup, iters = 64, 16, 2, 5
        depth_cfg = dict(model="cifar20")
    else:
        image, batch_per_chip, warmup, iters = 224, 128, 5, 20
        depth_cfg = dict(model="resnet50")

    hvd.init()
    n = hvd.size()
    batch = batch_per_chip * n

    if depth_cfg["model"] == "resnet50":
        model = models.resnet50(num_classes=1000, dtype=jnp.bfloat16,
                                axis_name=hvd.AXIS)
        classes = 1000
    else:
        model = models.cifar_resnet_v1(20, dtype=jnp.float32,
                                       axis_name=hvd.AXIS)
        classes = 10

    x_shape = (batch, image, image, 3)
    # Init from a per-chip-sized sample: flax init runs a real forward pass
    # on one device, so a global-batch sample would OOM at pod scale.
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((batch_per_chip,) + x_shape[1:], jnp.float32),
        optax.sgd(0.1, momentum=0.9))
    step = training.make_train_step(model, dist_opt)

    # Materialize only local shards (a host-side global batch would be
    # multiple GB at pod scale).
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(hvd.mesh(), P(hvd.AXIS))

    def _shard_data(idx):
        rng = np.random.RandomState(hash(str(idx)) % 2**31)
        shape = tuple(s.stop - s.start if s.start is not None else dim
                      for s, dim in zip(idx, x_shape))
        return rng.standard_normal(shape).astype(np.float32)

    def _shard_labels(idx):
        rng = np.random.RandomState(1 + hash(str(idx)) % 2**31)
        n = idx[0].stop - idx[0].start if idx[0].start is not None else batch
        return rng.randint(0, classes, size=(n,))

    data = (
        jax.make_array_from_callback(x_shape, sharding, _shard_data),
        jax.make_array_from_callback((batch,), sharding, _shard_labels),
    )

    for _ in range(warmup):
        state, metrics = step(state, data)
    float(metrics["loss"])  # full device->host sync before timing

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, data)
    # End the timed region with an explicit host transfer: on experimental
    # backends block_until_ready alone has been observed to return before
    # the dispatch queue drains, inflating throughput ~15x.
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), final_loss

    img_per_sec = batch * iters / dt
    per_chip = img_per_sec / n
    print(json.dumps({
        "metric": f"{depth_cfg['model']}_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / BASELINE_IMG_PER_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
