"""Benchmark: ResNet-50 synthetic training throughput (images/sec/chip).

Mirrors the reference's benchmark methodology — `tf_cnn_benchmarks.py
--variable_update horovod` with synthetic data (``docs/benchmarks.md:8-98``)
— on the flagship north-star workload (ResNet-50,
``examples/keras_imagenet_resnet50.py``). The baseline for ``vs_baseline``
is the reference's only published absolute throughput: ResNet-101 at
1656.82 images/sec across 16 Pascal GPUs = 103.55 images/sec/GPU
(``docs/benchmarks.md:24-54``; see /root/repo/BASELINE.md).

Default: prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
``--scaling`` (single-controller only): measures throughput at world sizes
1, 2, 4, ... and the full device count, printing one scaling-efficiency
JSON line per size (rate_N / (N · rate_1) — the reference's headline
metric: 90% @ 128 GPUs; north star ≥90% @ v5e-64) followed by the standard
full-world images/sec/chip line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models, training

# Reference baseline: 1656.82 images/sec on 16 GPUs running ResNet-101
# (docs/benchmarks.md:24-54) — the reference's only absolute throughput.
# For other models the per-GPU baseline is FLOPs-scaled from it (the
# reference GPU's estimated rate on that model), so vs_baseline stays an
# apples-to-apples hardware ratio rather than crediting cheaper models.
BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16


def _baseline_for(model: str) -> float:
    return BASELINE_IMG_PER_SEC_PER_DEVICE * (
        _FWD_GMACS["resnet101"] / _FWD_GMACS[model])

# Analytic FLOPs model: forward GMACs per image × 2 (multiply-accumulate =
# 2 FLOPs — the convention XLA's own cost analysis uses; its estimate for
# the ResNet-50 train step, 23.9 GFLOP/img, matches this model) × 3
# (backward ≈ 2× forward). Lets the JSON line report TFLOP/s and MFU so the
# number is judgeable against the chip's peak, not just a 2017 GPU.
_FWD_GMACS = {"resnet50": 4.09, "resnet101": 7.80, "vgg16": 15.47,
              "inception3": 5.73, "cifar20": 0.041}
TRAIN_GFLOP_PER_IMAGE = {k: 3 * 2 * v for k, v in _FWD_GMACS.items()}

# Peak dense bf16 TFLOP/s per chip by device kind (public specs; the
# denominators for MFU).
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),   # v5e
    ("v6 lite", 918.0),   # v6e / Trillium
    ("v5p", 459.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def _peak_tflops_per_chip():
    if jax.default_backend() != "tpu":
        return None
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


# Per-model TPU configs (the reference benchmark family, tf_cnn_benchmarks
# --model {resnet50, resnet101, vgg16, inception3}; docs/benchmarks.md:5-6).
_TPU_CONFIGS = {
    "resnet50": dict(model="resnet50", image=224, batch_per_chip=128,
                     warmup=5, iters=4, classes=1000, steps_per_call=8),
    "resnet101": dict(model="resnet101", image=224, batch_per_chip=96,
                      warmup=5, iters=4, classes=1000, steps_per_call=8),
    # VGG has no BN: classic SGD needs the small-lr recipe or it blows up.
    "vgg16": dict(model="vgg16", image=224, batch_per_chip=96,
                  warmup=5, iters=4, classes=1000, steps_per_call=8,
                  lr=0.01),
    "inception3": dict(model="inception3", image=299, batch_per_chip=96,
                       warmup=5, iters=4, classes=1000, steps_per_call=8),
}


def _bench_config(model: str = "resnet50"):
    smoke = bool(int(os.environ.get("HVD_BENCH_SMOKE", "0")))
    on_tpu = jax.default_backend() == "tpu"
    if smoke or not on_tpu:
        # No scan off-TPU: compiling the scanned step on the virtual CPU
        # mesh costs minutes and there is no dispatch overhead to amortize.
        return dict(model="cifar20", image=64, batch_per_chip=16,
                    warmup=2, iters=5, classes=10, steps_per_call=1)
    # steps_per_call: lax.scan over k steps inside one dispatch — amortizes
    # the per-call host->device dispatch overhead (measured ~4-5 ms on the
    # axon tunnel; worth ~+4% at 50 ms steps) exactly like
    # tf_cnn_benchmarks' in-graph loop over synthetic data.
    return dict(_TPU_CONFIGS[model])


def _build_model(cfg):
    """Benchmark models use local (per-replica) BatchNorm — the reference /
    Goyal configuration; cross-replica BN is opt-in via axis_name."""
    name = cfg["model"]
    if name == "resnet50":
        return models.resnet50(num_classes=cfg["classes"],
                               dtype=jnp.bfloat16)
    if name == "resnet101":
        return models.resnet101(num_classes=cfg["classes"],
                                dtype=jnp.bfloat16)
    if name == "vgg16":
        return models.vgg16(num_classes=cfg["classes"], dtype=jnp.bfloat16)
    if name == "inception3":
        return models.inception_v3(num_classes=cfg["classes"],
                                   dtype=jnp.bfloat16)
    return models.cifar_resnet_v1(20, dtype=jnp.float32)


def measure(devices=None, cfg=None) -> float:
    """Images/sec of the compiled distributed train step over ``devices``
    (default: all). Returns total (not per-chip) throughput."""
    cfg = cfg or _bench_config()
    if hvd.is_initialized():
        hvd.shutdown()
    hvd.init(devices=devices)
    n = hvd.size()
    batch = cfg["batch_per_chip"] * n
    image, classes = cfg["image"], cfg["classes"]

    model = _build_model(cfg)

    x_shape = (batch, image, image, 3)
    # Init from a per-chip-sized sample: flax init runs a real forward pass
    # on one device, so a global-batch sample would OOM at pod scale.
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((cfg["batch_per_chip"],) + x_shape[1:], jnp.float32),
        optax.sgd(cfg.get("lr", 0.1), momentum=0.9))
    step = training.make_train_step(model, dist_opt)

    # Materialize only local shards (a host-side global batch would be
    # multiple GB at pod scale).
    if hvd.world().env_world:
        # Independent process per chip: build just this rank's slice (the
        # shard_batch split), not the global batch — otherwise every rank
        # trains on all N shards and throughput is over-reported N×.
        r = hvd.rank()
        rng = np.random.RandomState(r)
        local = (cfg["batch_per_chip"],) + x_shape[1:]
        data = (
            jnp.asarray(rng.standard_normal(local).astype(np.float32)),
            jnp.asarray(rng.randint(0, classes,
                                    size=(cfg["batch_per_chip"],))),
        )
        for _ in range(cfg["warmup"]):
            state, metrics = step(state, data)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(cfg["iters"]):
            state, metrics = step(state, data)
        final_loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
        assert np.isfinite(final_loss), final_loss
        return batch * cfg["iters"] / dt

    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(hvd.mesh(), P(hvd.AXIS))

    def _shard_data(idx):
        rng = np.random.RandomState(hash(str(idx)) % 2**31)
        shape = tuple(s.stop - s.start if s.start is not None else dim
                      for s, dim in zip(idx, x_shape))
        return rng.standard_normal(shape).astype(np.float32)

    def _shard_labels(idx):
        rng = np.random.RandomState(1 + hash(str(idx)) % 2**31)
        rows = idx[0].stop - idx[0].start if idx[0].start is not None \
            else batch
        return rng.randint(0, classes, size=(rows,))

    data = (
        jax.make_array_from_callback(x_shape, sharding, _shard_data),
        jax.make_array_from_callback((batch,), sharding, _shard_labels),
    )

    k = int(cfg.get("steps_per_call", 1))
    if k > 1:
        def _body(s, _):
            s2, m = step(s, data)
            return s2, m["loss"]

        import functools

        # Donate the carried state: the inner step's donation is ignored
        # when traced under this jit, and an undonated TrainState copy
        # (~1 GB for VGG-16) would sit in HBM for the whole dispatch.
        @functools.partial(jax.jit, donate_argnums=0)
        def _multi(s):
            s2, losses = jax.lax.scan(_body, s, None, length=k)
            return s2, losses[-1]

        def run_once(s):
            s2, loss = _multi(s)
            return s2, loss
    else:
        def run_once(s):
            s2, m = step(s, data)
            return s2, m["loss"]

    for _ in range(cfg["warmup"]):
        state, loss = run_once(state)
    float(loss)  # full device->host sync before timing

    t0 = time.perf_counter()
    for _ in range(cfg["iters"]):
        state, loss = run_once(state)
    # End the timed region with an explicit host transfer: on experimental
    # backends block_until_ready alone has been observed to return before
    # the dispatch queue drains, inflating throughput ~15x.
    final_loss = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss), final_loss
    return batch * cfg["iters"] * k / dt


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--scaling", action="store_true",
                   help="measure world sizes 1,2,4,... and report "
                        "scaling efficiency per size")
    p.add_argument("--model", default="resnet50",
                   choices=sorted(_TPU_CONFIGS),
                   help="benchmark model (the reference's "
                        "tf_cnn_benchmarks family; ignored in smoke/CPU "
                        "mode)")
    args = p.parse_args()
    cfg = _bench_config(args.model)

    if args.scaling:
        # Scaling mode is single-controller only: it re-inits the world with
        # device subsets, which is ill-defined when other processes own part
        # of the mesh (jax.distributed) or in tpurun env-worlds.
        from horovod_tpu.utils import config as _hvd_config
        # Probe the ENV, not jax.process_count(): touching the backend here
        # would both defeat the check (count is 1 before distributed init)
        # and block a later jax.distributed initialization.
        if _hvd_config.launcher_size(1) > 1 \
                or os.environ.get("JAX_COORDINATOR_ADDRESS"):
            raise SystemExit(
                "--scaling requires a single-controller world (run without "
                "tpurun/jax.distributed; one process drives all chips)")
        devs = jax.devices()
        sizes = sorted({s for s in (2 ** p for p in range(8))
                        if s <= len(devs)} | {len(devs)})
        rate1 = None
        rate = None
        for n in sizes:
            rate = measure(devices=devs[:n], cfg=cfg)
            if n == 1:
                rate1 = rate
            eff = rate / (n * rate1) if rate1 else float("nan")
            print(json.dumps({
                "metric": f"{cfg['model']}_scaling_efficiency_{n}chips",
                "value": round(eff, 4),
                "unit": "fraction",
                "vs_baseline": round(eff / 0.90, 3),  # ref: 90% @ 128 GPUs
                "images_per_sec_total": round(rate, 2),
            }))
        # Also emit the standard absolute metric (full world) so parsers
        # keyed on it always find it.
        per_chip = rate / len(devs)
        print(json.dumps({
            "metric": f"{cfg['model']}_synthetic_images_per_sec_per_chip",
            "value": round(per_chip, 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(per_chip / _baseline_for(cfg["model"]),
                                 3),
        }))
        return

    rate = measure(cfg=cfg)
    per_chip = rate / hvd.size()
    line = {
        "metric": f"{cfg['model']}_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / _baseline_for(cfg["model"]), 3),
    }
    tflops = per_chip * TRAIN_GFLOP_PER_IMAGE[cfg["model"]] / 1e3
    line["tflops_per_chip"] = round(tflops, 1)
    peak = _peak_tflops_per_chip()
    if peak:
        line["mfu"] = round(tflops / peak, 3)
    print(json.dumps(line))


if __name__ == "__main__":
    sys.exit(main())
