"""Benchmark: synthetic training throughput (ResNet-50 + transformer LM).

Mirrors the reference's benchmark methodology — `tf_cnn_benchmarks.py
--variable_update horovod` with synthetic data (``docs/benchmarks.md:8-98``)
— on the flagship north-star workload (ResNet-50,
``examples/keras_imagenet_resnet50.py``) plus a transformer-LM training
step (the TPU-era matmul-dominated workload: bf16, Pallas flash attention,
``parallel/transformer.py``). The baseline for ``vs_baseline`` is the
reference's only published absolute throughput: ResNet-101 at 1656.82
images/sec across 16 Pascal GPUs = 103.55 images/sec/GPU
(``docs/benchmarks.md:24-54``; see /root/repo/BASELINE.md); other models'
baselines are FLOPs-scaled from it so the ratio compares hardware.

Default: prints TWO JSON lines {"metric", "value", "unit", "vs_baseline"}
— ResNet-50 images/sec/chip first (the primary metric), then the
transformer-LM tokens/sec/chip with TFLOP/s and MFU.
``--scaling`` (single-controller only): measures throughput at world sizes
1, 2, 4, ... and the full device count, printing one scaling-efficiency
JSON line per size (rate_N / (N · rate_1) — the reference's headline
metric: 90% @ 128 GPUs; north star ≥90% @ v5e-64) followed by the standard
full-world images/sec/chip line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models, training

# Reference baseline: 1656.82 images/sec on 16 GPUs running ResNet-101
# (docs/benchmarks.md:24-54) — the reference's only absolute throughput.
# For other models the per-GPU baseline is FLOPs-scaled from it (the
# reference GPU's estimated rate on that model), so vs_baseline stays an
# apples-to-apples hardware ratio rather than crediting cheaper models.
BASELINE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16


def _median_rate(run_once, state, units_per_round, rounds):
    """Median-of-rounds throughput: time ``rounds`` independent regions and
    take the median rate. A single timed region is exposed to one-off
    host/tunnel hiccups (measured r4/r5: back-to-back full runs scatter
    ~3%, and the r4 driver capture landed 4% low) — the median of several
    short regions is robust to any single glitch while keeping dispatches
    async *within* each region."""
    rates = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        state, loss = run_once(state)
        # End every timed region with a real host transfer: on experimental
        # backends block_until_ready alone has been observed to return
        # before the dispatch queue drains, inflating throughput ~15x.
        final_loss = float(loss)
        dt = time.perf_counter() - t0
        assert np.isfinite(final_loss), final_loss
        rates.append(units_per_round / dt)
    return sorted(rates)[len(rates) // 2], state


def _baseline_for(model: str) -> float:
    return BASELINE_IMG_PER_SEC_PER_DEVICE * (
        _FWD_GMACS["resnet101"] / _FWD_GMACS[model])

# Analytic FLOPs model: forward GMACs per image × 2 (multiply-accumulate =
# 2 FLOPs — the convention XLA's own cost analysis uses; its estimate for
# the ResNet-50 train step, 23.9 GFLOP/img, matches this model) × 3
# (backward ≈ 2× forward). Lets the JSON line report TFLOP/s and MFU so the
# number is judgeable against the chip's peak, not just a 2017 GPU.
_FWD_GMACS = {"resnet50": 4.09, "resnet101": 7.80, "vgg16": 15.47,
              "inception3": 5.73, "cifar20": 0.041}
TRAIN_GFLOP_PER_IMAGE = {k: 3 * 2 * v for k, v in _FWD_GMACS.items()}

# Peak dense bf16 TFLOP/s per chip by device kind (public specs; the
# denominators for MFU).
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),   # v5e
    ("v6 lite", 918.0),   # v6e / Trillium
    ("v5p", 459.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def _peak_tflops_per_chip():
    if jax.default_backend() != "tpu":
        return None
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


def _peak_bytes_per_chip():
    """Per-chip peak HBM bytes from the runtime's allocator stats, or None
    where the backend keeps none (CPU). Read AFTER the measured region so
    the number covers the train step — it is how the ZeRO memory win
    (opt state ÷ world size) shows up in BENCH_*.json."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — stats are best-effort telemetry
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


# Per-model TPU configs (the reference benchmark family, tf_cnn_benchmarks
# --model {resnet50, resnet101, vgg16, inception3}; docs/benchmarks.md:5-6).
_TPU_CONFIGS = {
    "resnet50": dict(model="resnet50", image=224, batch_per_chip=128,
                     warmup=5, iters=4, classes=1000, steps_per_call=8, rounds=3),
    "resnet101": dict(model="resnet101", image=224, batch_per_chip=96,
                      warmup=5, iters=4, classes=1000, steps_per_call=8, rounds=3),
    # VGG has no BN: classic SGD needs the small-lr recipe or it blows up.
    "vgg16": dict(model="vgg16", image=224, batch_per_chip=96,
                  warmup=5, iters=4, classes=1000, steps_per_call=8,
                  rounds=3, lr=0.01),
    "inception3": dict(model="inception3", image=299, batch_per_chip=96,
                       warmup=5, iters=4, classes=1000, steps_per_call=8, rounds=3),
}


def _bench_config(model: str = "resnet50"):
    smoke = bool(int(os.environ.get("HVD_BENCH_SMOKE", "0")))
    on_tpu = jax.default_backend() == "tpu"
    if smoke or not on_tpu:
        # No scan off-TPU: compiling the scanned step on the virtual CPU
        # mesh costs minutes and there is no dispatch overhead to amortize.
        return dict(model="cifar20", image=64, batch_per_chip=16,
                    warmup=2, iters=5, classes=10, steps_per_call=1)
    # steps_per_call: lax.scan over k steps inside one dispatch — amortizes
    # the per-call host->device dispatch overhead (measured ~4-5 ms on the
    # axon tunnel; worth ~+4% at 50 ms steps) exactly like
    # tf_cnn_benchmarks' in-graph loop over synthetic data.
    return dict(_TPU_CONFIGS[model])


def _build_model(cfg):
    """Benchmark models use local (per-replica) BatchNorm — the reference /
    Goyal configuration; cross-replica BN is opt-in via axis_name."""
    name = cfg["model"]
    # The HVD_FUSED_PARTS sweep (docs/benchmarks.md r5) enters here, at
    # model CONSTRUCTION — as a module attribute it keys the jit cache
    # and is uniform across ranks, which a trace-time env read was not.
    fused_parts = tuple(os.environ.get(
        "HVD_FUSED_PARTS", "reduce,expand,shortcut").split(","))
    if name == "resnet50":
        return models.resnet50(num_classes=cfg["classes"],
                               dtype=jnp.bfloat16,
                               conv_backend=cfg.get("conv_backend", "xla"),
                               fused_parts=fused_parts)
    if name == "resnet101":
        return models.resnet101(num_classes=cfg["classes"],
                                dtype=jnp.bfloat16,
                                conv_backend=cfg.get("conv_backend", "xla"),
                                fused_parts=fused_parts)
    if name == "vgg16":
        return models.vgg16(num_classes=cfg["classes"], dtype=jnp.bfloat16)
    if name == "inception3":
        return models.inception_v3(num_classes=cfg["classes"],
                                   dtype=jnp.bfloat16)
    return models.cifar_resnet_v1(20, dtype=jnp.float32)


def _measure_phases(model, dist_opt, cfg, state, data, accum, rate):
    """Per-phase wall attribution (ISSUE 6 satellite): three compiled
    probes over the same sharded batch — backward only; backward + the
    gradient exchange (the same fused all-reduce or reduce-scatter/
    all-gather round the step takes, same wire/overlap knobs); and the
    full step (derived from the measured rate). The exchange's EXPOSED
    wall time is ``t(exchange) - t(backward)``: when overlap hides the
    collectives behind backward compute it collapses toward zero even
    though the same bytes move — which is exactly what BENCH_r06 needs to
    show, not just img/s. Single-controller only (the env-world exchange
    is host-plane and already measured by its wait times)."""
    from jax.sharding import PartitionSpec as P
    from horovod_tpu.ops import fusion as _f

    if hvd.world().env_world:
        return None
    mesh = hvd.mesh()
    vag = training._build_value_and_grad(
        model, training.cross_entropy_loss, False)
    zero = bool(cfg.get("zero", False))
    wire = cfg.get("wire_dtype")
    overlap = bool(cfg.get("overlap", False))
    rng0 = jax.random.PRNGKey(0)

    def _grads(state, x, y):
        if accum == 1:
            _, g = vag(state.params, state.batch_stats, x, y, rng0)
        else:
            _, _, g, _ = training._accumulate_grads(
                vag, state.params, state.batch_stats, x, y,
                lambda i: jax.random.fold_in(rng0, i), accum, None)
        return g

    def _consume(tree):
        # Sum every inexact leaf: keeps the whole backward (or exchange)
        # live through DCE while returning one scalar to fetch.
        tot = jnp.zeros((), jnp.float32)
        for leaf in jax.tree_util.tree_leaves(tree):
            if jnp.issubdtype(leaf.dtype, jnp.inexact):
                tot = tot + jnp.sum(leaf.astype(jnp.float32))
        return jax.lax.pmean(tot, hvd.AXIS)

    def _bwd_only(state, x, y):
        return _consume(_grads(state, x, y))

    def _bwd_exchange(state, x, y):
        g = _grads(state, x, y)
        if zero:
            plan = state.opt_state.plan
            emit = tuple(range(len(plan.buckets))) if overlap else None
            shards = _f.fused_reduce_scatter(
                g, plan, average=True, wire_dtype=wire, emit_order=emit)
            return _consume(_f.fused_allgather_params(shards, plan))
        return _consume(_f.fused_allreduce(
            g, average=True, wire_dtype=wire, overlap=overlap))

    def _sharded(fn):
        return jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=(P(), P(hvd.AXIS), P(hvd.AXIS)),
            out_specs=P(), check_vma=False))

    times = {}
    for name, fn in (("backward", _sharded(_bwd_only)),
                     ("exchange", _sharded(_bwd_exchange))):
        fn(state, *data).block_until_ready()  # compile + warm
        reps = []
        for _ in range(max(3, int(cfg.get("iters", 3)))):
            t0 = time.perf_counter()
            fn(state, *data).block_until_ready()
            reps.append(time.perf_counter() - t0)
        times[name] = sorted(reps)[len(reps) // 2]

    rows = jax.tree_util.tree_leaves(data)[0].shape[0]
    t_step = rows / rate  # wall per optimizer step, from the headline rate
    t_bwd = times["backward"]
    t_coll = max(0.0, times["exchange"] - t_bwd)
    t_upd = max(0.0, t_step - times["exchange"])
    share = (lambda t: round(min(1.0, t / t_step), 3)) if t_step > 0 \
        else (lambda t: 0.0)
    return {
        "backward_s": round(t_bwd, 6),
        "collective_exposed_s": round(t_coll, 6),
        "update_s": round(t_upd, 6),
        "backward_share": share(t_bwd),
        "collective_share": share(t_coll),
        "update_share": share(t_upd),
    }


def measure(devices=None, cfg=None, want_phases: bool = False):
    """Images/sec of the compiled distributed train step over ``devices``
    (default: all). Returns total (not per-chip) throughput — or
    ``(rate, phases)`` with ``want_phases=True`` (phases is None on
    env-world runs)."""
    cfg = cfg or _bench_config()
    if hvd.is_initialized():
        hvd.shutdown()
    hvd.init(devices=devices)
    n = hvd.size()
    batch = cfg["batch_per_chip"] * n
    image, classes = cfg["image"], cfg["classes"]

    model = _build_model(cfg)

    x_shape = (batch, image, image, 3)
    # Init from a per-chip-sized sample: flax init runs a real forward pass
    # on one device, so a global-batch sample would OOM at pod scale.
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((cfg["batch_per_chip"],) + x_shape[1:], jnp.float32),
        optax.sgd(cfg.get("lr", 0.1), momentum=0.9),
        zero=bool(cfg.get("zero", False)),
        wire_dtype=cfg.get("wire_dtype"))
    accum = int(cfg.get("accum_steps", 1))
    if cfg["batch_per_chip"] % accum:
        raise SystemExit(
            f"--accum-steps {accum} does not divide the per-chip batch "
            f"of {cfg['batch_per_chip']}")
    step = training.make_train_step(
        model, dist_opt, accum_steps=accum,
        overlap=True if cfg.get("overlap") else None)

    # Materialize only local shards (a host-side global batch would be
    # multiple GB at pod scale).
    if hvd.world().env_world:
        # Independent process per chip: build just this rank's slice (the
        # shard_batch split), not the global batch — otherwise every rank
        # trains on all N shards and throughput is over-reported N×.
        r = hvd.rank()
        rng = np.random.RandomState(r)
        local = (cfg["batch_per_chip"],) + x_shape[1:]
        data = (
            jnp.asarray(rng.standard_normal(local).astype(np.float32)),
            jnp.asarray(rng.randint(0, classes,
                                    size=(cfg["batch_per_chip"],))),
        )
        for _ in range(cfg["warmup"]):
            state, metrics = step(state, data)
        float(metrics["loss"])

        def _region(s):
            for _ in range(cfg["iters"]):
                s, m = step(s, data)
            return s, m["loss"]

        rate, _ = _median_rate(_region, state, batch * cfg["iters"],
                               int(cfg.get("rounds", 1)))
        return (rate, None) if want_phases else rate

    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(hvd.mesh(), P(hvd.AXIS))

    def _shard_data(idx):
        rng = np.random.RandomState(hash(str(idx)) % 2**31)
        shape = tuple(s.stop - s.start if s.start is not None else dim
                      for s, dim in zip(idx, x_shape))
        return rng.standard_normal(shape).astype(np.float32)

    def _shard_labels(idx):
        rng = np.random.RandomState(1 + hash(str(idx)) % 2**31)
        rows = idx[0].stop - idx[0].start if idx[0].start is not None \
            else batch
        return rng.randint(0, classes, size=(rows,))

    data = (
        jax.make_array_from_callback(x_shape, sharding, _shard_data),
        jax.make_array_from_callback((batch,), sharding, _shard_labels),
    )

    k = int(cfg.get("steps_per_call", 1))
    if k > 1:
        def _body(s, _):
            s2, m = step(s, data)
            return s2, m["loss"]

        import functools

        # Donate the carried state: the inner step's donation is ignored
        # when traced under this jit, and an undonated TrainState copy
        # (~1 GB for VGG-16) would sit in HBM for the whole dispatch.
        @functools.partial(jax.jit, donate_argnums=0)
        def _multi(s):
            s2, losses = jax.lax.scan(_body, s, None, length=k)
            return s2, losses[-1]

        def run_once(s):
            s2, loss = _multi(s)
            return s2, loss
    else:
        def run_once(s):
            s2, m = step(s, data)
            return s2, m["loss"]

    for _ in range(cfg["warmup"]):
        state, loss = run_once(state)
    float(loss)  # full device->host sync before timing

    def _region(s):
        for _ in range(cfg["iters"]):
            s, loss = run_once(s)
        return s, loss

    rate, state = _median_rate(_region, state, batch * cfg["iters"] * k,
                               int(cfg.get("rounds", 1)))
    if not want_phases:
        return rate
    # Per-step rate (rows of one optimizer step / wall), for the phase
    # denominator — identical to `rate` since units_per_round counts rows.
    phases = _measure_phases(model, dist_opt, cfg, state, data, accum, rate)
    return rate, phases


# ---------------------------------------------------------------------------
# Transformer LM (the second BENCH metric): a matmul-dominated bf16 training
# step — Pallas flash attention, fused QKV, tied bf16 unembed — sized for one
# v5e chip. Where ResNet's MFU is bounded by XLA's conv kernels, this is the
# workload the MXU was built for; the analytic FLOPs model below counts
# matmul FLOPs only (2 per MAC, backward = 2x forward, causal attention at
# half), so MFU is not inflated by remat recompute or elementwise work.
# ---------------------------------------------------------------------------

_LM_TPU = dict(vocab=32768, d_model=2048, n_heads=16, n_layers=8,
               d_ff=8192, seq=2048, batch_per_chip=8,
               warmup=2, iters=6, steps_per_call=2, rounds=3)
_LM_SMOKE = dict(vocab=256, d_model=64, n_heads=2, n_layers=2,
                 d_ff=256, seq=128, batch_per_chip=4,
                 warmup=1, iters=2, steps_per_call=1)


def lm_train_gflop_per_token(c) -> float:
    """Matmul-only FLOPs: per layer fwd = 8·d² (qkv+proj) + 4·d·ff (ffn)
    + 2·T·d (causal QKᵀ+AV, halved) per token; + 2·d·V tied unembed;
    train = 3× forward."""
    d, ff, T, V, L = (c["d_model"], c["d_ff"], c["seq"], c["vocab"],
                      c["n_layers"])
    fwd = L * (8 * d * d + 4 * d * ff + 2 * T * d) + 2 * d * V
    return 3 * fwd / 1e9


def _lm_config():
    smoke = bool(int(os.environ.get("HVD_BENCH_SMOKE", "0")))
    on_tpu = jax.default_backend() == "tpu"
    cfg = dict(_LM_TPU if on_tpu and not smoke else _LM_SMOKE)
    # Experiment knob (docs/benchmarks.md LM experiments table): online
    # chunked cross-entropy instead of the dense [B,T,vocab] softmax.
    chunk = int(os.environ.get("HVD_LM_LOSS_CHUNK", "0"))
    if chunk:
        cfg["loss_chunk"] = chunk
    return cfg


def measure_lm(cfg=None) -> float:
    """Tokens/sec of the compiled transformer-LM train step over all
    visible devices — a pure dp mesh by default, dp×tp with
    ``cfg["tp"] > 1`` (the hybrid plane: Megatron-sharded weights, batch
    over dp; ISSUE 8), or the full 3-D dp×tp×pp mesh with
    ``cfg["pp"] > 1`` (the pipelined family: 1F1B schedule, gradient
    sync interpreted from the unified spec-grouped plan; ISSUE 20).
    Returns total (not per-chip) throughput. Single-controller only: the
    parallel transformer's mesh covers this process's devices, so an
    env-world run would train unsynced local replicas and report a
    meaningless rate."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from horovod_tpu.parallel.mesh import create_hybrid_mesh
    from horovod_tpu.parallel.transformer import (
        TransformerConfig, make_parallel_train_step)

    on_tpu = jax.default_backend() == "tpu"
    cfg = cfg or _lm_config()

    if hvd.is_initialized():
        hvd.shutdown()
    hvd.init()
    if hvd.world().env_world:
        raise SystemExit(
            "the transformer_lm benchmark is single-controller only (run "
            "without tpurun; one process drives all chips)")

    devs = jax.devices()
    n = len(devs)
    tp = int(cfg.get("tp", 1))
    pp = int(cfg.get("pp", 1))
    if tp < 1 or pp < 1 or n % (tp * pp):
        raise SystemExit(
            f"--tp {tp} × --pp {pp} must divide the visible device count "
            f"{n} (the mesh is dp={n}//(tp·pp) × tp × pp)")
    dp = n // (tp * pp)
    want_dp = cfg.get("mesh_dp")
    if want_dp is not None and int(want_dp) != dp:
        raise SystemExit(
            f"--mesh dp={want_dp},tp={tp},pp={pp} does not match the "
            f"visible device count {n} (needs dp×tp×pp == devices; dp "
            f"here is {dp})")
    mesh = create_hybrid_mesh(dp=dp, tp=tp, pp=pp)
    tcfg = TransformerConfig(
        vocab=cfg["vocab"], d_model=cfg["d_model"], n_heads=cfg["n_heads"],
        n_layers=cfg["n_layers"], d_ff=cfg["d_ff"], dtype=jnp.bfloat16,
        attn_backend="pallas" if on_tpu else "xla",
        unembed_dtype=jnp.bfloat16, remat=bool(cfg.get("remat", False)),
        loss_chunk=int(cfg.get("loss_chunk", 0)))
    opt = optax.adamw(1e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    if pp > 1:
        from horovod_tpu.parallel.pp_transformer import (
            make_pp_transformer_train_step)
        if cfg["n_layers"] % pp:
            raise SystemExit(
                f"--pp {pp} must divide n_layers={cfg['n_layers']} (each "
                f"pipeline stage owns n_layers//pp layers)")
        # Accumulation is NATIVE in the pipelined family — microbatches
        # ARE the accumulation, one planned exchange per optimizer step —
        # so --accum-steps sets the microbatch count (min 2: a 1-deep
        # pipeline is all bubble).
        micro = max(2, int(cfg.get("accum_steps", 1)))
        if cfg["batch_per_chip"] % micro:
            raise SystemExit(
                f"batch_per_chip={cfg['batch_per_chip']} must divide into "
                f"--accum-steps {micro} microbatches for the pipelined "
                f"path")
        init_state, step = make_pp_transformer_train_step(
            tcfg, mesh, opt, n_microbatches=micro,
            wire_dtype=cfg.get("wire_dtype"),
            zero=bool(cfg.get("zero", False)),
            overlap=True if cfg.get("overlap") else None)
    else:
        init_state, step = make_parallel_train_step(
            tcfg, mesh, opt, wire_dtype=cfg.get("wire_dtype"),
            zero=bool(cfg.get("zero", False)),
            overlap=True if cfg.get("overlap") else None,
            accum_steps=int(cfg.get("accum_steps", 1)))
    params, opt_state = init_state(jax.random.PRNGKey(0))

    # tp ranks within a dp group replicate the same rows, so the global
    # batch scales with dp, not the chip count.
    B = cfg["batch_per_chip"] * dp
    T = cfg["seq"]
    rng = np.random.RandomState(0)
    sharding = NamedSharding(mesh, P("dp", None))
    tokens = jax.device_put(
        rng.randint(0, cfg["vocab"], size=(B, T)).astype(np.int32),
        sharding)
    labels = jax.device_put(
        rng.randint(0, cfg["vocab"], size=(B, T)).astype(np.int32),
        sharding)

    k = int(cfg.get("steps_per_call", 1))
    if k > 1:
        import functools

        def _body(carry, _):
            p2, o2, loss = step(*carry, tokens, labels)
            return (p2, o2), loss

        @functools.partial(jax.jit, donate_argnums=0)
        def _multi(carry):
            carry, losses = jax.lax.scan(_body, carry, None, length=k)
            return carry, losses[-1]

        def run_once(carry):
            return _multi(carry)
    else:
        def run_once(carry):
            p2, o2, loss = step(*carry, tokens, labels)
            return (p2, o2), loss

    carry = (params, opt_state)
    for _ in range(cfg["warmup"]):
        carry, loss = run_once(carry)
    float(loss)

    def _region(c):
        for _ in range(cfg["iters"]):
            c, loss = run_once(c)
        return c, loss

    rate, _ = _median_rate(_region, carry, B * T * cfg["iters"] * k,
                           int(cfg.get("rounds", 1)))
    return rate


def _mesh_desc(n: int, tp: int, pp: int = 1) -> str:
    dp = n // (max(1, tp) * max(1, pp))
    return (f"dp{dp}" + (f",tp{tp}" if tp > 1 else "")
            + (f",pp{pp}" if pp > 1 else ""))


def lm_line(wire_dtype=None, tp: int = 1, pp: int = 1, zero: bool = False,
            overlap: bool = False, accum_steps: int = 1,
            mesh_dp=None) -> dict:
    from horovod_tpu.ops.fusion import wire_dtype_name
    cfg = _lm_config()
    if wire_dtype:
        cfg["wire_dtype"] = wire_dtype
    cfg["tp"] = tp
    cfg["pp"] = pp
    cfg["zero"] = zero
    cfg["overlap"] = overlap
    cfg["accum_steps"] = accum_steps
    cfg["mesh_dp"] = mesh_dp
    rate = measure_lm(cfg)
    n = hvd.size()
    per_chip = rate / n
    gflop_tok = lm_train_gflop_per_token(cfg)
    # Hardware-ratio baseline, like the conv models: the reference GPU's
    # estimated tokens/sec at this FLOPs cost. With tp the per-chip FLOPs
    # fall by tp (the model is split), so the per-chip token rate is
    # still the apples-to-apples number.
    baseline = BASELINE_IMG_PER_SEC_PER_DEVICE * (
        TRAIN_GFLOP_PER_IMAGE["resnet101"] / gflop_tok)
    line = {
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(per_chip / baseline, 3),
        # per_chip = rate / ALL chips already spreads each token's FLOPs
        # over the tp split — no further /tp, or hybrid MFU reads tp×
        # low vs the tp=1 rows.
        "tflops_per_chip": round(per_chip * gflop_tok / 1e3, 1),
        # Knob provenance (ISSUEs 6+8): since the retarget onto the core
        # stack, the LM rides the same fused-bucket planes as the conv
        # family — every knob applies and is recorded.
        "accum_steps": int(accum_steps),
        "zero": bool(zero),
        "overlap": bool(overlap),
        "wire_dtype": wire_dtype_name(cfg.get("wire_dtype")),
        "tp": int(tp),
        "pp": int(pp),
        # The bench LM carries no experts; the field still appears so a
        # future MoE measurement is distinguishable from these lines.
        "ep": 1,
        "mesh": _mesh_desc(n, tp, pp),
    }
    # The hybrid HBM win (weights + opt state ÷ tp, opt state ÷ dp with
    # --zero) is only claimable if the line carries the number.
    peak_bytes = _peak_bytes_per_chip()
    if peak_bytes is not None:
        line["peak_bytes_per_chip"] = peak_bytes
    peak = _peak_tflops_per_chip()
    if peak:
        line["mfu"] = round(per_chip * gflop_tok / 1e3 / peak, 3)
    return line


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--scaling", action="store_true",
                   help="measure world sizes 1,2,4,... and report "
                        "scaling efficiency per size")
    p.add_argument("--model", default=None,
                   choices=sorted(_TPU_CONFIGS) + ["transformer_lm"],
                   help="benchmark model (default: resnet50 then "
                        "transformer_lm; the conv family mirrors the "
                        "reference's tf_cnn_benchmarks; ignored in "
                        "smoke/CPU mode)")
    p.add_argument("--conv-backend", default=None,
                   choices=["xla", "fused"],
                   help="ResNet conv backend: 'fused' routes the "
                        "bottleneck 1x1 convs through the fused Pallas "
                        "conv+BN+ReLU kernel (ops/pallas_conv.py)")
    p.add_argument("--accum-steps", type=int, default=1,
                   help="in-step gradient accumulation: scan N microbatches "
                        "inside the compiled step, one fused allreduce per "
                        "accumulated step (docs/performance.md); the "
                        "per-chip batch is split, so the global batch per "
                        "optimizer update is unchanged")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO-1 sharded optimizer updates: fused "
                        "reduce-scatter + all-gather instead of the "
                        "all-reduce, optimizer state rank-sharded to "
                        "1/size per chip (docs/performance.md); recorded "
                        "in the JSON line alongside peak_bytes_per_chip "
                        "so the memory win is attributable")
    p.add_argument("--overlap", action="store_true",
                   help="backward-overlapped bucket collectives: per-"
                        "bucket gradient collectives issue in backward-"
                        "completion order behind optimization_barrier "
                        "pins so wire time hides behind backward compute "
                        "(docs/performance.md 'Overlap & wire formats'); "
                        "recorded in every JSON line")
    p.add_argument("--wire-dtype", default=None,
                   choices=["fp32", "bf16", "fp8"],
                   help="low-precision wire format for the gradient "
                        "collectives (fp32 scales, fp32 result "
                        "accumulation; fp8 is e4m3 with per-bucket "
                        "dynamic scaling); recorded in every JSON line")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel axis size for the hybrid dp×tp "
                        "mesh (transformer_lm only: Megatron-sharded "
                        "weights over tp, batch over dp=devices//tp; "
                        "docs/performance.md 'Hybrid dp×tp'); recorded "
                        "in every JSON line alongside 'mesh'")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel axis size for the 3-D dp×tp×pp "
                        "mesh (transformer_lm only: 1F1B schedule, stage-"
                        "owned weights, gradient sync from the unified "
                        "spec-grouped plan; docs/performance.md 'One "
                        "plan, every plane'); recorded in every JSON "
                        "line alongside 'mesh'")
    p.add_argument("--mesh", default=None,
                   help="explicit mesh spec 'dp=N,tp=M,pp=P' (must "
                        "multiply to the visible device count); "
                        "equivalent to --tp M --pp P with a dp sanity "
                        "check")
    args = p.parse_args()
    if args.accum_steps < 1:
        raise SystemExit(f"--accum-steps must be >= 1, got "
                         f"{args.accum_steps}")
    tp = args.tp
    pp = args.pp
    mesh_dp = None
    if args.mesh:
        import re as _re
        sizes = {}
        for part in args.mesh.split(","):
            m = _re.match(r"^\s*(dp|tp|pp)\s*=?\s*(\d+)\s*$", part)
            if not m:
                raise SystemExit(
                    f"--mesh expects 'dp=N,tp=M,pp=P' (got {part!r}); "
                    f"axes beyond dp/tp/pp are "
                    f"examples/transformer_lm.py territory")
            sizes[m.group(1)] = int(m.group(2))
        mtp = sizes.get("tp", 1)
        if tp != 1 and tp != mtp:
            raise SystemExit(
                f"--tp {tp} conflicts with --mesh {args.mesh!r}")
        tp = mtp
        mpp = sizes.get("pp", 1)
        if pp != 1 and pp != mpp:
            raise SystemExit(
                f"--pp {pp} conflicts with --mesh {args.mesh!r}")
        pp = mpp
        mesh_dp = sizes.get("dp")
    if tp < 1:
        raise SystemExit(f"--tp must be >= 1, got {tp}")
    if pp < 1:
        raise SystemExit(f"--pp must be >= 1, got {pp}")
    if args.model == "transformer_lm":
        if args.scaling:
            raise SystemExit(
                "--scaling is not supported for transformer_lm (the conv "
                "family's re-init-with-device-subsets machinery does not "
                "apply); run it without --scaling")
        print(json.dumps(lm_line(
            wire_dtype=args.wire_dtype, tp=tp, pp=pp,
            zero=bool(args.zero), overlap=bool(args.overlap),
            accum_steps=args.accum_steps, mesh_dp=mesh_dp)))
        return
    if tp > 1 or pp > 1:
        raise SystemExit(
            "--tp/--pp/--mesh beyond pure dp applies to --model "
            "transformer_lm (the hybrid and pipelined workloads): the "
            "conv family's flax models are neither tensor-sharded nor "
            "staged — a silent ignore would mislabel a pure-dp run as a "
            "multi-axis measurement")
    cfg = _bench_config(args.model or "resnet50")
    cfg["accum_steps"] = args.accum_steps
    cfg["zero"] = bool(args.zero)
    cfg["overlap"] = bool(args.overlap)
    if args.wire_dtype and args.wire_dtype != "fp32":
        cfg["wire_dtype"] = args.wire_dtype
    if args.conv_backend:
        if (args.model or "resnet50") not in ("resnet50", "resnet101"):
            raise SystemExit(
                "--conv-backend applies to the resnet models only (the "
                "fused kernel targets bottleneck 1x1 convs); a silent "
                "ignore would mislabel a stock run as a fused measurement")
        if cfg["model"] not in ("resnet50", "resnet101"):
            raise SystemExit(
                "--conv-backend has no effect in smoke/CPU mode (the "
                "fallback config swaps the model to cifar20); run on TPU "
                "without HVD_BENCH_SMOKE for a fused measurement")
        cfg["conv_backend"] = args.conv_backend

    from horovod_tpu.ops.fusion import wire_dtype_name

    def _knob_fields():
        return {
            "accum_steps": int(cfg.get("accum_steps", 1)),
            "zero": bool(cfg.get("zero", False)),
            "overlap": bool(cfg.get("overlap", False)),
            "wire_dtype": wire_dtype_name(cfg.get("wire_dtype")),
            # The conv family is pure dp (flax models are neither
            # tensor-sharded nor staged); the fields still appear so
            # every JSON line is mesh-attributable.
            "tp": 1,
            "pp": 1,
            "mesh": _mesh_desc(hvd.size(), 1),
        }

    if args.scaling:
        # Scaling mode is single-controller only: it re-inits the world with
        # device subsets, which is ill-defined when other processes own part
        # of the mesh (jax.distributed) or in tpurun env-worlds.
        from horovod_tpu.utils import config as _hvd_config
        # Probe the ENV, not jax.process_count(): touching the backend here
        # would both defeat the check (count is 1 before distributed init)
        # and block a later jax.distributed initialization.
        if _hvd_config.launcher_size(1) > 1 \
                or os.environ.get("JAX_COORDINATOR_ADDRESS"):
            raise SystemExit(
                "--scaling requires a single-controller world (run without "
                "tpurun/jax.distributed; one process drives all chips)")
        devs = jax.devices()
        sizes = sorted({s for s in (2 ** p for p in range(8))
                        if s <= len(devs)} | {len(devs)})
        rate1 = None
        rate = None
        for n in sizes:
            rate = measure(devices=devs[:n], cfg=cfg)
            if n == 1:
                rate1 = rate
            eff = rate / (n * rate1) if rate1 else float("nan")
            print(json.dumps({
                "metric": f"{cfg['model']}_scaling_efficiency_{n}chips",
                "value": round(eff, 4),
                "unit": "fraction",
                "vs_baseline": round(eff / 0.90, 3),  # ref: 90% @ 128 GPUs
                "images_per_sec_total": round(rate, 2),
                **_knob_fields(),
            }))
        # Also emit the standard absolute metric (full world) so parsers
        # keyed on it always find it.
        per_chip = rate / len(devs)
        line = {
            "metric": f"{cfg['model']}_synthetic_images_per_sec_per_chip",
            "value": round(per_chip, 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(per_chip / _baseline_for(cfg["model"]),
                                 3),
            **_knob_fields(),
        }
        peak_bytes = _peak_bytes_per_chip()
        if peak_bytes is not None:
            line["peak_bytes_per_chip"] = peak_bytes
        print(json.dumps(line))
        return

    rate, phases = measure(cfg=cfg, want_phases=True)
    per_chip = rate / hvd.size()
    line = {
        "metric": f"{cfg['model']}_synthetic_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / _baseline_for(cfg["model"]), 3),
        **_knob_fields(),
    }
    if phases is not None:
        line["phases"] = phases
    peak_bytes = _peak_bytes_per_chip()
    if peak_bytes is not None:
        line["peak_bytes_per_chip"] = peak_bytes
    tflops = per_chip * TRAIN_GFLOP_PER_IMAGE[cfg["model"]] / 1e3
    line["tflops_per_chip"] = round(tflops, 1)
    peak = _peak_tflops_per_chip()
    if peak:
        line["mfu"] = round(tflops / peak, 3)
    print(json.dumps(line), flush=True)

    if args.model is None:
        # Second BENCH metric: the transformer-LM step (matmul-dominated —
        # shows the framework sustains near-peak where the hardware allows).
        if hvd.world().env_world:
            print("skipping transformer_lm line: single-controller only",
                  file=sys.stderr)
        else:
            print(json.dumps(lm_line(wire_dtype=args.wire_dtype,
                                     zero=bool(args.zero),
                                     overlap=bool(args.overlap))),
                  flush=True)


if __name__ == "__main__":
    sys.exit(main())
