"""Input pipeline utilities: dataset loading, per-rank sharding, prefetch.

The reference delegates input to TF's pipelines (its examples feed
feed-dicts or Keras generators; real MNIST/CIFAR arrive via Keras
downloads). A TPU framework needs the equivalent plumbing in-framework:

* :func:`load_dataset` — real arrays from disk when present
  (``HVD_DATA_DIR``/``data_dir`` with ``mnist.npz`` / ``cifar10.npz`` in
  the Keras archive layout), the in-wheel real ``digits`` set (scikit-learn,
  no download needed), or a deterministic learnable synthetic stand-in.
* :func:`shard_iterator` — applies :func:`horovod_tpu.training.shard_batch`
  to every batch (world-axis split in single-controller/jax.distributed
  mode, this rank's contiguous slice in env-world mode).
* :func:`prefetch_to_device` — a bounded background thread that stages the
  next ``size`` sharded batches onto the devices while the current step
  runs, overlapping host input work (decode/augment/transfer) with device
  compute. On TPU this is the difference between MXU-bound and input-bound
  steps.

Typical loop::

    for batch in prefetch_to_device(shard_iterator(host_batches()), 2):
        state, metrics = step(state, batch)
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
from typing import Any, Iterable, Iterator, Optional, Tuple

import numpy as np

from .training import shard_batch


# ---------------------------------------------------------------------------
# Dataset loading (real data when available; synthetic stand-in otherwise).
# ---------------------------------------------------------------------------

def _synthetic(n, shape, classes, seed):
    rng = np.random.RandomState(seed)
    # A learnable task: labels depend linearly on the input so loss
    # actually decreases (pure noise would plateau instantly).
    x = rng.randn(n, *shape).astype(np.float32)
    w = rng.randn(int(np.prod(shape)), classes).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)
    return x, y


def load_dataset(name: str, data_dir: Optional[str] = None,
                 n_train: int = 4096, n_test: int = 512) -> Tuple[
                     Tuple[np.ndarray, np.ndarray],
                     Tuple[np.ndarray, np.ndarray], dict]:
    """Load ``name`` in {"mnist", "cifar10", "digits"}.

    Returns ``((x_train, y_train), (x_test, y_test), info)`` with
    ``info = {"real": bool, "classes": int}``. Real data is used when
    available: ``<data_dir or $HVD_DATA_DIR>/<name>.npz`` in the Keras
    archive layout (x_train/y_train/x_test/y_test) for mnist/cifar10;
    ``digits`` is scikit-learn's real 8x8 handwritten-digit set shipped in
    the wheel (1,797 images — usable for convergence validation with zero
    network egress). Without real data, a deterministic learnable
    synthetic stand-in with the same shapes is returned (``real: False``)
    so examples still demonstrate the framework end to end (the part the
    reference's downloads provided).
    """
    d = data_dir or os.environ.get("HVD_DATA_DIR")
    info = {"real": False, "classes": 10}
    if name == "digits":
        try:
            from sklearn.datasets import load_digits
        except ImportError as e:  # optional dependency (extras: datasets)
            raise ImportError(
                "load_dataset('digits') needs scikit-learn (the real 8x8 "
                "digit images ship inside its wheel): pip install "
                "scikit-learn, or pip install horovod_tpu[datasets]"
            ) from e
        x, y = load_digits(return_X_y=True)
        x = (x.astype(np.float32) / 16.0).reshape(-1, 8, 8, 1)
        y = y.astype(np.int32)
        # Deterministic shuffle + 80/20 split (the set ships unshuffled,
        # grouped by writer).
        idx = np.random.RandomState(0).permutation(len(x))
        x, y = x[idx], y[idx]
        n = int(0.8 * len(x))
        info["real"] = True
        return (x[:n], y[:n]), (x[n:], y[n:]), info
    # npz datasets: name -> (x transform, synthetic stand-in shape).
    _npz = {
        "mnist": (lambda x: x.reshape(-1, 784), (784,)),
        "cifar10": (lambda x: x, (32, 32, 3)),
    }
    if name not in _npz:
        raise ValueError(f"unknown dataset {name!r} "
                         "(expected mnist/cifar10/digits)")
    x_tf, syn_shape = _npz[name]
    path = d and os.path.join(d, f"{name}.npz")
    if path and os.path.exists(path):
        with np.load(path) as f:
            info["real"] = True
            return ((x_tf(f["x_train"]).astype(np.float32) / 255.0,
                     f["y_train"].astype(np.int32).ravel()),
                    (x_tf(f["x_test"]).astype(np.float32) / 255.0,
                     f["y_test"].astype(np.int32).ravel()), info)
    return (_synthetic(n_train, syn_shape, 10, 0),
            _synthetic(n_test, syn_shape, 10, 1), info)


def shard_iterator(batches: Iterable, mesh: Optional[Any] = None) -> Iterator:
    """Yield each global host batch placed onto the world (leading axis
    split across ranks; see :func:`horovod_tpu.training.shard_batch`)."""
    for batch in batches:
        yield shard_batch(batch, mesh=mesh)


class _Sentinel:
    pass


_END = _Sentinel()


def prefetch_to_device(batches: Iterable, size: int = 2,
                       sharding: Optional[Any] = None,
                       timeline: Optional[Any] = None) -> Iterator:
    """Iterate ``batches`` with a background thread staying ``size`` batches
    ahead. Exceptions in the source iterator re-raise at the consuming
    ``next()`` call. Abandoning the iterator early (a ``break``, a
    stop-at-step hook) stops the worker, releases its staged batches, and
    closes the source iterator — no thread or device memory outlives the
    consumer.

    ``sharding`` places each batch from the WORKER thread: pass a single
    ``NamedSharding`` (applied to every leaf — e.g. the world mesh's
    leading-axis split) or a pytree of shardings matching the batch. This
    is what makes the prefetch depth actually overlap H2D for sharded
    meshes — without it the source must yield already-placed batches, and
    a source built on a default single-device ``device_put`` serializes
    the transfer into the consuming ``next()``. Each placement is recorded
    as an ``H2D`` timeline phase (``timeline`` defaults to the runtime's
    writer) so a trace can attribute input-bound vs compute-bound steps.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    return _prefetch_gen(batches, size, sharding, timeline)


# Timeline-row pool: concurrent streams (train + eval) need DISTINCT rows
# so B/E events don't interleave, but sequential streams (one per epoch)
# reuse freed ids — otherwise a long run grows one single-use Chrome-trace
# pseudo-process (and Timeline dict entry) per epoch without bound.
_h2d_rows = itertools.count()
_h2d_free: list = []
_h2d_lock = threading.Lock()


def _prefetch_gen(batches: Iterable, size: int,
                  sharding: Optional[Any] = None,
                  timeline: Optional[Any] = None) -> Iterator:
    import jax

    from . import runtime
    from .utils import timeline as _tl

    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()

    if timeline is None and runtime.is_initialized():
        timeline = runtime.world().timeline
    with _h2d_lock:
        row_id = _h2d_free.pop() if _h2d_free else next(_h2d_rows)
    row = f"input.h2d.{row_id}"

    def _place(b):
        if sharding is None:
            return b
        with _tl.maybe_op(timeline, row, _tl.H2D):
            if isinstance(sharding, jax.sharding.Sharding):
                placed = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sharding), b)
            else:
                placed = jax.device_put(b, sharding)
            # Block HERE, on the worker thread: device_put only dispatches
            # the copy, so without this the H2D phase measures dispatch
            # (~0) and the input-bound attribution under-reports — and a
            # dequeued batch must already be device-resident for the
            # prefetch depth to mean completed transfers.
            jax.block_until_ready(placed)
        return placed

    def _put(item) -> bool:
        # Bounded put with a stop check: the consumer may vanish while the
        # queue is full; never block forever on any worker-side put.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _fill():
        try:
            for b in batches:
                if not _put(_place(b)):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            _put(e)
            return
        _put(_END)

    t = threading.Thread(target=_fill, daemon=True)
    t.start()

    try:
        while True:
            item = q.get()
            if isinstance(item, _Sentinel):
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # Unblock a worker stuck in put() and drop staged batches.
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5)
        if not t.is_alive():
            # Recycle the timeline row only once the worker can no longer
            # emit on it (a wedged worker leaks its id — safe, just wider).
            with _h2d_lock:
                _h2d_free.append(row_id)
        close = getattr(batches, "close", None)
        if close is not None:
            close()
