"""Input pipeline utilities: per-rank sharding + background device prefetch.

The reference delegates input to TF's pipelines (its examples feed
feed-dicts or Keras generators); a TPU framework needs the equivalent
plumbing in-framework: the chip must never wait on the host. These helpers
wrap any Python iterable of host batches:

* :func:`shard_iterator` — applies :func:`horovod_tpu.training.shard_batch`
  to every batch (world-axis split in single-controller/jax.distributed
  mode, this rank's contiguous slice in env-world mode).
* :func:`prefetch_to_device` — a bounded background thread that stages the
  next ``size`` sharded batches onto the devices while the current step
  runs, overlapping host input work (decode/augment/transfer) with device
  compute. On TPU this is the difference between MXU-bound and input-bound
  steps.

Typical loop::

    for batch in prefetch_to_device(shard_iterator(host_batches()), 2):
        state, metrics = step(state, batch)
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

from .training import shard_batch


def shard_iterator(batches: Iterable, mesh: Optional[Any] = None) -> Iterator:
    """Yield each global host batch placed onto the world (leading axis
    split across ranks; see :func:`horovod_tpu.training.shard_batch`)."""
    for batch in batches:
        yield shard_batch(batch, mesh=mesh)


class _Sentinel:
    pass


_END = _Sentinel()


def prefetch_to_device(batches: Iterable, size: int = 2) -> Iterator:
    """Iterate ``batches`` with a background thread staying ``size`` batches
    ahead. Exceptions in the source iterator re-raise at the consuming
    ``next()`` call. Abandoning the iterator early (a ``break``, a
    stop-at-step hook) stops the worker, releases its staged batches, and
    closes the source iterator — no thread or device memory outlives the
    consumer.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    return _prefetch_gen(batches, size)


def _prefetch_gen(batches: Iterable, size: int) -> Iterator:
    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()

    def _put(item) -> bool:
        # Bounded put with a stop check: the consumer may vanish while the
        # queue is full; never block forever on any worker-side put.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _fill():
        try:
            for b in batches:
                if not _put(b):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            _put(e)
            return
        _put(_END)

    t = threading.Thread(target=_fill, daemon=True)
    t.start()

    try:
        while True:
            item = q.get()
            if isinstance(item, _Sentinel):
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # Unblock a worker stuck in put() and drop staged batches.
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join(timeout=5)
        close = getattr(batches, "close", None)
        if close is not None:
            close()
