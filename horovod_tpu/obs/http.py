"""Per-rank metrics HTTP listener: ``GET /metrics`` in Prometheus text.

Training jobs expose nothing while running — the serving plane has
``/stats`` but a training rank's only live signal is stdout. This
listener gives every rank a scrape endpoint:

* ``HVD_METRICS_PORT=<base>`` — rank *r* listens on ``base + r`` (one
  process per rank in a tpurun env-world; the single-controller process
  is rank 0). ``0``/unset disables. Started by ``runtime.init()``,
  stopped by ``runtime.shutdown()`` — a live resize that re-forms the
  world restarts it on the (same) rank port.
* ``HVD_METRICS_HOST`` — bind address (default ``0.0.0.0`` so a fleet
  scraper on another host can reach it; the port is read-only text).

The handler renders the process-default registry
(:func:`horovod_tpu.obs.registry`) with a ``rank`` const label, so the
``tpurun --metrics-summary`` poller can aggregate one fleet view
without per-rank relabeling config.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from .registry import registry as _default_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    render: Callable[[], str] = None     # installed by MetricsListener

    def log_message(self, *a):  # scrapes are not log-worthy
        pass

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path in ("/metrics", ""):
            try:
                body = type(self).render().encode()
            except Exception as e:  # noqa: BLE001 — scrape must not 500-loop
                self.send_response(500)
                self.end_headers()
                self.wfile.write(f"render failed: {e!r}".encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self.send_response(404)
            self.end_headers()


class MetricsListener:
    """Serve a render callback over HTTP on a background thread.
    ``port=0`` binds an ephemeral port (read ``.port`` back) — the
    test-friendly default."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 render: Optional[Callable[[], str]] = None):
        if render is None:
            render = _default_registry().render
        handler = type("BoundMetricsHandler", (_Handler,),
                       {"render": staticmethod(render)})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"hvd-metrics-{self.port}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def start_from_env(rank: int) -> Optional[MetricsListener]:
    """Start the per-rank listener if ``HVD_METRICS_PORT`` asks for one
    (port = base + rank; 0/unset disables). A bind failure warns and
    returns None — metrics must never kill training."""
    import os
    import warnings
    from ..utils import config as _config
    base = _config.metrics_port()
    if not base:
        return None
    host = os.environ.get("HVD_METRICS_HOST") or "0.0.0.0"
    port = base + int(rank)

    def _render():
        return _default_registry().render(
            const_labels={"rank": str(rank)})

    try:
        return MetricsListener(port, host, render=_render)
    except OSError as e:
        warnings.warn(
            f"metrics listener could not bind {host}:{port} ({e}); "
            f"rank {rank} runs without a /metrics endpoint")
        return None
