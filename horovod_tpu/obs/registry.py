"""Host-side metrics registry with Prometheus text exposition.

One registry serves BOTH planes of this framework: the training loop
(per-step wall time, samples/sec, bad-step and recovery counters) and
the serving engines (request/TTFT latency, block-pool gauges). Until
now every subsystem grew a one-off signal — chrome-trace timelines,
JSON ``/stats`` reservoirs, heartbeat liveness, bench.py phase blocks —
and nothing was scrapeable by a standard collector. The exposition
format here is Prometheus text format 0.0.4, the lowest common
denominator every metrics stack ingests, so ``curl :PORT/metrics``
works against a training rank exactly as it does against a serving
engine.

Design constraints (why this is ~200 lines and not a client_golang
port):

* **Lock-light hot path.** A counter ``inc()`` is one short critical
  section on the child's own lock (never a registry-wide lock), so N
  instrumented threads never serialize against each other except on the
  same series. Python's GIL makes the reads cheap; the per-child lock
  exists because ``+=`` on a float is NOT atomic across bytecode
  boundaries and torn counters are worse than none.
* **Fixed histogram bounds.** Buckets are chosen at metric creation and
  never re-bucketed — cumulative bucket counts are monotone, which is
  what makes rate()/histogram_quantile() correct on the scraper side.
* **Stable names are an API** (``docs/observability.md`` holds the
  inventory): dashboards and the ``tpurun --metrics-summary`` fleet
  poller key on them.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default histogram bounds: latency-shaped, 1 ms .. 60 s. Wide enough
# for a TPU train step (ms..s) and a generation TTFT under load.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# A sample is (name, labels-dict, value) — the unit the renderer groups.
Sample = Tuple[str, Dict[str, str], float]
# Metadata: name -> (type, help).
Meta = Dict[str, Tuple[str, str]]


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline
    (text format 0.0.4 — the three characters that would corrupt the
    line grammar)."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f != f:
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def render(meta: Meta, samples: Iterable[Sample]) -> str:
    """Render samples as exposition text, GROUPED by metric name (the
    format requires all lines of one metric to form a single block, with
    at most one ``# TYPE`` — the reason merging two engines' metrics
    cannot be plain string concatenation)."""
    by_name: Dict[str, List[Sample]] = {}
    order: List[str] = []
    for s in samples:
        base = s[0]
        # Histogram series group under the base metric name.
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[:-len(suffix)] in meta:
                base = base[:-len(suffix)]
                break
        if base not in by_name:
            by_name[base] = []
            order.append(base)
        by_name[base].append(s)
    out: List[str] = []
    for base in order:
        typ, help_ = meta.get(base, ("untyped", ""))
        if help_:
            out.append(f"# HELP {base} {help_}")
        out.append(f"# TYPE {base} {typ}")
        for name, labels, value in by_name[base]:
            out.append(f"{name}{_fmt_labels(labels)} {_fmt_value(value)}")
    return "\n".join(out) + ("\n" if out else "")


def parse_exposition(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                         ...]], float]:
    """Parse exposition text back into ``{(name, sorted-label-items):
    value}`` — the scraper half used by :mod:`.summary` (the fleet
    poller) and by tests asserting golden lines survive a round trip.
    Tolerant: unknown/comment lines are skipped, not errors."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{(.*)\})?\s+(\S+)$", line)
        if not m:
            continue
        name, labelstr, value = m.groups()
        labels: Dict[str, str] = {}
        if labelstr:
            for lm in re.finditer(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                    labelstr):
                # Single-pass unescape: sequential str.replace would
                # consume the 'n' of an escaped backslash followed by n
                # ("\\n" must parse as backslash+n, not newline).
                labels[lm.group(1)] = re.sub(
                    r"\\(.)",
                    lambda m: "\n" if m.group(1) == "n" else m.group(1),
                    lm.group(2))
        try:
            if value == "+Inf":
                v = float("inf")
            elif value == "-Inf":
                v = float("-inf")
            else:
                v = float(value)
        except ValueError:
            continue
        out[(name, tuple(sorted(labels.items())))] = v
    return out


class _Child:
    """One concrete series (a metric bound to one label-value set)."""

    def __init__(self):
        self._lock = threading.Lock()


class Counter(_Child):
    """Monotone counter. ``inc(n)`` with n >= 0 only — a counter that
    goes down lies to every rate() on the scraper side."""

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Child):
    """Settable instantaneous value."""

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Child):
    """Fixed-bound cumulative histogram (the Prometheus shape:
    ``_bucket{le=}`` counts are cumulative, plus ``_sum``/``_count``)."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__()
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b != b or b == float("inf") for b in bounds):
            raise ValueError(f"bucket bounds must be finite, got {bounds}")
        self.bounds = bounds
        self._counts = [0] * len(bounds)
        self._inf = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._inf += 1
            # Linear scan: bucket lists here are ~15 long and observe()
            # sits on host paths measured in ms, not ns.
            # _counts are per-bucket (non-cumulative) internally;
            # snapshot() cumulates, so one observation lands in exactly
            # one slot here.
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    break

    def snapshot(self) -> Tuple[Tuple[Tuple[float, int], ...], float, int]:
        """(cumulative (bound, count) pairs, sum, total count)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._inf, self._sum
        cum = 0
        out = []
        for b, c in zip(self.bounds, counts):
            cum += c
            out.append((b, cum))
        return tuple(out), s, total

    @property
    def count(self) -> int:
        with self._lock:
            return self._inf

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Metric:
    """A named metric family: the child itself when unlabeled, or a
    lazily-populated ``labels()`` map of children."""

    def __init__(self, name: str, help_: str, kind: str,
                 label_names: Tuple[str, ...], **kw):
        self.name = name
        self.help = help_
        self.kind = kind
        self.label_names = label_names
        self._kw = kw
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Child] = {}
        if not label_names:
            self._children[()] = _KINDS[kind](**kw)

    def labels(self, **labels) -> _Child:
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"metric {self.name} takes labels {self.label_names}, "
                f"got {tuple(labels)}")
        key = tuple(str(labels[k]) for k in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key,
                                                  _KINDS[self.kind](
                                                      **self._kw))
        return child

    def remove(self, **labels) -> None:
        """Drop one child series (idempotent). For label sets that churn
        over a process lifetime — e.g. a serving fleet's retired replica
        names — unbounded children are a slow leak in memory AND in the
        exposition; scrapers treat the disappearance as a normal series
        termination."""
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"metric {self.name} takes labels {self.label_names}, "
                f"got {tuple(labels)}")
        key = tuple(str(labels[k]) for k in self.label_names)
        with self._lock:
            self._children.pop(key, None)

    # Unlabeled convenience: the family IS its single child.
    def _only(self) -> _Child:
        if self.label_names:
            raise ValueError(
                f"metric {self.name} is labeled {self.label_names}; "
                f"use .labels(...)")
        return self._children[()]

    def inc(self, n: float = 1.0) -> None:
        self._only().inc(n)          # type: ignore[attr-defined]

    def set(self, v: float) -> None:
        self._only().set(v)          # type: ignore[attr-defined]

    def dec(self, n: float = 1.0) -> None:
        self._only().dec(n)          # type: ignore[attr-defined]

    def observe(self, v: float) -> None:
        self._only().observe(v)      # type: ignore[attr-defined]

    @property
    def value(self) -> float:
        return self._only().value    # type: ignore[attr-defined]

    @property
    def count(self) -> int:
        return self._only().count    # type: ignore[attr-defined]

    @property
    def sum(self) -> float:
        return self._only().sum      # type: ignore[attr-defined]

    def snapshot(self):
        return self._only().snapshot()  # type: ignore[attr-defined]

    def collect(self, const_labels: Optional[Dict[str, str]] = None
                ) -> List[Sample]:
        const = dict(const_labels or {})
        with self._lock:
            children = list(self._children.items())
        out: List[Sample] = []
        for key, child in children:
            labels = dict(const)
            labels.update(zip(self.label_names, key))
            if self.kind == "histogram":
                cum, s, total = child.snapshot()  # type: ignore
                for bound, c in cum:
                    bl = dict(labels)
                    bl["le"] = _fmt_value(bound)
                    out.append((self.name + "_bucket", bl, c))
                il = dict(labels)
                il["le"] = "+Inf"
                out.append((self.name + "_bucket", il, total))
                out.append((self.name + "_sum", labels, s))
                out.append((self.name + "_count", dict(labels), total))
            else:
                out.append((self.name, labels,
                            child.value))  # type: ignore[attr-defined]
        return out


class MetricsRegistry:
    """A set of named metrics with one exposition renderer.

    Creation is idempotent (``counter(name)`` returns the existing
    family) so call sites register at first use without an init-order
    protocol; re-registering under a DIFFERENT kind raises — two
    subsystems fighting over one name is a bug, not a merge.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, name: str, help_: str, kind: str,
             labels: Sequence[str] = (), **kw) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labels:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric {name} already registered as {m.kind}, "
                        f"cannot re-register as {kind}")
                if kind == "histogram" and m._kw != kw:
                    # Same discipline as the kind conflict: silently
                    # keeping the first registration's bounds would hand
                    # the caller buckets they never asked for.
                    raise ValueError(
                        f"histogram {name} already registered with "
                        f"buckets {m._kw.get('buckets')}, cannot "
                        f"re-register with {kw.get('buckets')}")
                return m
            m = _Metric(name, help_, kind, tuple(labels), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> _Metric:
        return self._get(name, help_, "counter", labels)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> _Metric:
        return self._get(name, help_, "gauge", labels)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Metric:
        # Normalized bounds so list-vs-tuple spellings of the same
        # buckets compare equal in the re-registration check.
        return self._get(name, help_, "histogram", labels,
                         buckets=tuple(sorted(float(b) for b in buckets)))

    def collect(self, const_labels: Optional[Dict[str, str]] = None
                ) -> Tuple[Meta, List[Sample]]:
        with self._lock:
            metrics = list(self._metrics.values())
        meta: Meta = {}
        samples: List[Sample] = []
        for m in metrics:
            meta[m.name] = (m.kind, m.help)
            samples.extend(m.collect(const_labels))
        return meta, samples

    def render(self, const_labels: Optional[Dict[str, str]] = None) -> str:
        meta, samples = self.collect(const_labels)
        return render(meta, samples)


# ---------------------------------------------------------------------------
# The process-default registry: the training plane's shared surface
# (trainer, elastic, runtime, env-world collectives all register here;
# the per-rank HTTP listener renders it). Serving engines deliberately
# use PRIVATE registries — two engines in one process must not collide.
# ---------------------------------------------------------------------------

_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _default
