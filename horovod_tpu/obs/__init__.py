"""One telemetry plane for training and serving.

Three pieces, one surface (``docs/observability.md`` holds the metric
name inventory and scrape recipes):

* :mod:`.registry` — lock-light counters/gauges/histograms with
  Prometheus text exposition; the process-default registry is the
  training plane's shared namespace.
* :mod:`.http` — the per-rank ``GET /metrics`` listener
  (``HVD_METRICS_PORT``, port + rank, 0 disables), started by
  ``runtime.init()``. The serving plane exposes the same format on the
  existing :class:`~horovod_tpu.serve.server.HttpServer` (``/metrics``
  next to ``/stats``).
* :mod:`.flightrec` — the crash-safe flight recorder: a bounded ring of
  recent structured events dumped to ``hvd_flightrec.rank{N}.json``
  when a rank dies badly, so a post-mortem names the final step without
  grepping stdout.

:mod:`.summary` aggregates the per-rank endpoints into the
``tpurun --metrics-summary`` fleet line.
"""

from . import flightrec  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .http import MetricsListener  # noqa: F401
from .registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
    parse_exposition,
    registry,
    render,
)
from .summary import FleetPoller, scrape  # noqa: F401
