"""Fleet view: scrape every rank's ``/metrics`` and print ONE line.

The PR-9 supervisor (``tpurun``) watches exit codes and resize status —
it has no idea whether the job it babysits is training at speed,
crawling, or skipping every step on NaNs. ``tpurun --metrics-summary``
turns the per-rank listeners (:mod:`.http`) into that missing fleet
view: scrape ``base_port + r`` for every rank, aggregate, one line.

Aggregation rules (per series NAME, labels ignored — each rank's
registry carries its own ``rank`` const label):

* counters (``*_total``) sum across ranks — fleet throughput;
* ``hvd_global_step`` reports min/max — a spread is a straggler;
* everything is cumulative, so the poller keeps the previous sample and
  prints rates (steps/s, samples/s, tokens/s) from the delta.
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from .registry import parse_exposition


def scrape(host: str, port: int, timeout: float = 2.0) -> Optional[Dict]:
    """One rank's parsed ``/metrics`` (series-name → summed value), or
    None when unreachable (a dead/not-yet-up rank is a datum, not an
    error)."""
    url = f"http://{host}:{port}/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            text = resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError):
        return None
    out: Dict[str, float] = {}
    for (name, _labels), v in parse_exposition(text).items():
        out[name] = out.get(name, 0.0) + v
    return out


class FleetPoller:
    """Stateful aggregator: each :meth:`line` call scrapes all ranks and
    renders one operator-facing summary, with rates computed against the
    previous poll."""

    def __init__(self, host: str, base_port: int, world: int,
                 timeout: float = 2.0, ranks=None):
        """``ranks``: the rank indices to scrape (default all of
        ``range(world)``). A multi-host launcher passes its LOCAL rank
        block — remote ranks' listeners live on other machines, and
        polling them on this host's loopback would report a healthy job
        as permanently degraded."""
        self.host = host
        self.base_port = int(base_port)
        self.world = int(world)
        self.timeout = timeout
        self._ranks = None if ranks is None else list(ranks)
        self._prev: Optional[Dict[str, float]] = None
        self._prev_t: Optional[float] = None

    def set_world(self, world: int) -> None:
        """Live resize moved the world size; later polls scrape the new
        rank set (explicit ``ranks`` clamp to it)."""
        self.world = int(world)

    def ranks(self) -> List[int]:
        if self._ranks is None:
            return list(range(self.world))
        return [r for r in self._ranks if r < self.world]

    def sample(self) -> List[Optional[Dict]]:
        return [scrape(self.host, self.base_port + r, self.timeout)
                for r in self.ranks()]

    def line(self) -> str:
        samples = self.sample()
        now = time.monotonic()
        up = [s for s in samples if s is not None]
        totals: Dict[str, float] = {}
        for s in up:
            for k, v in s.items():
                totals[k] = totals.get(k, 0.0) + v
        steps = [s.get("hvd_global_step") for s in up
                 if s.get("hvd_global_step") is not None]
        n_polled = len(samples)
        scope = ("" if self._ranks is None or n_polled == self.world
                 else " (this node)")
        parts = [f"fleet: {len(up)}/{n_polled} ranks up{scope}"]
        if steps:
            lo, hi = int(min(steps)), int(max(steps))
            parts.append(f"step {lo}" if lo == hi
                         else f"step {lo}..{hi} (straggler spread "
                              f"{hi - lo})")
        if self._prev is not None and self._prev_t is not None:
            dt = max(1e-9, now - self._prev_t)
            for key, label in (("hvd_steps_total", "steps/s"),
                               ("hvd_samples_total", "samples/s"),
                               ("hvd_tokens_generated_total", "tokens/s")):
                if key in totals:
                    rate = (totals[key] - self._prev.get(key, 0.0)) / dt
                    parts.append(f"{label} {max(0.0, rate):.1f}")
        for key, label in (("hvd_bad_steps_total", "bad_steps"),
                           ("hvd_commits_total", "commits"),
                           ("hvd_restores_total", "restores"),
                           ("hvd_resizes_total", "resizes")):
            if key in totals:
                parts.append(f"{label} {int(totals[key])}")
        self._prev, self._prev_t = totals, now
        return " | ".join(parts)
