"""Fleet view: scrape every rank's ``/metrics`` and print ONE line.

The PR-9 supervisor (``tpurun``) watches exit codes and resize status —
it has no idea whether the job it babysits is training at speed,
crawling, or skipping every step on NaNs. ``tpurun --metrics-summary``
turns the per-rank listeners (:mod:`.http`) into that missing fleet
view: scrape ``base_port + r`` for every rank, aggregate, one line.

Aggregation rules (per series NAME, labels ignored — each rank's
registry carries its own ``rank`` const label):

* counters (``*_total``) sum across ranks — fleet throughput;
* ``hvd_global_step`` reports min/max — a spread is a straggler;
* everything is cumulative, so the poller keeps the previous sample and
  prints rates (steps/s, samples/s, tokens/s) from the delta.

The poller also speaks *serving*: pointed at a
:class:`~horovod_tpu.serve.router.FleetRouter`'s ``/metrics`` (``tpurun
-np 1 --metrics-summary --metrics-port <serving port>``), the scrape
carries ``hvd_fleet_replicas`` and the line flips to the replica-centric
summary — ``fleet: K/N replicas ready | depth=… | ttft_p50<=…ms`` —
with the TTFT quantile estimated from the fleet-summed
``hvd_generate_ttft_seconds`` histogram buckets.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from .registry import parse_exposition


def scrape_exposition(host: str, port: int,
                      timeout: float = 2.0) -> Optional[Dict]:
    """One endpoint's fully parsed ``/metrics``
    (``{(name, sorted-label-items): value}``), or None when
    unreachable. The label-preserving form — the serving-fleet summary
    needs the ``state=`` / ``le=`` breakdowns that name-summing
    destroys."""
    url = f"http://{host}:{port}/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            text = resp.read().decode("utf-8", "replace")
    except (urllib.error.URLError, OSError, ValueError):
        return None
    return parse_exposition(text)


def scrape(host: str, port: int, timeout: float = 2.0) -> Optional[Dict]:
    """One rank's parsed ``/metrics`` (series-name → summed value), or
    None when unreachable (a dead/not-yet-up rank is a datum, not an
    error)."""
    parsed = scrape_exposition(host, port, timeout)
    if parsed is None:
        return None
    out: Dict[str, float] = {}
    for (name, _labels), v in parsed.items():
        out[name] = out.get(name, 0.0) + v
    return out


class FleetPoller:
    """Stateful aggregator: each :meth:`line` call scrapes all ranks and
    renders one operator-facing summary, with rates computed against the
    previous poll."""

    def __init__(self, host: str, base_port: int, world: int,
                 timeout: float = 2.0, ranks=None):
        """``ranks``: the rank indices to scrape (default all of
        ``range(world)``). A multi-host launcher passes its LOCAL rank
        block — remote ranks' listeners live on other machines, and
        polling them on this host's loopback would report a healthy job
        as permanently degraded."""
        self.host = host
        self.base_port = int(base_port)
        self.world = int(world)
        self.timeout = timeout
        self._ranks = None if ranks is None else list(ranks)
        self._prev: Optional[Dict[str, float]] = None
        self._prev_t: Optional[float] = None
        # The labeled parses behind the last sample() — kept so the
        # serving-mode line reuses ONE scrape per poll instead of
        # re-fetching every endpoint (None when sample() was shimmed).
        self._last_labeled: Optional[List[Optional[Dict]]] = None
        # Structured verdict of the last line() — what the one-shot CLI
        # keys its exit code on (never parse the prose back).
        self.last_mode: Optional[str] = None      # "training"|"serving"
        self.last_up: int = 0                     # endpoints that answered

    def set_world(self, world: int) -> None:
        """Live resize moved the world size; later polls scrape the new
        rank set (explicit ``ranks`` clamp to it)."""
        self.world = int(world)

    def ranks(self) -> List[int]:
        if self._ranks is None:
            return list(range(self.world))
        return [r for r in self._ranks if r < self.world]

    def sample(self) -> List[Optional[Dict]]:
        self._last_labeled = [
            scrape_exposition(self.host, self.base_port + r, self.timeout)
            for r in self.ranks()]
        out: List[Optional[Dict]] = []
        for parsed in self._last_labeled:
            if parsed is None:
                out.append(None)
                continue
            summed: Dict[str, float] = {}
            for (name, _labels), v in parsed.items():
                summed[name] = summed.get(name, 0.0) + v
            out.append(summed)
        return out

    def _advertised_endpoints(self) -> List[Tuple[str, int]]:
        """Subprocess-replica ``/metrics`` endpoints advertised by the
        scraped router's ``/healthz`` ``replica_metrics`` breakdown
        (``{name: "host:port"}``) — a subprocess fleet's samples live at
        the CHILDREN's endpoints (the router deliberately does not relay
        them), so a summary that only reads the router's port would
        report a process fleet as generating nothing. A not-ready
        ``/healthz`` answers 503 with the same JSON body; read it
        through the HTTPError. Deduplicated, order-stable."""
        seen: Dict[Tuple[str, int], None] = {}
        for r in self.ranks():
            url = f"http://{self.host}:{self.base_port + r}/healthz"
            try:
                with urllib.request.urlopen(url,
                                            timeout=self.timeout) as resp:
                    text = resp.read()
            except urllib.error.HTTPError as e:
                try:
                    text = e.read()
                except (OSError, ValueError):
                    continue
            except (urllib.error.URLError, OSError, ValueError):
                continue
            try:
                body = json.loads(text.decode("utf-8", "replace"))
            except ValueError:
                continue
            for ep in (body.get("replica_metrics") or {}).values():
                host, _, port = str(ep).rpartition(":")
                try:
                    seen.setdefault((host or self.host, int(port)), None)
                except ValueError:
                    continue
        return list(seen)

    def _serving_line(self, now: float, totals: Dict[str, float]) -> str:
        """The serving-fleet flavor of :meth:`line`: a scrape that
        carries ``hvd_fleet_replicas`` is a :class:`~horovod_tpu.serve.
        router.FleetRouter` endpoint, not a training rank — summarize
        replicas/depth/TTFT instead of steps. TTFT p50 comes from the
        fleet-summed ``hvd_generate_ttft_seconds`` histogram (cumulative
        bucket counts sum across replicas, so the quantile estimate is
        fleet-wide — the thing per-replica reservoirs can never give).
        Reuses the labeled parses the triggering :meth:`sample` already
        fetched — one scrape per endpoint per poll (the fallback
        re-fetch only fires when sample() was replaced by a shim)."""
        labeled = self._last_labeled
        if labeled is None:
            labeled = [scrape_exposition(self.host, self.base_port + r,
                                         self.timeout)
                       for r in self.ranks()]
        merged: Dict = {}
        for parsed in labeled:
            for key, v in (parsed or {}).items():
                merged[key] = merged.get(key, 0.0) + v
        # Subprocess fleets: walk each child endpoint the router's
        # /healthz advertises — ONE scrape per endpoint per poll (the
        # PR-14 rule), folded into BOTH views of this poll (`merged`
        # feeds the labeled breakdowns, `totals` feeds the rate deltas
        # and becomes `_prev`, so the walk must land in each or
        # tokens/s would read zero forever on a process fleet).
        for host, port in self._advertised_endpoints():
            child = scrape_exposition(host, port, self.timeout)
            if child is None:
                continue
            for key, v in child.items():
                merged[key] = merged.get(key, 0.0) + v
                name = key[0]
                totals[name] = totals.get(name, 0.0) + v
        states = {dict(labels).get("state"): v
                  for (name, labels), v in merged.items()
                  if name == "hvd_fleet_replicas"}
        ready = int(states.get("ready", 0))
        total = ready + int(states.get("warming", 0)) \
            + int(states.get("draining", 0))
        depth = sum(v for (name, _), v in merged.items()
                    if name == "hvd_queue_depth")
        parts = [f"fleet: {ready}/{total} replicas ready",
                 f"depth={int(depth)}"]
        # Adapter residency (multi-tenant serving): the router-level
        # distinct count — present only when some replica carries a
        # registry, read from the SAME labeled parse as everything else
        # (one scrape per endpoint per poll, the PR-13 rule).
        for (name, _labels), v in merged.items():
            if name == "hvd_fleet_adapters_resident":
                parts.append(f"adapters={int(v)} resident")
                break
        # Prefix-cache effectiveness: hit share of all prefix lookups,
        # from the SAME merged parse (one scrape per endpoint per poll,
        # the PR-13 rule) — shown only once some lookup happened, so a
        # fleet without prefix reuse keeps its old line.
        hits = sum(v for (name, _), v in merged.items()
                   if name == "hvd_prefix_hits_total")
        lookups = hits + sum(v for (name, _), v in merged.items()
                             if name == "hvd_prefix_misses_total")
        if lookups > 0:
            parts.append(f"prefix={100.0 * hits / lookups:.0f}%")
        buckets: Dict[str, float] = {}
        for (name, labels), v in merged.items():
            if name == "hvd_generate_ttft_seconds_bucket":
                le = dict(labels).get("le", "+Inf")
                buckets[le] = buckets.get(le, 0.0) + v
        n = buckets.get("+Inf", 0.0)
        if n > 0:
            bounds = sorted((float(le), c) for le, c in buckets.items()
                            if le != "+Inf")
            p50 = next((b for b, c in bounds if c >= n / 2.0), None)
            parts.append("ttft_p50<={:.1f}ms".format(p50 * 1e3)
                         if p50 is not None else "ttft_p50>last_bucket")
        else:
            parts.append("ttft_p50=n/a")
        for direction in ("grow", "shrink"):
            key = ("hvd_fleet_scale_events_total",
                   (("direction", direction),))
            if key in merged:
                parts.append(f"{direction}_events {int(merged[key])}")
        # `totals` is line()'s name-summed view of the SAME scrape —
        # rebuilt nowhere (three drifting copies of the summing loop is
        # how a future series fix misses one).
        if self._prev is not None and self._prev_t is not None:
            dt = max(1e-9, now - self._prev_t)
            if "hvd_tokens_generated_total" in totals:
                rate = (totals["hvd_tokens_generated_total"]
                        - self._prev.get("hvd_tokens_generated_total",
                                         0.0)) / dt
                parts.append(f"tokens/s {max(0.0, rate):.1f}")
        self._prev, self._prev_t = totals, now
        return " | ".join(parts)

    def line(self) -> str:
        samples = self.sample()
        now = time.monotonic()
        up = [s for s in samples if s is not None]
        totals: Dict[str, float] = {}
        for s in up:
            for k, v in s.items():
                totals[k] = totals.get(k, 0.0) + v
        self.last_up = len(up)
        self.last_mode = ("serving" if "hvd_fleet_replicas" in totals
                          else "training")
        if self.last_mode == "serving":
            # The scraped port is a serving fleet's /metrics, not a
            # training rank's — switch to the replica-centric summary.
            return self._serving_line(now, totals)
        steps = [s.get("hvd_global_step") for s in up
                 if s.get("hvd_global_step") is not None]
        n_polled = len(samples)
        scope = ("" if self._ranks is None or n_polled == self.world
                 else " (this node)")
        parts = [f"fleet: {len(up)}/{n_polled} ranks up{scope}"]
        if steps:
            lo, hi = int(min(steps)), int(max(steps))
            parts.append(f"step {lo}" if lo == hi
                         else f"step {lo}..{hi} (straggler spread "
                              f"{hi - lo})")
        if self._prev is not None and self._prev_t is not None:
            dt = max(1e-9, now - self._prev_t)
            for key, label in (("hvd_steps_total", "steps/s"),
                               ("hvd_samples_total", "samples/s"),
                               ("hvd_tokens_generated_total", "tokens/s")):
                if key in totals:
                    rate = (totals[key] - self._prev.get(key, 0.0)) / dt
                    parts.append(f"{label} {max(0.0, rate):.1f}")
        for key, label in (("hvd_bad_steps_total", "bad_steps"),
                           ("hvd_commits_total", "commits"),
                           ("hvd_restores_total", "restores"),
                           ("hvd_resizes_total", "resizes")):
            if key in totals:
                parts.append(f"{label} {int(totals[key])}")
        self._prev, self._prev_t = totals, now
        return " | ".join(parts)
