"""Crash-safe flight recorder: a bounded ring of recent structured
events, dumped to disk when the process dies badly.

The chaos drills (PRs 1/4/9) diagnose rank deaths by grepping stdout;
an operator debugging a real fleet incident has no stdout — the rank is
gone and its buffered logs with it. This module keeps the last-K events
(step boundaries, collective exchanges, commit/restore/resize/guard/
fault events) in memory at near-zero cost (one deque append per event)
and writes ``hvd_flightrec.rank{N}.json`` when something terminal
happens:

* :class:`~horovod_tpu.exceptions.WorkerFailureError` / coordinator
  ABORT — the coordination client dumps the moment the abort surfaces,
  so every SURVIVING rank leaves a record naming the dead party and its
  own last completed step (ranks run lockstep, so that IS the dead
  rank's last completed step ±1);
* a fatal signal (SIGTERM — what tpurun's teardown escalation and every
  real preemption notice deliver first; SIGKILL is untrappable by the
  kernel's contract, which is exactly why the SURVIVORS' dumps matter);
* ``runtime.shutdown(error=...)`` — the programmatic "this world is
  dying for a reason" path (:func:`horovod_tpu.elastic.run_with_recovery`
  routes every recoverable world failure through it);
* the fault injector's ``kill``/``exit`` actions dump just before
  pulling the trigger — the drill stands in for the platform's
  SIGTERM-before-SIGKILL preemption contract, so a drilled "dead" rank
  leaves the record a real preempted rank would.

Knobs: ``HVD_FLIGHTREC_DIR`` (dump directory, default cwd),
``HVD_FLIGHTREC_EVENTS`` (ring capacity, default 256; 0 disables both
recording and dumping).
"""

from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

DEFAULT_CAPACITY = 256
FILENAME = "hvd_flightrec.rank{rank}.json"


def _capacity() -> int:
    raw = os.environ.get("HVD_FLIGHTREC_EVENTS")
    if raw is None or raw == "":
        return DEFAULT_CAPACITY
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_CAPACITY


def _directory() -> str:
    return os.path.abspath(os.environ.get("HVD_FLIGHTREC_DIR") or ".")


def _my_rank() -> int:
    # Lazy imports: this module must stay import-light (the coordination
    # client and the fault injector import it on their error paths).
    from .. import runtime
    from ..utils import config as _config
    if runtime.is_initialized():
        return runtime.world().process_index
    return _config.launcher_rank(default=0)


class FlightRecorder:
    """Bounded ring of ``{"t": wall-clock, "kind": ..., **fields}``
    events. ``record`` is the hot call: one lock + one deque append —
    cheap enough for once-per-step emitters (NOT for per-element inner
    loops; callers aggregate first)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        # RLock, not Lock: the SIGTERM dump handler runs on the MAIN
        # thread between bytecodes, and the main thread may be inside
        # record()/dump() holding this very lock when the signal lands —
        # a non-reentrant lock would deadlock the dying rank instead of
        # writing its post-mortem.
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._dumps = 0

    def record(self, kind: str, **fields: Any) -> None:
        ev = {"t": round(time.time(), 6), "kind": str(kind)}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def last(self, kind: str) -> Optional[Dict]:
        with self._lock:
            for ev in reversed(self._ring):
                if ev["kind"] == kind:
                    return ev
        return None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def dump(self, reason: str, *, directory: Optional[str] = None,
             rank: Optional[int] = None) -> Optional[str]:
        """Write the ring as one JSON object (atomic rename, fsync'd —
        the reader may be a post-mortem on a machine that lost power).
        Repeated dumps overwrite: the LAST record before death wins.
        Returns the path, or None when recording is disabled."""
        if _capacity() == 0:
            return None
        rank = _my_rank() if rank is None else int(rank)
        base = _directory() if directory is None else os.path.abspath(
            directory)
        events = self.events()
        last_step = None
        for ev in reversed(events):
            if "step" in ev:
                last_step = ev["step"]
                break
        record = {
            "rank": rank,
            "reason": str(reason),
            "dumped_at": time.time(),
            "last_step": last_step,
            "n_events": len(events),
            "events": events,
        }
        path = os.path.join(base, FILENAME.format(rank=rank))
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(base, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(record, f)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            # Dumping is a courtesy on a dying process — never let the
            # post-mortem writer mask the original failure.
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        with self._lock:
            self._dumps += 1
        return path


_recorder = FlightRecorder(_capacity())
_crash_hooks: List[Callable[[], Any]] = []
_hooks_lock = threading.Lock()
_installed = False


def recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **fields: Any) -> None:
    """Append one event to the process-default ring (no-op when
    ``HVD_FLIGHTREC_EVENTS=0``)."""
    if _capacity() == 0:
        return
    _recorder.record(kind, **fields)


def dump(reason: str, **kw) -> Optional[str]:
    return _recorder.dump(reason, **kw)


def add_crash_hook(fn: Callable[[], Any]) -> None:
    """Register a flush-style callback to run (best-effort) after the
    fatal-signal dump — e.g. the timeline writer's fsync, so a killed
    rank's trace survives alongside its flight record."""
    with _hooks_lock:
        if fn not in _crash_hooks:
            _crash_hooks.append(fn)


def remove_crash_hook(fn: Callable[[], Any]) -> None:
    with _hooks_lock:
        try:
            _crash_hooks.remove(fn)
        except ValueError:
            pass


def run_crash_hooks() -> None:
    with _hooks_lock:
        hooks = list(_crash_hooks)
    for fn in hooks:
        try:
            fn()
        except Exception:  # noqa: BLE001 — a dying process keeps dying
            pass


def _on_fatal(signum, frame):
    record("signal", signum=int(signum))
    dump(reason=f"fatal signal {int(signum)}")
    run_crash_hooks()
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
        return
    # Default disposition: restore it and re-deliver so the exit status
    # still says "killed by signal" (supervisors key on that).
    _signal.signal(signum, _signal.SIG_DFL)
    os.kill(os.getpid(), signum)


_prev_handlers: Dict[int, Any] = {}


def install_signal_dump() -> bool:
    """Install the SIGTERM dump hook (idempotent; main thread only —
    returns False elsewhere or when a prior non-default handler would be
    better left alone is NOT a concern: we chain to it)."""
    global _installed
    if _installed or _capacity() == 0:
        return _installed
    try:
        prev = _signal.getsignal(_signal.SIGTERM)
        _prev_handlers[_signal.SIGTERM] = (
            prev if callable(prev) and prev not in (
                _signal.SIG_DFL, _signal.SIG_IGN) else None)
        _signal.signal(_signal.SIGTERM, _on_fatal)
        _installed = True
    except (ValueError, OSError):
        # Not the main thread (jupyter, server worker) — the other dump
        # triggers (abort / shutdown(error=) / fault injector) still run.
        return False
    return True
