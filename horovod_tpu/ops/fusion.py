"""Tensor fusion: bucket many small tensors into one flat collective.

Reference semantics (``docs/tensor-fusion.md:6-28``, fusion decision
``mpi_ops.cc:1395-1422``, data movement ``mpi_ops.cc:1024-1096``):

* Only tensors of the **same dtype** fuse (and same device set — moot here:
  everything lives on the world mesh).
* A bucket's total byte size is capped by the fusion threshold
  (default 64 MiB, ``mpi_ops.cc:165``; env ``HOROVOD_FUSION_THRESHOLD``,
  0 disables fusion, ``docs/tensor-fusion.md:24-28``).
* **Request order is preserved**: scanning stops at the first non-fusable
  tensor rather than skipping ahead (``mpi_ops.cc:1414-1419``), so fusion
  never reorders collectives.

TPU-native design: instead of memcpy loops into a persistent staging buffer,
bucketing happens at trace time — each bucket's members are flattened and
concatenated into one flat vector in HBM, reduced with a single XLA
``all-reduce`` over ICI, and split back. XLA fuses the (de)concatenation with
neighbors, so the "fusion buffer" never exists as a separate persistent
allocation. An oversized tensor becomes its own bucket (the reference
likewise falls back to a direct non-fused collective for tensors above the
threshold, ``mpi_ops.cc:1101-1105``).

The same bucket planner also feeds the ZeRO-1 sharded-update plane
(:class:`ZeroPlan`, :func:`fused_reduce_scatter`,
:func:`fused_allgather_params`): reduce-scatter + all-gather spend the same
bytes on the wire as the fused all-reduce while cutting optimizer-state
memory and update FLOPs by the world size (``docs/performance.md``).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import AXIS
from ..utils import config as _config
from ..utils.compat import all_gather_invariant, axis_size
from .collectives import Op, _reduce_in_trace


def _greedy_scan(key, order, fusion_threshold: int):
    """The fusion scan over leaves visited in ``order``: fuse while the
    dtype matches and cumulative bytes stay within the threshold; close the
    bucket at the first non-fusable tensor (``mpi_ops.cc:1414-1419`` —
    never look ahead, never reorder within the visit order)."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_dtype = None
    cur_bytes = 0
    for i in order:
        shape, dtype = key[i]
        nbytes = int(math.prod(shape)) * np.dtype(dtype).itemsize
        fusable = (
            fusion_threshold > 0
            and cur
            and dtype == cur_dtype
            and cur_bytes + nbytes <= fusion_threshold
        )
        if fusable:
            cur.append(i)
            cur_bytes += nbytes
        else:
            if cur:
                buckets.append(cur)
            cur = [i]
            cur_dtype = dtype
            cur_bytes = nbytes
    if cur:
        buckets.append(cur)
    return tuple(tuple(b) for b in buckets)


@functools.lru_cache(maxsize=512)
def _plan_cached(key: Tuple[Tuple[Tuple[int, ...], str], ...],
                 fusion_threshold: int) -> Tuple[Tuple[int, ...], ...]:
    """The fusion scan, memoized. The plan is a pure function of the leaf
    (shape, dtype) sequence and the threshold, so repeated traces and
    eager per-step calls over the same gradient tree (every step of the
    env-world plane, every re-trace of the compiled one) stop re-walking
    the whole tree. Keyed on resolved values only — the env-var default
    is resolved by the caller, so changing ``HOROVOD_FUSION_THRESHOLD``
    between calls still takes effect."""
    return _greedy_scan(key, range(len(key)), fusion_threshold)


def plan_buckets(leaves: Sequence[jax.Array],
                 fusion_threshold: Optional[int] = None) -> List[List[int]]:
    """Partition leaf indices into fusion buckets, preserving order.

    Mirrors the coordinator's fusion scan (``mpi_ops.cc:1395-1422``): walk the
    queue in order; fuse while dtype matches and cumulative bytes stay within
    the threshold; close the bucket at the first non-fusable tensor.
    ``fusion_threshold=0`` disables fusion (one bucket per tensor).

    The scan is cached per ``(shapes, dtypes, threshold)`` — see
    :func:`_plan_cached`; callers get a fresh mutable copy each call, so
    mutating a returned plan cannot poison the cache.
    """
    if fusion_threshold is None:
        fusion_threshold = _config.fusion_threshold_bytes()
    key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
                for leaf in leaves)
    return [list(b) for b in _plan_cached(key, int(fusion_threshold))]


# ---------------------------------------------------------------------------
# Backward-overlapped emission (ISSUE 6 tentpole; the core Horovod trick,
# Sergeev & Del Balso 2018 §3): issue one collective per bucket AS ITS
# GRADIENTS COMPLETE instead of one fused traversal after backward. On the
# compiled plane the mechanism is data dependencies + optimization_barrier
# pins: buckets group leaves ADJACENT IN BACKWARD-COMPLETION ORDER (so a
# bucket's collective depends only on an early prefix of the backward), and
# each bucket's operand is barrier-chained to the previous bucket's result —
# which (a) fixes the issue order deterministically, (b) stops XLA's
# all-reduce combiner from re-merging the buckets into one post-backward
# blob, and (c) leaves XLA's latency-hiding scheduler free to hoist every
# collective behind the remaining backward compute (it does: the HLO pin in
# tests/test_overlap_wire.py shows each bucket's collective scheduled before
# the last backward op of the module).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketSchedule:
    """An ordered fusion plan: ``buckets`` are leaf-index groups (over the
    same flattened tree ``plan_buckets`` scans) built by walking the leaves
    in ``order`` — backward-completion order from
    :func:`probe_grad_order` — so bucket k's members finish together and
    its collective can fire while buckets k+1... are still back-propagating.
    A pure function of (shapes, dtypes, threshold, order): deterministic
    across processes and across cache hits."""

    buckets: Tuple[Tuple[int, ...], ...]
    order: Tuple[int, ...]
    threshold: int


@functools.lru_cache(maxsize=512)
def _schedule_cached(key, order, fusion_threshold: int):
    return _greedy_scan(key, order, fusion_threshold)


def plan_schedule(leaves: Sequence[jax.Array],
                  grad_order: Optional[Sequence[int]] = None,
                  fusion_threshold: Optional[int] = None) -> BucketSchedule:
    """Build the overlap emission schedule for ``leaves``.

    ``grad_order`` is the backward-completion permutation of leaf indices
    (:func:`probe_grad_order`); None falls back to flatten order, which
    degrades to the non-overlapped grouping. Same caching contract as
    :func:`plan_buckets` — keyed on resolved (shapes, dtypes, order,
    threshold), so an env-var threshold flip between calls still
    invalidates."""
    if fusion_threshold is None:
        fusion_threshold = _config.fusion_threshold_bytes()
    key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
                for leaf in leaves)
    order = (tuple(range(len(key))) if grad_order is None
             else tuple(int(i) for i in grad_order))
    if sorted(order) != list(range(len(key))):
        raise ValueError(
            f"grad_order must be a permutation of the {len(key)} leaf "
            f"indices; got {order}")
    return BucketSchedule(
        buckets=_schedule_cached(key, order, int(fusion_threshold)),
        order=order, threshold=int(fusion_threshold))


def probe_grad_order(grad_fn, *args, **kwargs) -> Optional[Tuple[int, ...]]:
    """Backward-completion order of a gradient tree's leaves, from a
    one-time abstract trace (no FLOPs): ``grad_fn(*args)`` must return the
    grad tree; each output leaf is ranked by the position of its defining
    equation in the traced jaxpr — the order the backward pass materializes
    it. Leaves whose producer cannot be identified (literals, forwarded
    inputs, leaves fused into one opaque sub-jaxpr such as a rolled scan)
    keep flatten order as a stable tie-break, so the probe degrades to the
    non-overlapped schedule rather than guessing. Returns None when the
    function cannot be traced outside its collective context (e.g. a model
    with cross-replica BatchNorm probed without its axis bound) — callers
    fall back to flatten order."""
    try:
        closed = jax.make_jaxpr(grad_fn)(*args, **kwargs)
    except Exception:  # noqa: BLE001 — probe is best-effort by contract
        return None
    jaxpr = closed.jaxpr
    pos = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            pos[v] = i
    outvars = jaxpr.outvars

    def _rank(k):
        v = outvars[k]
        # Literal outvars (e.g. the zero cotangent of a leaf the loss never
        # reads) are unhashable on older jax — they take the flatten-order
        # fallback, same as any other unrankable leaf.
        if not isinstance(v, jax.core.Var):
            return (-1, k)
        return (pos.get(v, -1), k)

    return tuple(sorted(range(len(outvars)), key=_rank))


@functools.lru_cache(maxsize=512)
def _emit_order_cached(buckets, grad_order):
    ready = []
    pos = {leaf: p for p, leaf in enumerate(grad_order)}
    for b in buckets:
        ready.append(max(pos.get(j, j) for j in b))
    return tuple(sorted(range(len(buckets)),
                        key=lambda i: (ready[i], i)))


def zero_emit_order(plan: "ZeroPlan",
                    grad_order: Optional[Sequence[int]]) -> Tuple[int, ...]:
    """Emission order of a :class:`ZeroPlan`'s buckets under overlap:
    sorted by READINESS (the latest backward-completion position among the
    bucket's members). Unlike the all-reduce plane's
    :class:`BucketSchedule`, ZeRO bucket MEMBERSHIP never changes — the
    plan defines the sharded optimizer-state layout and the world-agnostic
    checkpoint form, so overlap may only reorder which bucket's
    reduce-scatter issues first, never regroup leaves."""
    if grad_order is None:
        return tuple(range(len(plan.buckets)))
    return _emit_order_cached(plan.buckets, tuple(int(i)
                                                  for i in grad_order))


def _barrier_chain(operand, prev):
    """Pin emission order: barrier the next bucket's operand against the
    previous bucket's reduced result. Creates the data dependency that (a)
    makes the cross-bucket issue order deterministic and (b) keeps XLA's
    collective combiner from merging the per-bucket collectives back into
    one post-backward blob (combining requires independence)."""
    if prev is None:
        return operand
    operand, _ = jax.lax.optimization_barrier((operand, prev))
    return operand


def _fuse(leaves: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate([jnp.ravel(l) for l in leaves])


def _unfuse(flat: jax.Array, leaves: Sequence[jax.Array]) -> List[jax.Array]:
    out = []
    offset = 0
    for l in leaves:
        n = int(math.prod(l.shape))
        out.append(jnp.reshape(flat[offset:offset + n], l.shape))
        offset += n
    return out


def _prescale_array(x, prescale):
    """Scale one flat/bucketed array before its collective. Dtype-preserving
    on the outside (the result returns in the operand dtype, so bf16 buckets
    stay bf16 on the wire), but sub-fp32 buckets are scaled IN fp32 — a
    bf16 multiply quantizes the scale itself (bf16(1/3) carries 8 mantissa
    bits) and double-rounds, so the fp32 product with a single final cast
    is strictly more accurate for the same wire bytes. Integer leaves pass
    through untouched — a fractional scale would silently floor them."""
    if prescale is None or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    if jnp.dtype(x.dtype).itemsize < 4:
        return (x.astype(jnp.float32)
                * jnp.asarray(prescale, jnp.float32)).astype(x.dtype)
    return x * jnp.asarray(prescale, x.dtype)


# ---------------------------------------------------------------------------
# Low-precision wire formats: cast-on-send, fp32-accumulated results.
# The collective itself runs in the wire dtype (half/quarter the ICI bytes);
# every scale that touches the bucket (average's 1/size, accumulation's 1/N,
# fp8's dynamic scale) is applied in fp32 BEFORE the cast, and the reduced
# result is cast back to the bucket's original dtype immediately after — so
# everything downstream of the wire (shard updates, optimizer math) runs at
# full precision and the only loss is the one quantization on send.
# ---------------------------------------------------------------------------

# fp8 (e4m3) headroom: values are scaled so the WORST-CASE reduced sum
# (every rank at amax, same sign) lands at half of the 448 format max —
# range is cheap in e4m3 (17 binades) and the margin keeps rounding in the
# reduction from saturating into NaN (e4m3fn has no Inf).
_FP8_MARGIN = 224.0

_WIRE_ALIASES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "fp8": "float8_e4m3fn", "fp8_e4m3": "float8_e4m3fn",
    "f8e4m3": "float8_e4m3fn", "float8_e4m3fn": "float8_e4m3fn",
}
_WIRE_NONE = (None, "", "none", "fp32", "f32", "float32")


def resolve_wire_dtype(spec):
    """Normalize a wire-format spec to a jnp dtype (or None = full
    precision). Accepts the knob spellings (``"bf16"``, ``"fp8"``), the
    canonical dtype names, actual dtypes, or None/``"fp32"``. Unknown
    specs raise eagerly with the supported set named — a typo must not
    silently train at full precision."""
    if spec in _WIRE_NONE:
        return None
    key = spec if isinstance(spec, str) else jnp.dtype(spec).name
    key = key.strip().lower()
    if key in _WIRE_NONE:
        return None
    name = _WIRE_ALIASES.get(key)
    if name is None:
        raise ValueError(
            f"unknown wire_dtype {spec!r}: supported are 'bf16', 'fp8' "
            f"(e4m3 with per-bucket dynamic scaling), or None/'fp32' for "
            f"full precision")
    return jnp.dtype(name)


def wire_dtype_name(wire) -> str:
    """Knob spelling of a resolved wire dtype (for stamps/JSON lines)."""
    w = resolve_wire_dtype(wire)
    if w is None:
        return "fp32"
    return "bf16" if w == jnp.dtype(jnp.bfloat16) else "fp8"


def _wire_applies(dtype, wire) -> bool:
    """A bucket rides the wire format only when it is float and strictly
    wider than the wire dtype — bf16 buckets under a bf16 wire are already
    at wire width (no cast), integers never quantize."""
    return (wire is not None
            and jnp.issubdtype(dtype, jnp.floating)
            and jnp.dtype(dtype).itemsize > jnp.dtype(wire).itemsize)


def _wire_exchange(flat, axis_names, wire, world, reduce_fn, prescale=None):
    """One wire-format reduction, shared by the all-reduce and ZeRO
    planes: fp32 prescale → (fp8: dynamic scale) → ONE cast on send →
    ``reduce_fn`` in the wire dtype → fp32 result, scale divided back out,
    cast to the original dtype — fp32 accumulation for everything
    downstream of the wire.

    fp8 additionally exchanges one scalar ``pmax`` per bucket (the only
    collective any wire format adds): the per-bucket dynamic scale must be
    identical on every rank or the scaled values would not share a unit,
    and the sum of ``world`` in-range values must stay in range — so the
    scale is ``margin / (world * global_amax)``, applied in fp32 and
    divided back out of the fp32 result."""
    orig = flat.dtype
    x = flat.astype(jnp.float32) if orig != jnp.float32 else flat
    if prescale is not None:
        x = x * jnp.asarray(prescale, jnp.float32)
    scale = None
    if jnp.dtype(wire).itemsize == 1:
        amax = jax.lax.pmax(jnp.max(jnp.abs(x)), axis_names)
        scale = jnp.where(amax > 0, _FP8_MARGIN / (world * amax), 1.0)
        x = x * scale
    out = reduce_fn(x.astype(wire)).astype(jnp.float32)
    if scale is not None:
        out = out / scale
    return out.astype(orig)


def _wire_sum(flat, axis_names, wire, prescale=None):
    """Wire-format psum over ``axis_names`` (see :func:`_wire_exchange`)."""
    world = 1
    for a in ((axis_names,) if isinstance(axis_names, str)
              else tuple(axis_names)):
        world *= int(axis_size(a))
    return _wire_exchange(
        flat, axis_names, wire, world,
        lambda w: jax.lax.psum(w, axis_names), prescale=prescale)


def _wire_scatter(flat, axis_name, wire, nshards, prescale=None):
    """Wire-format ``psum_scatter`` (see :func:`_wire_exchange`): this
    rank's shard comes back in the bucket's original dtype, so the
    optimizer update accumulates in fp32 even when the wire carried
    bf16/fp8."""
    return _wire_exchange(
        flat, axis_name, wire, nshards,
        lambda w: jax.lax.psum_scatter(w, axis_name, tiled=True),
        prescale=prescale)


def fused_allreduce(tree, average: bool = True,
                    fusion_threshold: Optional[int] = None,
                    axis_name: str = AXIS,
                    prescale: Optional[float] = None,
                    return_finite: bool = False,
                    wire_dtype=None,
                    overlap: bool = False,
                    grad_order: Optional[Sequence[int]] = None):
    """Allreduce a pytree with fusion bucketing. Compiled-context only
    (it is the gradient hot path inside the jitted train step).

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves are kept
    whole and routed through the two-allgather sparse path — never flattened
    into dense buckets (their integer indices must not be summed).

    ``prescale`` multiplies every bucket by a scalar *before* the reduce —
    one fused multiply on the already-flattened bucket, not one per leaf —
    which is how gradient accumulation folds its ``1/accum_steps`` into the
    same traversal (the reference's ``backward_passes_per_step`` divides by
    the global microbatch count at the same point). The reduce is linear, so
    pre- and post-scaling are equivalent; prescaling keeps the bucketed tree
    the single thing the collective ever sees.

    ``return_finite=True`` returns ``(reduced_tree, all_finite)`` where
    ``all_finite`` is a scalar bool, True iff every float leaf of EVERY
    rank's input was finite — the in-jit bad-step guard's signal. It is
    folded into the same bucket traversal with **zero extra collectives**:
    the reduce is a sum, and IEEE754 sums propagate any NaN/Inf operand
    into the result (Inf−Inf pairs become NaN, overflow becomes Inf), so
    checking ``isfinite`` on each REDUCED bucket while still flat — one
    pass per bucket, before unfusing — sees every rank's poison through
    the psum that already happened. The flag is therefore identical on
    all replicas, which is exactly what a divergence-free skip-step
    decision needs.

    ``wire_dtype`` (``"bf16"`` / ``"fp8"``) puts float buckets on the wire
    in reduced precision: every scale is applied in fp32 before ONE cast on
    send, the collective runs in the wire dtype, and the result is cast
    back to the bucket's original dtype immediately after (fp32
    accumulation downstream; see :func:`_wire_sum` — fp8 adds one scalar
    ``pmax`` per bucket for its dynamic scale, the only extra collective
    any wire format introduces). The bucket PLAN is unchanged — a wire
    cast never merges or splits buckets.

    ``overlap=True`` (or a ``grad_order`` from :func:`probe_grad_order`)
    switches to the backward-overlapped emission: buckets group leaves by
    backward-completion order (:func:`plan_schedule`) and each bucket's
    collective is barrier-chained behind the previous one's result, so the
    per-bucket collectives issue as their gradients complete and XLA hides
    wire time behind the remaining backward compute. Same total collective
    count as the non-overlapped plan over the same leaf multiset — overlap
    reorders, never adds."""
    from .sparse import IndexedSlices, allreduce_indexed_slices

    wire = resolve_wire_dtype(wire_dtype)
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if not leaves:
        return (tree, jnp.ones((), jnp.bool_)) if return_finite else tree
    op = Op.AVERAGE if average else Op.SUM
    reduced: List[Optional[jax.Array]] = [None] * len(leaves)
    finite = jnp.ones((), jnp.bool_)

    def _check(x):
        nonlocal finite
        if return_finite and jnp.issubdtype(x.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(x))

    dense_idx = [i for i, l in enumerate(leaves)
                 if not isinstance(l, IndexedSlices)]
    for i in (i for i in range(len(leaves)) if i not in dense_idx):
        s = leaves[i]
        if prescale is not None:
            s = IndexedSlices(_prescale_array(s.values, prescale),
                              s.indices, s.dense_shape)
        r = allreduce_indexed_slices(
            s, average=average, axis_name=axis_name)
        # Allgathered slices carry every rank's raw values, so a local
        # NaN is literally present in each rank's gathered copy.
        _check(r.values)
        reduced[i] = r

    dense = [leaves[i] for i in dense_idx]
    overlap_on = overlap or grad_order is not None
    if overlap_on:
        order_d = None
        if grad_order is not None:
            # Project the full-tree completion order onto the dense
            # subsequence (sparse leaves ride their own allgather path).
            full_to_dense = {fi: di for di, fi in enumerate(dense_idx)}
            order_d = tuple(full_to_dense[i] for i in grad_order
                            if i in full_to_dense)
        buckets = [list(b) for b in
                   plan_schedule(dense, order_d, fusion_threshold).buckets]
    else:
        buckets = plan_buckets(dense, fusion_threshold)

    prev = None
    for bucket in buckets:
        if len(bucket) == 1:
            operand = dense[bucket[0]]
        else:
            operand = _fuse([dense[j] for j in bucket])
        if overlap_on and len(buckets) > 1:
            operand = _barrier_chain(operand, prev)
        if _wire_applies(operand.dtype, wire):
            eff = prescale
            if op is Op.AVERAGE:
                inv = 1.0 / int(axis_size(axis_name))
                eff = inv if eff is None else eff * inv
            r = _wire_sum(operand, axis_name, wire, prescale=eff)
        else:
            r = _reduce_in_trace(
                _prescale_array(operand, prescale), op, axis_name)
        if overlap_on:
            prev = r
        _check(r)
        if len(bucket) == 1:
            reduced[dense_idx[bucket[0]]] = r
        else:
            members = [dense[j] for j in bucket]
            for j, rr in zip(bucket, _unfuse(r, members)):
                reduced[dense_idx[j]] = rr
    out = jax.tree_util.tree_unflatten(treedef, reduced)
    return (out, finite) if return_finite else out


# ---------------------------------------------------------------------------
# ZeRO-1 sharded-update plane (Rajbhandari et al. 2020; Xu et al. 2020,
# "Automatic Cross-Replica Sharding of Weight Update Computation"): the same
# bucket planner that feeds the fused all-reduce instead feeds a
# reduce-scatter — every rank receives the REDUCED 1/N slice of each flat
# bucket, applies the optimizer update to its slice only, and the updated
# slices ride one all-gather back into the full tree. Bytes on the wire are
# unchanged (ring all-reduce = reduce-scatter + all-gather); optimizer-state
# memory and update FLOPs drop by the world size.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ZeroPlan:
    """Static layout of a tree's rank-sharded flat buckets.

    Everything here is trace-time constant (hashable, usable as pytree aux
    data): ``buckets`` are :func:`plan_buckets` index groups over the
    flattened tree, ``sizes``/``padded`` the true and rank-padded flat
    length per bucket (``padded[i]`` is the smallest multiple of
    ``nshards`` >= ``sizes[i]``, so ``lax.psum_scatter(tiled=True)`` splits
    evenly), ``shapes``/``dtypes`` the member leaves' layout for unfusing,
    and ``treedef`` the original tree structure."""

    buckets: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    padded: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    treedef: Any
    nshards: int

    def shard_len(self, i: int) -> int:
        return self.padded[i] // self.nshards

    def shard_shapes(self):
        """Per-bucket ``(nshards, shard_len)`` — the stacked layout the
        sharded optimizer state stores (leading axis split one shard per
        rank over the world mesh)."""
        return tuple((self.nshards, self.shard_len(i))
                     for i in range(len(self.buckets)))


def plan_zero(tree, nshards: int,
              fusion_threshold: Optional[int] = None) -> ZeroPlan:
    """Build the sharded-update layout for ``tree`` over ``nshards`` ranks.

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves cannot
    be flattened into rank-sharded dense buckets (their integer indices
    must not be summed, and a slice of a slice has no owner rank) — a tree
    carrying them raises; densify first (``sparse_as_dense``) or keep the
    replicated optimizer for sparse models."""
    from .sparse import IndexedSlices
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if any(isinstance(l, IndexedSlices) for l in leaves):
        raise ValueError(
            "ZeRO sharded updates require dense gradients: an "
            "IndexedSlices leaf cannot be flattened into rank-sharded "
            "buckets (densify with sparse_as_dense=True, or use the "
            "replicated DistributedOptimizer for sparse models)")
    if nshards < 1:
        raise ValueError(f"nshards must be >= 1, got {nshards}")
    buckets = plan_buckets(leaves, fusion_threshold)
    sizes = []
    padded = []
    for b in buckets:
        n = sum(int(math.prod(leaves[j].shape)) for j in b)
        sizes.append(n)
        padded.append(-(-n // nshards) * nshards)
    return ZeroPlan(
        buckets=tuple(tuple(b) for b in buckets),
        sizes=tuple(sizes),
        padded=tuple(padded),
        shapes=tuple(tuple(l.shape) for l in leaves),
        dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
        treedef=treedef,
        nshards=nshards,
    )


def _fuse_bucket(leaves, plan: ZeroPlan, i: int):
    """Flatten bucket ``i``'s members into one rank-padded flat vector."""
    flat = _fuse([leaves[j] for j in plan.buckets[i]])
    pad = plan.padded[i] - plan.sizes[i]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def fused_reduce_scatter(tree, plan: ZeroPlan, *,
                         average: bool = True,
                         axis_name: str = AXIS,
                         prescale: Optional[float] = None,
                         return_finite: bool = False,
                         wire_dtype=None,
                         emit_order: Optional[Sequence[int]] = None):
    """Reduce-scatter a pytree into this rank's flat bucket shards.

    Each bucket is flattened, zero-padded to a multiple of the world size,
    optionally prescaled (one fused multiply on the flat bucket — gradient
    accumulation's ``1/accum_steps`` and ``average``'s ``1/size`` fold into
    the same scalar), and fed to one ``lax.psum_scatter`` — rank ``r``
    receives the REDUCED slice ``flat[r*s:(r+1)*s]``. Returns the per-bucket
    shard list (order = plan order).

    ``return_finite=True`` additionally returns a **rank-local** all-finite
    scalar derived from the already-reduced shards: IEEE sums propagate any
    rank's NaN/Inf into the reduced value at that position, which lands on
    exactly one rank's shard — so the flag differs per rank and only the
    AND over ranks is the world-wide verdict. :func:`fused_allgather_params`
    folds that AND into the all-gather the updated shards already ride
    (``and_finite=``), keeping the bad-step guard at zero extra collectives
    in ZeRO mode too.

    ``wire_dtype`` (``"bf16"`` / ``"fp8"``) runs the scatter in reduced
    precision — fp32 prescale, one cast on send, and the received shard
    cast straight back to the bucket's dtype so the optimizer update
    accumulates in fp32 (:func:`_wire_scatter`). ``emit_order`` (a bucket
    permutation from :func:`zero_emit_order`) issues the scatters in
    backward-readiness order behind ``optimization_barrier`` pins — bucket
    MEMBERSHIP (and therefore the sharded state layout and the checkpoint
    canonical form) never changes, only which collective fires first. The
    returned shard list is always in PLAN order.
    """
    wire = resolve_wire_dtype(wire_dtype)
    leaves = plan.treedef.flatten_up_to(tree)
    scale = None
    if average and plan.nshards > 1:
        scale = 1.0 / plan.nshards
    if prescale is not None:
        scale = prescale if scale is None else scale * prescale
    nb = len(plan.buckets)
    order = tuple(range(nb)) if emit_order is None \
        else tuple(int(i) for i in emit_order)
    if sorted(order) != list(range(nb)):
        raise ValueError(
            f"emit_order must be a permutation of the {nb} bucket "
            f"indices; got {order}")
    shards: List[Optional[jax.Array]] = [None] * nb
    finite = jnp.ones((), jnp.bool_)
    prev = None
    for i in order:
        flat = _fuse_bucket(leaves, plan, i)
        if emit_order is not None and nb > 1:
            flat = _barrier_chain(flat, prev)
        if plan.nshards > 1:
            if _wire_applies(flat.dtype, wire):
                shard = _wire_scatter(flat, axis_name, wire, plan.nshards,
                                      prescale=scale)
            else:
                shard = jax.lax.psum_scatter(
                    _prescale_array(flat, scale), axis_name, tiled=True)
        else:
            # Single shard: the reduce is the identity, and nothing rides
            # the wire — no quantization round-trip either.
            shard = _prescale_array(flat, scale)
        if emit_order is not None:
            prev = shard
        if return_finite and jnp.issubdtype(shard.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(shard))
        shards[i] = shard
    return (shards, finite) if return_finite else shards


def shard_params(tree, plan: ZeroPlan, *, axis_name: str = AXIS,
                 rank: Optional[int] = None):
    """Slice this rank's flat bucket shards out of a replicated pytree
    (no collective — each rank takes ``flat[rank*s:(rank+1)*s]``). The
    owner index is ``lax.axis_index`` in-trace, or the static ``rank``
    the env-world plane passes (one process = one shard)."""
    leaves = plan.treedef.flatten_up_to(tree)
    idx = jax.lax.axis_index(axis_name) if rank is None else rank
    shards = []
    for i in range(len(plan.buckets)):
        flat = _fuse_bucket(leaves, plan, i)
        s = plan.shard_len(i)
        if plan.nshards == 1:
            shards.append(flat)
        elif rank is None:
            shards.append(jax.lax.dynamic_slice(flat, (idx * s,), (s,)))
        else:
            shards.append(flat[rank * s:(rank + 1) * s])
    return shards


def _unfuse_flat(flats, plan: ZeroPlan):
    """Rebuild the original tree from per-bucket UNPADDED flat vectors."""
    reduced: List[Optional[jax.Array]] = [None] * len(plan.shapes)
    for i, bucket in enumerate(plan.buckets):
        flat = flats[i]
        offset = 0
        for j in bucket:
            n = int(math.prod(plan.shapes[j]))
            reduced[j] = jnp.reshape(flat[offset:offset + n], plan.shapes[j])
            offset += n
    return plan.treedef.unflatten(reduced)


def fused_allgather_params(shards, plan: ZeroPlan, *,
                           axis_name: str = AXIS,
                           and_finite: Optional[jax.Array] = None):
    """Rebuild a full pytree from every rank's updated flat bucket shards:
    one ``all_gather`` per bucket, padding stripped, leaves reshaped.

    ``and_finite`` (a rank-LOCAL boolean from
    :func:`fused_reduce_scatter`'s ``return_finite``) rides the same
    gather: the scalar is appended as one extra element to the first
    inexact bucket's shard, so after gathering every rank sees every
    rank's flag and the AND is replica-identical — the world-wide
    bad-step verdict with **zero** extra collectives. Returns
    ``(tree, all_finite)`` in that case, else just ``tree``.
    """
    nb = len(plan.buckets)
    flag_bucket = None
    if and_finite is not None:
        flag_bucket = next(
            (i for i in range(nb)
             if jnp.issubdtype(jnp.dtype(plan.dtypes[plan.buckets[i][0]]),
                               jnp.inexact)), None)
    shards = list(shards)
    if flag_bucket is not None:
        flag = and_finite.astype(shards[flag_bucket].dtype).reshape(1)
        shards[flag_bucket] = jnp.concatenate([shards[flag_bucket], flag])
    flats = []
    all_finite = None
    for i in range(nb):
        if plan.nshards > 1:
            gathered = all_gather_invariant(shards[i], axis_name, tiled=True)
        else:
            gathered = shards[i]
        if i == flag_bucket:
            s = plan.shard_len(i)
            blocks = gathered.reshape(plan.nshards, s + 1)
            # 1.0/0.0 flags by construction (isfinite output cast to the
            # bucket dtype) — exactly representable in every float dtype.
            all_finite = jnp.all(blocks[:, -1].astype(jnp.float32) > 0.5)
            gathered = blocks[:, :s].reshape(-1)
        flats.append(gathered[:plan.sizes[i]])
    out = _unfuse_flat(flats, plan)
    if and_finite is None:
        return out
    if all_finite is None:
        # No inexact bucket: an all-integer tree is finite by construction,
        # so the local flag (constant True) is already the global verdict.
        all_finite = and_finite
    return out, all_finite
