"""Tensor fusion: bucket many small tensors into one flat collective.

Reference semantics (``docs/tensor-fusion.md:6-28``, fusion decision
``mpi_ops.cc:1395-1422``, data movement ``mpi_ops.cc:1024-1096``):

* Only tensors of the **same dtype** fuse (and same device set — moot here:
  everything lives on the world mesh).
* A bucket's total byte size is capped by the fusion threshold
  (default 64 MiB, ``mpi_ops.cc:165``; env ``HOROVOD_FUSION_THRESHOLD``,
  0 disables fusion, ``docs/tensor-fusion.md:24-28``).
* **Request order is preserved**: scanning stops at the first non-fusable
  tensor rather than skipping ahead (``mpi_ops.cc:1414-1419``), so fusion
  never reorders collectives.

TPU-native design: instead of memcpy loops into a persistent staging buffer,
bucketing happens at trace time — each bucket's members are flattened and
concatenated into one flat vector in HBM, reduced with a single XLA
``all-reduce`` over ICI, and split back. XLA fuses the (de)concatenation with
neighbors, so the "fusion buffer" never exists as a separate persistent
allocation. An oversized tensor becomes its own bucket (the reference
likewise falls back to a direct non-fused collective for tensors above the
threshold, ``mpi_ops.cc:1101-1105``).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..runtime import AXIS
from ..utils import config as _config
from .collectives import Op, _reduce_in_trace


def plan_buckets(leaves: Sequence[jax.Array],
                 fusion_threshold: Optional[int] = None) -> List[List[int]]:
    """Partition leaf indices into fusion buckets, preserving order.

    Mirrors the coordinator's fusion scan (``mpi_ops.cc:1395-1422``): walk the
    queue in order; fuse while dtype matches and cumulative bytes stay within
    the threshold; close the bucket at the first non-fusable tensor.
    ``fusion_threshold=0`` disables fusion (one bucket per tensor).
    """
    if fusion_threshold is None:
        fusion_threshold = _config.fusion_threshold_bytes()

    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_dtype = None
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = int(math.prod(leaf.shape)) * leaf.dtype.itemsize
        fusable = (
            fusion_threshold > 0
            and cur
            and leaf.dtype == cur_dtype
            and cur_bytes + nbytes <= fusion_threshold
        )
        if fusable:
            cur.append(i)
            cur_bytes += nbytes
        else:
            if cur:
                buckets.append(cur)
            cur = [i]
            cur_dtype = leaf.dtype
            cur_bytes = nbytes
    if cur:
        buckets.append(cur)
    return buckets


def _fuse(leaves: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate([jnp.ravel(l) for l in leaves])


def _unfuse(flat: jax.Array, leaves: Sequence[jax.Array]) -> List[jax.Array]:
    out = []
    offset = 0
    for l in leaves:
        n = int(math.prod(l.shape))
        out.append(jnp.reshape(flat[offset:offset + n], l.shape))
        offset += n
    return out


def _prescale_array(x, prescale):
    """Scale one flat/bucketed array before its collective. Dtype-preserving
    (the scale is cast to the operand dtype, so bf16 buckets stay bf16 on
    the wire); integer leaves pass through untouched — a fractional scale
    would silently floor them."""
    if prescale is None or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    return x * jnp.asarray(prescale, x.dtype)


def fused_allreduce(tree, average: bool = True,
                    fusion_threshold: Optional[int] = None,
                    axis_name: str = AXIS,
                    prescale: Optional[float] = None,
                    return_finite: bool = False):
    """Allreduce a pytree with fusion bucketing. Compiled-context only
    (it is the gradient hot path inside the jitted train step).

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves are kept
    whole and routed through the two-allgather sparse path — never flattened
    into dense buckets (their integer indices must not be summed).

    ``prescale`` multiplies every bucket by a scalar *before* the reduce —
    one fused multiply on the already-flattened bucket, not one per leaf —
    which is how gradient accumulation folds its ``1/accum_steps`` into the
    same traversal (the reference's ``backward_passes_per_step`` divides by
    the global microbatch count at the same point). The reduce is linear, so
    pre- and post-scaling are equivalent; prescaling keeps the bucketed tree
    the single thing the collective ever sees.

    ``return_finite=True`` returns ``(reduced_tree, all_finite)`` where
    ``all_finite`` is a scalar bool, True iff every float leaf of EVERY
    rank's input was finite — the in-jit bad-step guard's signal. It is
    folded into the same bucket traversal with **zero extra collectives**:
    the reduce is a sum, and IEEE754 sums propagate any NaN/Inf operand
    into the result (Inf−Inf pairs become NaN, overflow becomes Inf), so
    checking ``isfinite`` on each REDUCED bucket while still flat — one
    pass per bucket, before unfusing — sees every rank's poison through
    the psum that already happened. The flag is therefore identical on
    all replicas, which is exactly what a divergence-free skip-step
    decision needs."""
    from .sparse import IndexedSlices, allreduce_indexed_slices

    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if not leaves:
        return (tree, jnp.ones((), jnp.bool_)) if return_finite else tree
    op = Op.AVERAGE if average else Op.SUM
    reduced: List[Optional[jax.Array]] = [None] * len(leaves)
    finite = jnp.ones((), jnp.bool_)

    def _check(x):
        nonlocal finite
        if return_finite and jnp.issubdtype(x.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(x))

    dense_idx = [i for i, l in enumerate(leaves)
                 if not isinstance(l, IndexedSlices)]
    for i in (i for i in range(len(leaves)) if i not in dense_idx):
        s = leaves[i]
        if prescale is not None:
            s = IndexedSlices(_prescale_array(s.values, prescale),
                              s.indices, s.dense_shape)
        r = allreduce_indexed_slices(
            s, average=average, axis_name=axis_name)
        # Allgathered slices carry every rank's raw values, so a local
        # NaN is literally present in each rank's gathered copy.
        _check(r.values)
        reduced[i] = r

    dense = [leaves[i] for i in dense_idx]
    buckets = plan_buckets(dense, fusion_threshold)
    for bucket in buckets:
        if len(bucket) == 1:
            j = bucket[0]
            r = _reduce_in_trace(
                _prescale_array(dense[j], prescale), op, axis_name)
            _check(r)
            reduced[dense_idx[j]] = r
        else:
            members = [dense[j] for j in bucket]
            flat = _reduce_in_trace(
                _prescale_array(_fuse(members), prescale), op, axis_name)
            _check(flat)
            for j, r in zip(bucket, _unfuse(flat, members)):
                reduced[dense_idx[j]] = r
    out = jax.tree_util.tree_unflatten(treedef, reduced)
    return (out, finite) if return_finite else out
