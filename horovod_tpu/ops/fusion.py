"""Tensor fusion: bucket many small tensors into one flat collective.

Reference semantics (``docs/tensor-fusion.md:6-28``, fusion decision
``mpi_ops.cc:1395-1422``, data movement ``mpi_ops.cc:1024-1096``):

* Only tensors of the **same dtype** fuse (and same device set — moot here:
  everything lives on the world mesh).
* A bucket's total byte size is capped by the fusion threshold
  (default 64 MiB, ``mpi_ops.cc:165``; env ``HOROVOD_FUSION_THRESHOLD``,
  0 disables fusion, ``docs/tensor-fusion.md:24-28``).
* **Request order is preserved**: scanning stops at the first non-fusable
  tensor rather than skipping ahead (``mpi_ops.cc:1414-1419``), so fusion
  never reorders collectives.

TPU-native design: instead of memcpy loops into a persistent staging buffer,
bucketing happens at trace time — each bucket's members are flattened and
concatenated into one flat vector in HBM, reduced with a single XLA
``all-reduce`` over ICI, and split back. XLA fuses the (de)concatenation with
neighbors, so the "fusion buffer" never exists as a separate persistent
allocation. An oversized tensor becomes its own bucket (the reference
likewise falls back to a direct non-fused collective for tensors above the
threshold, ``mpi_ops.cc:1101-1105``).

The same bucket planner also feeds the ZeRO-1 sharded-update plane
(:class:`ZeroPlan`, :func:`fused_reduce_scatter`,
:func:`fused_allgather_params`): reduce-scatter + all-gather spend the same
bytes on the wire as the fused all-reduce while cutting optimizer-state
memory and update FLOPs by the world size (``docs/performance.md``).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import AXIS
from ..utils import config as _config
from ..utils.compat import all_gather_invariant
from .collectives import Op, _reduce_in_trace


@functools.lru_cache(maxsize=512)
def _plan_cached(key: Tuple[Tuple[Tuple[int, ...], str], ...],
                 fusion_threshold: int) -> Tuple[Tuple[int, ...], ...]:
    """The fusion scan, memoized. The plan is a pure function of the leaf
    (shape, dtype) sequence and the threshold, so repeated traces and
    eager per-step calls over the same gradient tree (every step of the
    env-world plane, every re-trace of the compiled one) stop re-walking
    the whole tree. Keyed on resolved values only — the env-var default
    is resolved by the caller, so changing ``HOROVOD_FUSION_THRESHOLD``
    between calls still takes effect."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_dtype = None
    cur_bytes = 0
    for i, (shape, dtype) in enumerate(key):
        nbytes = int(math.prod(shape)) * np.dtype(dtype).itemsize
        fusable = (
            fusion_threshold > 0
            and cur
            and dtype == cur_dtype
            and cur_bytes + nbytes <= fusion_threshold
        )
        if fusable:
            cur.append(i)
            cur_bytes += nbytes
        else:
            if cur:
                buckets.append(cur)
            cur = [i]
            cur_dtype = dtype
            cur_bytes = nbytes
    if cur:
        buckets.append(cur)
    return tuple(tuple(b) for b in buckets)


def plan_buckets(leaves: Sequence[jax.Array],
                 fusion_threshold: Optional[int] = None) -> List[List[int]]:
    """Partition leaf indices into fusion buckets, preserving order.

    Mirrors the coordinator's fusion scan (``mpi_ops.cc:1395-1422``): walk the
    queue in order; fuse while dtype matches and cumulative bytes stay within
    the threshold; close the bucket at the first non-fusable tensor.
    ``fusion_threshold=0`` disables fusion (one bucket per tensor).

    The scan is cached per ``(shapes, dtypes, threshold)`` — see
    :func:`_plan_cached`; callers get a fresh mutable copy each call, so
    mutating a returned plan cannot poison the cache.
    """
    if fusion_threshold is None:
        fusion_threshold = _config.fusion_threshold_bytes()
    key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
                for leaf in leaves)
    return [list(b) for b in _plan_cached(key, int(fusion_threshold))]


def _fuse(leaves: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate([jnp.ravel(l) for l in leaves])


def _unfuse(flat: jax.Array, leaves: Sequence[jax.Array]) -> List[jax.Array]:
    out = []
    offset = 0
    for l in leaves:
        n = int(math.prod(l.shape))
        out.append(jnp.reshape(flat[offset:offset + n], l.shape))
        offset += n
    return out


def _prescale_array(x, prescale):
    """Scale one flat/bucketed array before its collective. Dtype-preserving
    (the scale is cast to the operand dtype, so bf16 buckets stay bf16 on
    the wire); integer leaves pass through untouched — a fractional scale
    would silently floor them."""
    if prescale is None or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    return x * jnp.asarray(prescale, x.dtype)


def fused_allreduce(tree, average: bool = True,
                    fusion_threshold: Optional[int] = None,
                    axis_name: str = AXIS,
                    prescale: Optional[float] = None,
                    return_finite: bool = False):
    """Allreduce a pytree with fusion bucketing. Compiled-context only
    (it is the gradient hot path inside the jitted train step).

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves are kept
    whole and routed through the two-allgather sparse path — never flattened
    into dense buckets (their integer indices must not be summed).

    ``prescale`` multiplies every bucket by a scalar *before* the reduce —
    one fused multiply on the already-flattened bucket, not one per leaf —
    which is how gradient accumulation folds its ``1/accum_steps`` into the
    same traversal (the reference's ``backward_passes_per_step`` divides by
    the global microbatch count at the same point). The reduce is linear, so
    pre- and post-scaling are equivalent; prescaling keeps the bucketed tree
    the single thing the collective ever sees.

    ``return_finite=True`` returns ``(reduced_tree, all_finite)`` where
    ``all_finite`` is a scalar bool, True iff every float leaf of EVERY
    rank's input was finite — the in-jit bad-step guard's signal. It is
    folded into the same bucket traversal with **zero extra collectives**:
    the reduce is a sum, and IEEE754 sums propagate any NaN/Inf operand
    into the result (Inf−Inf pairs become NaN, overflow becomes Inf), so
    checking ``isfinite`` on each REDUCED bucket while still flat — one
    pass per bucket, before unfusing — sees every rank's poison through
    the psum that already happened. The flag is therefore identical on
    all replicas, which is exactly what a divergence-free skip-step
    decision needs."""
    from .sparse import IndexedSlices, allreduce_indexed_slices

    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if not leaves:
        return (tree, jnp.ones((), jnp.bool_)) if return_finite else tree
    op = Op.AVERAGE if average else Op.SUM
    reduced: List[Optional[jax.Array]] = [None] * len(leaves)
    finite = jnp.ones((), jnp.bool_)

    def _check(x):
        nonlocal finite
        if return_finite and jnp.issubdtype(x.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(x))

    dense_idx = [i for i, l in enumerate(leaves)
                 if not isinstance(l, IndexedSlices)]
    for i in (i for i in range(len(leaves)) if i not in dense_idx):
        s = leaves[i]
        if prescale is not None:
            s = IndexedSlices(_prescale_array(s.values, prescale),
                              s.indices, s.dense_shape)
        r = allreduce_indexed_slices(
            s, average=average, axis_name=axis_name)
        # Allgathered slices carry every rank's raw values, so a local
        # NaN is literally present in each rank's gathered copy.
        _check(r.values)
        reduced[i] = r

    dense = [leaves[i] for i in dense_idx]
    buckets = plan_buckets(dense, fusion_threshold)
    for bucket in buckets:
        if len(bucket) == 1:
            j = bucket[0]
            r = _reduce_in_trace(
                _prescale_array(dense[j], prescale), op, axis_name)
            _check(r)
            reduced[dense_idx[j]] = r
        else:
            members = [dense[j] for j in bucket]
            flat = _reduce_in_trace(
                _prescale_array(_fuse(members), prescale), op, axis_name)
            _check(flat)
            for j, r in zip(bucket, _unfuse(flat, members)):
                reduced[dense_idx[j]] = r
    out = jax.tree_util.tree_unflatten(treedef, reduced)
    return (out, finite) if return_finite else out


# ---------------------------------------------------------------------------
# ZeRO-1 sharded-update plane (Rajbhandari et al. 2020; Xu et al. 2020,
# "Automatic Cross-Replica Sharding of Weight Update Computation"): the same
# bucket planner that feeds the fused all-reduce instead feeds a
# reduce-scatter — every rank receives the REDUCED 1/N slice of each flat
# bucket, applies the optimizer update to its slice only, and the updated
# slices ride one all-gather back into the full tree. Bytes on the wire are
# unchanged (ring all-reduce = reduce-scatter + all-gather); optimizer-state
# memory and update FLOPs drop by the world size.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ZeroPlan:
    """Static layout of a tree's rank-sharded flat buckets.

    Everything here is trace-time constant (hashable, usable as pytree aux
    data): ``buckets`` are :func:`plan_buckets` index groups over the
    flattened tree, ``sizes``/``padded`` the true and rank-padded flat
    length per bucket (``padded[i]`` is the smallest multiple of
    ``nshards`` >= ``sizes[i]``, so ``lax.psum_scatter(tiled=True)`` splits
    evenly), ``shapes``/``dtypes`` the member leaves' layout for unfusing,
    and ``treedef`` the original tree structure."""

    buckets: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    padded: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    treedef: Any
    nshards: int

    def shard_len(self, i: int) -> int:
        return self.padded[i] // self.nshards

    def shard_shapes(self):
        """Per-bucket ``(nshards, shard_len)`` — the stacked layout the
        sharded optimizer state stores (leading axis split one shard per
        rank over the world mesh)."""
        return tuple((self.nshards, self.shard_len(i))
                     for i in range(len(self.buckets)))


def plan_zero(tree, nshards: int,
              fusion_threshold: Optional[int] = None) -> ZeroPlan:
    """Build the sharded-update layout for ``tree`` over ``nshards`` ranks.

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves cannot
    be flattened into rank-sharded dense buckets (their integer indices
    must not be summed, and a slice of a slice has no owner rank) — a tree
    carrying them raises; densify first (``sparse_as_dense``) or keep the
    replicated optimizer for sparse models."""
    from .sparse import IndexedSlices
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if any(isinstance(l, IndexedSlices) for l in leaves):
        raise ValueError(
            "ZeRO sharded updates require dense gradients: an "
            "IndexedSlices leaf cannot be flattened into rank-sharded "
            "buckets (densify with sparse_as_dense=True, or use the "
            "replicated DistributedOptimizer for sparse models)")
    if nshards < 1:
        raise ValueError(f"nshards must be >= 1, got {nshards}")
    buckets = plan_buckets(leaves, fusion_threshold)
    sizes = []
    padded = []
    for b in buckets:
        n = sum(int(math.prod(leaves[j].shape)) for j in b)
        sizes.append(n)
        padded.append(-(-n // nshards) * nshards)
    return ZeroPlan(
        buckets=tuple(tuple(b) for b in buckets),
        sizes=tuple(sizes),
        padded=tuple(padded),
        shapes=tuple(tuple(l.shape) for l in leaves),
        dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
        treedef=treedef,
        nshards=nshards,
    )


def _fuse_bucket(leaves, plan: ZeroPlan, i: int):
    """Flatten bucket ``i``'s members into one rank-padded flat vector."""
    flat = _fuse([leaves[j] for j in plan.buckets[i]])
    pad = plan.padded[i] - plan.sizes[i]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def fused_reduce_scatter(tree, plan: ZeroPlan, *,
                         average: bool = True,
                         axis_name: str = AXIS,
                         prescale: Optional[float] = None,
                         return_finite: bool = False):
    """Reduce-scatter a pytree into this rank's flat bucket shards.

    Each bucket is flattened, zero-padded to a multiple of the world size,
    optionally prescaled (one fused multiply on the flat bucket — gradient
    accumulation's ``1/accum_steps`` and ``average``'s ``1/size`` fold into
    the same scalar), and fed to one ``lax.psum_scatter`` — rank ``r``
    receives the REDUCED slice ``flat[r*s:(r+1)*s]``. Returns the per-bucket
    shard list (order = plan order).

    ``return_finite=True`` additionally returns a **rank-local** all-finite
    scalar derived from the already-reduced shards: IEEE sums propagate any
    rank's NaN/Inf into the reduced value at that position, which lands on
    exactly one rank's shard — so the flag differs per rank and only the
    AND over ranks is the world-wide verdict. :func:`fused_allgather_params`
    folds that AND into the all-gather the updated shards already ride
    (``and_finite=``), keeping the bad-step guard at zero extra collectives
    in ZeRO mode too.
    """
    leaves = plan.treedef.flatten_up_to(tree)
    scale = None
    if average and plan.nshards > 1:
        scale = 1.0 / plan.nshards
    if prescale is not None:
        scale = prescale if scale is None else scale * prescale
    shards = []
    finite = jnp.ones((), jnp.bool_)
    for i in range(len(plan.buckets)):
        flat = _prescale_array(_fuse_bucket(leaves, plan, i), scale)
        if plan.nshards > 1:
            shard = jax.lax.psum_scatter(flat, axis_name, tiled=True)
        else:
            shard = flat  # single shard: the reduce is the identity
        if return_finite and jnp.issubdtype(shard.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(shard))
        shards.append(shard)
    return (shards, finite) if return_finite else shards


def shard_params(tree, plan: ZeroPlan, *, axis_name: str = AXIS,
                 rank: Optional[int] = None):
    """Slice this rank's flat bucket shards out of a replicated pytree
    (no collective — each rank takes ``flat[rank*s:(rank+1)*s]``). The
    owner index is ``lax.axis_index`` in-trace, or the static ``rank``
    the env-world plane passes (one process = one shard)."""
    leaves = plan.treedef.flatten_up_to(tree)
    idx = jax.lax.axis_index(axis_name) if rank is None else rank
    shards = []
    for i in range(len(plan.buckets)):
        flat = _fuse_bucket(leaves, plan, i)
        s = plan.shard_len(i)
        if plan.nshards == 1:
            shards.append(flat)
        elif rank is None:
            shards.append(jax.lax.dynamic_slice(flat, (idx * s,), (s,)))
        else:
            shards.append(flat[rank * s:(rank + 1) * s])
    return shards


def _unfuse_flat(flats, plan: ZeroPlan):
    """Rebuild the original tree from per-bucket UNPADDED flat vectors."""
    reduced: List[Optional[jax.Array]] = [None] * len(plan.shapes)
    for i, bucket in enumerate(plan.buckets):
        flat = flats[i]
        offset = 0
        for j in bucket:
            n = int(math.prod(plan.shapes[j]))
            reduced[j] = jnp.reshape(flat[offset:offset + n], plan.shapes[j])
            offset += n
    return plan.treedef.unflatten(reduced)


def fused_allgather_params(shards, plan: ZeroPlan, *,
                           axis_name: str = AXIS,
                           and_finite: Optional[jax.Array] = None):
    """Rebuild a full pytree from every rank's updated flat bucket shards:
    one ``all_gather`` per bucket, padding stripped, leaves reshaped.

    ``and_finite`` (a rank-LOCAL boolean from
    :func:`fused_reduce_scatter`'s ``return_finite``) rides the same
    gather: the scalar is appended as one extra element to the first
    inexact bucket's shard, so after gathering every rank sees every
    rank's flag and the AND is replica-identical — the world-wide
    bad-step verdict with **zero** extra collectives. Returns
    ``(tree, all_finite)`` in that case, else just ``tree``.
    """
    nb = len(plan.buckets)
    flag_bucket = None
    if and_finite is not None:
        flag_bucket = next(
            (i for i in range(nb)
             if jnp.issubdtype(jnp.dtype(plan.dtypes[plan.buckets[i][0]]),
                               jnp.inexact)), None)
    shards = list(shards)
    if flag_bucket is not None:
        flag = and_finite.astype(shards[flag_bucket].dtype).reshape(1)
        shards[flag_bucket] = jnp.concatenate([shards[flag_bucket], flag])
    flats = []
    all_finite = None
    for i in range(nb):
        if plan.nshards > 1:
            gathered = all_gather_invariant(shards[i], axis_name, tiled=True)
        else:
            gathered = shards[i]
        if i == flag_bucket:
            s = plan.shard_len(i)
            blocks = gathered.reshape(plan.nshards, s + 1)
            # 1.0/0.0 flags by construction (isfinite output cast to the
            # bucket dtype) — exactly representable in every float dtype.
            all_finite = jnp.all(blocks[:, -1].astype(jnp.float32) > 0.5)
            gathered = blocks[:, :s].reshape(-1)
        flats.append(gathered[:plan.sizes[i]])
    out = _unfuse_flat(flats, plan)
    if and_finite is None:
        return out
    if all_finite is None:
        # No inexact bucket: an all-integer tree is finite by construction,
        # so the local flag (constant True) is already the global verdict.
        all_finite = and_finite
    return out, all_finite
