"""Tensor fusion: bucket many small tensors into one flat collective.

Reference semantics (``docs/tensor-fusion.md:6-28``, fusion decision
``mpi_ops.cc:1395-1422``, data movement ``mpi_ops.cc:1024-1096``):

* Only tensors of the **same dtype** fuse (and same device set — moot here:
  everything lives on the world mesh).
* A bucket's total byte size is capped by the fusion threshold
  (default 64 MiB, ``mpi_ops.cc:165``; env ``HOROVOD_FUSION_THRESHOLD``,
  0 disables fusion, ``docs/tensor-fusion.md:24-28``).
* **Request order is preserved**: scanning stops at the first non-fusable
  tensor rather than skipping ahead (``mpi_ops.cc:1414-1419``), so fusion
  never reorders collectives.

TPU-native design: instead of memcpy loops into a persistent staging buffer,
bucketing happens at trace time — each bucket's members are flattened and
concatenated into one flat vector in HBM, reduced with a single XLA
``all-reduce`` over ICI, and split back. XLA fuses the (de)concatenation with
neighbors, so the "fusion buffer" never exists as a separate persistent
allocation. An oversized tensor becomes its own bucket (the reference
likewise falls back to a direct non-fused collective for tensors above the
threshold, ``mpi_ops.cc:1101-1105``).

The same bucket planner also feeds the ZeRO-1 sharded-update plane
(:class:`ZeroPlan`, :func:`fused_reduce_scatter`,
:func:`fused_allgather_params`): reduce-scatter + all-gather spend the same
bytes on the wire as the fused all-reduce while cutting optimizer-state
memory and update FLOPs by the world size (``docs/performance.md``).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import AXIS
from ..utils import config as _config
from ..utils.compat import all_gather_invariant, axis_size
from .collectives import Op, _reduce_in_trace


def _greedy_scan(key, order, fusion_threshold: int):
    """The fusion scan over leaves visited in ``order``: fuse while the
    dtype (and, on an N-D mesh, the reduce-axis group — see
    :func:`plan_grad_sync`) matches and cumulative bytes stay within the
    threshold; close the bucket at the first non-fusable tensor
    (``mpi_ops.cc:1414-1419`` — never look ahead, never reorder within the
    visit order). ``key[i]`` is ``(shape, dtype)`` or
    ``(shape, dtype, group)``; two leaves fuse only when BOTH dtype and
    group agree — a bucket rides exactly one collective, so its members
    must share the axes that collective reduces over."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_dtype = None
    cur_bytes = 0
    for i in order:
        shape, dtype = key[i][0], key[i][1:]
        nbytes = int(math.prod(shape)) * np.dtype(key[i][1]).itemsize
        fusable = (
            fusion_threshold > 0
            and cur
            and dtype == cur_dtype
            and cur_bytes + nbytes <= fusion_threshold
        )
        if fusable:
            cur.append(i)
            cur_bytes += nbytes
        else:
            if cur:
                buckets.append(cur)
            cur = [i]
            cur_dtype = dtype
            cur_bytes = nbytes
    if cur:
        buckets.append(cur)
    return tuple(tuple(b) for b in buckets)


@functools.lru_cache(maxsize=512)
def _plan_cached(key: Tuple[Tuple[Tuple[int, ...], str], ...],
                 fusion_threshold: int) -> Tuple[Tuple[int, ...], ...]:
    """The fusion scan, memoized. The plan is a pure function of the leaf
    (shape, dtype) sequence and the threshold, so repeated traces and
    eager per-step calls over the same gradient tree (every step of the
    env-world plane, every re-trace of the compiled one) stop re-walking
    the whole tree. Keyed on resolved values only — the env-var default
    is resolved by the caller, so changing ``HOROVOD_FUSION_THRESHOLD``
    between calls still takes effect."""
    return _greedy_scan(key, range(len(key)), fusion_threshold)


def plan_buckets(leaves: Sequence[jax.Array],
                 fusion_threshold: Optional[int] = None,
                 groups: Optional[Sequence[Any]] = None) -> List[List[int]]:
    """Partition leaf indices into fusion buckets, preserving order.

    Mirrors the coordinator's fusion scan (``mpi_ops.cc:1395-1422``): walk the
    queue in order; fuse while dtype matches and cumulative bytes stay within
    the threshold; close the bucket at the first non-fusable tensor.
    ``fusion_threshold=0`` disables fusion (one bucket per tensor).

    ``groups`` (optional, one hashable per leaf) adds a second fusion key
    next to dtype: leaves fuse only within the same group. This is how the
    N-D mesh plane keeps tp-sharded weight gradients (psum over ``dp``
    only) out of the buckets carrying replicated leaves (psum over the
    full mesh) — a bucket rides ONE collective, so its members must agree
    on the reduce axes (:func:`plan_grad_sync` builds the keys).

    The scan is cached per ``(shapes, dtypes, groups, threshold)`` — see
    :func:`_plan_cached`; callers get a fresh mutable copy each call, so
    mutating a returned plan cannot poison the cache.
    """
    if fusion_threshold is None:
        fusion_threshold = _config.fusion_threshold_bytes()
    if groups is None:
        key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
                    for leaf in leaves)
    else:
        if len(groups) != len(leaves):
            raise ValueError(
                f"groups must align with leaves: {len(groups)} group keys "
                f"for {len(leaves)} leaves")
        key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)), g)
                    for leaf, g in zip(leaves, groups))
    return [list(b) for b in _plan_cached(key, int(fusion_threshold))]


# ---------------------------------------------------------------------------
# Backward-overlapped emission (ISSUE 6 tentpole; the core Horovod trick,
# Sergeev & Del Balso 2018 §3): issue one collective per bucket AS ITS
# GRADIENTS COMPLETE instead of one fused traversal after backward. On the
# compiled plane the mechanism is data dependencies + optimization_barrier
# pins: buckets group leaves ADJACENT IN BACKWARD-COMPLETION ORDER (so a
# bucket's collective depends only on an early prefix of the backward), and
# each bucket's operand is barrier-chained to the previous bucket's result —
# which (a) fixes the issue order deterministically, (b) stops XLA's
# all-reduce combiner from re-merging the buckets into one post-backward
# blob, and (c) leaves XLA's latency-hiding scheduler free to hoist every
# collective behind the remaining backward compute (it does: the HLO pin in
# tests/test_overlap_wire.py shows each bucket's collective scheduled before
# the last backward op of the module).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketSchedule:
    """An ordered fusion plan: ``buckets`` are leaf-index groups (over the
    same flattened tree ``plan_buckets`` scans) built by walking the leaves
    in ``order`` — backward-completion order from
    :func:`probe_grad_order` — so bucket k's members finish together and
    its collective can fire while buckets k+1... are still back-propagating.
    A pure function of (shapes, dtypes, threshold, order): deterministic
    across processes and across cache hits."""

    buckets: Tuple[Tuple[int, ...], ...]
    order: Tuple[int, ...]
    threshold: int


@functools.lru_cache(maxsize=512)
def _schedule_cached(key, order, fusion_threshold: int):
    return _greedy_scan(key, order, fusion_threshold)


def plan_schedule(leaves: Sequence[jax.Array],
                  grad_order: Optional[Sequence[int]] = None,
                  fusion_threshold: Optional[int] = None,
                  groups: Optional[Sequence[Any]] = None) -> BucketSchedule:
    """Build the overlap emission schedule for ``leaves``.

    ``grad_order`` is the backward-completion permutation of leaf indices
    (:func:`probe_grad_order`); None falls back to flatten order, which
    degrades to the non-overlapped grouping. ``groups`` adds the same
    per-leaf reduce-axis fusion key :func:`plan_buckets` takes — on an N-D
    mesh leaves only fuse within their spec group. Same caching contract
    as :func:`plan_buckets` — keyed on resolved (shapes, dtypes, groups,
    order, threshold), so an env-var threshold flip between calls still
    invalidates."""
    if fusion_threshold is None:
        fusion_threshold = _config.fusion_threshold_bytes()
    if groups is None:
        key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
                    for leaf in leaves)
    else:
        key = tuple((tuple(leaf.shape), str(jnp.dtype(leaf.dtype)), g)
                    for leaf, g in zip(leaves, groups))
    order = (tuple(range(len(key))) if grad_order is None
             else tuple(int(i) for i in grad_order))
    if sorted(order) != list(range(len(key))):
        raise ValueError(
            f"grad_order must be a permutation of the {len(key)} leaf "
            f"indices; got {order}")
    return BucketSchedule(
        buckets=_schedule_cached(key, order, int(fusion_threshold)),
        order=order, threshold=int(fusion_threshold))


def probe_grad_order(grad_fn, *args, **kwargs) -> Optional[Tuple[int, ...]]:
    """Backward-completion order of a gradient tree's leaves, from a
    one-time abstract trace (no FLOPs): ``grad_fn(*args)`` must return the
    grad tree; each output leaf is ranked by the position of its defining
    equation in the traced jaxpr — the order the backward pass materializes
    it. Leaves whose producer cannot be identified (literals, forwarded
    inputs, leaves fused into one opaque sub-jaxpr such as a rolled scan)
    keep flatten order as a stable tie-break, so the probe degrades to the
    non-overlapped schedule rather than guessing. Returns None when the
    function cannot be traced outside its collective context (e.g. a model
    with cross-replica BatchNorm probed without its axis bound) — callers
    fall back to flatten order."""
    try:
        closed = jax.make_jaxpr(grad_fn)(*args, **kwargs)
    except Exception:  # noqa: BLE001 — probe is best-effort by contract
        return None
    jaxpr = closed.jaxpr
    pos = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            pos[v] = i
    outvars = jaxpr.outvars

    def _rank(k):
        v = outvars[k]
        # Literal outvars (e.g. the zero cotangent of a leaf the loss never
        # reads) are unhashable on older jax — they take the flatten-order
        # fallback, same as any other unrankable leaf.
        if not isinstance(v, jax.core.Var):
            return (-1, k)
        return (pos.get(v, -1), k)

    return tuple(sorted(range(len(outvars)), key=_rank))


@functools.lru_cache(maxsize=512)
def _emit_order_cached(buckets, grad_order):
    ready = []
    pos = {leaf: p for p, leaf in enumerate(grad_order)}
    for b in buckets:
        ready.append(max(pos.get(j, j) for j in b))
    return tuple(sorted(range(len(buckets)),
                        key=lambda i: (ready[i], i)))


def zero_emit_order(plan: "ZeroPlan",
                    grad_order: Optional[Sequence[int]]) -> Tuple[int, ...]:
    """Emission order of a :class:`ZeroPlan`'s buckets under overlap:
    sorted by READINESS (the latest backward-completion position among the
    bucket's members). Unlike the all-reduce plane's
    :class:`BucketSchedule`, ZeRO bucket MEMBERSHIP never changes — the
    plan defines the sharded optimizer-state layout and the world-agnostic
    checkpoint form, so overlap may only reorder which bucket's
    reduce-scatter issues first, never regroup leaves."""
    if grad_order is None:
        return tuple(range(len(plan.buckets)))
    return _emit_order_cached(plan.buckets, tuple(int(i)
                                                  for i in grad_order))


def _barrier_chain(operand, prev):
    """Pin emission order: barrier the next bucket's operand against the
    previous bucket's reduced result. Creates the data dependency that (a)
    makes the cross-bucket issue order deterministic and (b) keeps XLA's
    collective combiner from merging the per-bucket collectives back into
    one post-backward blob (combining requires independence)."""
    if prev is None:
        return operand
    operand, _ = jax.lax.optimization_barrier((operand, prev))
    return operand


def _fuse(leaves: Sequence[jax.Array]) -> jax.Array:
    return jnp.concatenate([jnp.ravel(l) for l in leaves])


def _unfuse(flat: jax.Array, leaves: Sequence[jax.Array]) -> List[jax.Array]:
    out = []
    offset = 0
    for l in leaves:
        n = int(math.prod(l.shape))
        out.append(jnp.reshape(flat[offset:offset + n], l.shape))
        offset += n
    return out


def _prescale_array(x, prescale):
    """Scale one flat/bucketed array before its collective. Dtype-preserving
    on the outside (the result returns in the operand dtype, so bf16 buckets
    stay bf16 on the wire), but sub-fp32 buckets are scaled IN fp32 — a
    bf16 multiply quantizes the scale itself (bf16(1/3) carries 8 mantissa
    bits) and double-rounds, so the fp32 product with a single final cast
    is strictly more accurate for the same wire bytes. Integer leaves pass
    through untouched — a fractional scale would silently floor them."""
    if prescale is None or not jnp.issubdtype(x.dtype, jnp.inexact):
        return x
    if jnp.dtype(x.dtype).itemsize < 4:
        return (x.astype(jnp.float32)
                * jnp.asarray(prescale, jnp.float32)).astype(x.dtype)
    return x * jnp.asarray(prescale, x.dtype)


# ---------------------------------------------------------------------------
# Low-precision wire formats: cast-on-send, fp32-accumulated results.
# The collective itself runs in the wire dtype (half/quarter the ICI bytes);
# every scale that touches the bucket (average's 1/size, accumulation's 1/N,
# fp8's dynamic scale) is applied in fp32 BEFORE the cast, and the reduced
# result is cast back to the bucket's original dtype immediately after — so
# everything downstream of the wire (shard updates, optimizer math) runs at
# full precision and the only loss is the one quantization on send.
# ---------------------------------------------------------------------------

# fp8 (e4m3) headroom: values are scaled so the WORST-CASE reduced sum
# (every rank at amax, same sign) lands at half of the 448 format max —
# range is cheap in e4m3 (17 binades) and the margin keeps rounding in the
# reduction from saturating into NaN (e4m3fn has no Inf).
_FP8_MARGIN = 224.0

_WIRE_ALIASES = {
    "bf16": "bfloat16", "bfloat16": "bfloat16",
    "fp8": "float8_e4m3fn", "fp8_e4m3": "float8_e4m3fn",
    "f8e4m3": "float8_e4m3fn", "float8_e4m3fn": "float8_e4m3fn",
}
_WIRE_NONE = (None, "", "none", "fp32", "f32", "float32")


def resolve_wire_dtype(spec):
    """Normalize a wire-format spec to a jnp dtype (or None = full
    precision). Accepts the knob spellings (``"bf16"``, ``"fp8"``), the
    canonical dtype names, actual dtypes, or None/``"fp32"``. Unknown
    specs raise eagerly with the supported set named — a typo must not
    silently train at full precision."""
    if spec in _WIRE_NONE:
        return None
    key = spec if isinstance(spec, str) else jnp.dtype(spec).name
    key = key.strip().lower()
    if key in _WIRE_NONE:
        return None
    name = _WIRE_ALIASES.get(key)
    if name is None:
        raise ValueError(
            f"unknown wire_dtype {spec!r}: supported are 'bf16', 'fp8' "
            f"(e4m3 with per-bucket dynamic scaling), or None/'fp32' for "
            f"full precision")
    return jnp.dtype(name)


def wire_dtype_name(wire) -> str:
    """Knob spelling of a resolved wire dtype (for stamps/JSON lines)."""
    w = resolve_wire_dtype(wire)
    if w is None:
        return "fp32"
    return "bf16" if w == jnp.dtype(jnp.bfloat16) else "fp8"


def _wire_applies(dtype, wire) -> bool:
    """A bucket rides the wire format only when it is float and strictly
    wider than the wire dtype — bf16 buckets under a bf16 wire are already
    at wire width (no cast), integers never quantize."""
    return (wire is not None
            and jnp.issubdtype(dtype, jnp.floating)
            and jnp.dtype(dtype).itemsize > jnp.dtype(wire).itemsize)


def _wire_exchange(flat, axis_names, wire, world, reduce_fn, prescale=None):
    """One wire-format reduction, shared by the all-reduce and ZeRO
    planes: fp32 prescale → (fp8: dynamic scale) → ONE cast on send →
    ``reduce_fn`` in the wire dtype → fp32 result, scale divided back out,
    cast to the original dtype — fp32 accumulation for everything
    downstream of the wire.

    fp8 additionally exchanges one scalar ``pmax`` per bucket (the only
    collective any wire format adds): the per-bucket dynamic scale must be
    identical on every rank or the scaled values would not share a unit,
    and the sum of ``world`` in-range values must stay in range — so the
    scale is ``margin / (world * global_amax)``, applied in fp32 and
    divided back out of the fp32 result."""
    orig = flat.dtype
    x = flat.astype(jnp.float32) if orig != jnp.float32 else flat
    if prescale is not None:
        x = x * jnp.asarray(prescale, jnp.float32)
    scale = None
    if jnp.dtype(wire).itemsize == 1:
        amax = jax.lax.pmax(jnp.max(jnp.abs(x)), axis_names)
        scale = jnp.where(amax > 0, _FP8_MARGIN / (world * amax), 1.0)
        x = x * scale
    out = reduce_fn(x.astype(wire)).astype(jnp.float32)
    if scale is not None:
        out = out / scale
    return out.astype(orig)


def _wire_sum(flat, axis_names, wire, prescale=None):
    """Wire-format psum over ``axis_names`` (see :func:`_wire_exchange`)."""
    world = 1
    for a in ((axis_names,) if isinstance(axis_names, str)
              else tuple(axis_names)):
        world *= int(axis_size(a))
    return _wire_exchange(
        flat, axis_names, wire, world,
        lambda w: jax.lax.psum(w, axis_names), prescale=prescale)


def _wire_scatter(flat, axis_name, wire, nshards, prescale=None):
    """Wire-format ``psum_scatter`` (see :func:`_wire_exchange`): this
    rank's shard comes back in the bucket's original dtype, so the
    optimizer update accumulates in fp32 even when the wire carried
    bf16/fp8."""
    return _wire_exchange(
        flat, axis_name, wire, nshards,
        lambda w: jax.lax.psum_scatter(w, axis_name, tiled=True),
        prescale=prescale)


# ---------------------------------------------------------------------------
# Axis-aware collective planning (ISSUE 8 tentpole): on an N-D named mesh
# ('dp', 'tp', ...) the per-leaf gradient-sync decision is a PLAN, not a
# hard-coded world axis. Each leaf's PartitionSpec determines (a) which axes
# its gradient must be summed over — every mesh axis the leaf is REPLICATED
# across — and (b) the averaging denominator, including the tp
# psum-transpose correction (under full-manual shard_map the transpose of
# the row-parallel psum is psum, so tp-sharded weight grads arrive
# multiplied by tp — the rule parallel/mesh.grad_sync_by_spec pinned
# empirically). Leaves group by that decision: tp-sharded weight grads psum
# over dp ONLY, replicated leaves keep the full-mesh path, and the two
# never share a bucket.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GradSync:
    """One leaf's gradient-sync decision on an N-D mesh (hashable — it is
    the fusion-group key :func:`plan_buckets` scans on).

    ``psum``: mesh axes the gradient is summed over (the leaf is
    replicated across exactly these). ``shard``: mesh axes the leaf itself
    is sharded over (``psum`` ∪ ``shard`` = the mesh axes minus
    ``skip_axes``). ``denom``: the averaging denominator — the product of
    the ``psum`` axis sizes times the tp correction for tp-sharded leaves.
    """

    psum: Tuple[str, ...]
    shard: Tuple[str, ...]
    denom: int


def _spec_axes(spec) -> set:
    """Mesh axis names a PartitionSpec references (entries may be a name,
    a tuple of names, or None)."""
    axes = set()
    for s in (spec or ()):
        if s is None:
            continue
        axes.update((s,) if isinstance(s, str) else s)
    return axes


def plan_grad_sync(specs: Sequence[Any], mesh,
                   *, skip_axes: Tuple[str, ...] = ()) -> List[GradSync]:
    """Per-leaf :class:`GradSync` for a flat list of ``PartitionSpec``s
    over ``mesh`` (a named N-D mesh). The decision mirrors
    ``parallel/mesh.grad_sync_by_spec`` exactly — psum over every mesh
    axis the leaf is replicated across (minus ``skip_axes``), averaged by
    the product of those axis sizes, with the extra ``1/tp`` on tp-sharded
    leaves (the psum-transpose factor) folded into ``denom`` so the whole
    correction rides the bucket's one fused prescale multiply."""
    mesh_axes = tuple(mesh.axis_names)
    sizes = dict(mesh.shape)
    out = []
    for spec in specs:
        leaf_axes = _spec_axes(spec)
        over = tuple(a for a in mesh_axes
                     if a not in leaf_axes and a not in skip_axes)
        shard = tuple(a for a in mesh_axes
                      if a in leaf_axes and a not in skip_axes)
        denom = 1
        for a in over:
            denom *= int(sizes[a])
        if "tp" in leaf_axes and "tp" in sizes:
            denom *= int(sizes["tp"])
        out.append(GradSync(psum=over, shard=shard, denom=denom))
    return out


def plan_exchange(leaves: Sequence[Any], *, world_size: int,
                  axis_name: str = AXIS,
                  fusion_threshold: Optional[int] = None):
    """The host-plane (env-world) view of the gradient-sync plan: the
    SAME :class:`GradSync` data the compiled executors interpret,
    specialized to the coordinator's 1-D world. Every rank computes a
    full local gradient — every leaf is replicated across the whole
    world — so each leaf's decision is
    ``GradSync(psum=(axis_name,), shard=(), denom=world_size)`` and
    bucket membership comes from the same fusion scan
    (:func:`plan_buckets` with the sync as the group key; one group, so
    the scan degrades to the classic dtype+threshold walk and existing
    bucket layouts are unchanged). Returns ``(buckets, syncs)``.

    One planner, two executors: the compiled plane realizes a sync with
    ``lax.psum`` + a ``1/denom`` prescale; the host executor realizes
    the identical denominator through the coordinator's AVERAGE op (an
    explicit post-scale if a future planner's denom ever disagrees with
    the world size) — membership and denominators can never drift
    between the two because both read this object."""
    syncs = [GradSync(psum=(axis_name,), shard=(),
                      denom=int(world_size)) for _ in leaves]
    return plan_buckets(leaves, fusion_threshold, groups=syncs), syncs


def _grouped_allreduce(leaves, treedef, syncs: Sequence[GradSync],
                       fusion_threshold, prescale, return_finite, wire,
                       overlap_on: bool, grad_order):
    """The N-D (spec-grouped) half of :func:`fused_allreduce`: leaves
    bucket within their :class:`GradSync` group (same psum axes, same
    denominator), each bucket rides ONE ``lax.psum`` over its group's
    axes, and the group's ``1/denom`` average folds into the same fp32
    prescale multiply the accumulation scale uses.

    ``return_finite``: buckets psum'd over the FULL reduce set propagate
    any rank's NaN/Inf to every rank, so their flags are mesh-consistent
    for free; buckets reduced over a strict subset (tp-sharded weight
    grads, psum over dp only) leave per-rank flags — those are folded
    with one scalar ``pmin`` over the missing axes, the only collective
    the guard adds on the hybrid plane (documented in
    docs/performance.md; the 1-D plane stays at zero extra)."""
    # GradSync is frozen/hashable — the object IS the fusion-group key,
    # so the allreduce and ZeRO planes cannot drift on what "same group"
    # means (plan_zero passes the same objects).
    groups = list(syncs)
    if overlap_on:
        order = None if grad_order is None \
            else tuple(int(i) for i in grad_order)
        buckets = [list(b) for b in
                   plan_schedule(leaves, order, fusion_threshold,
                                 groups=groups).buckets]
    else:
        buckets = plan_buckets(leaves, fusion_threshold, groups=groups)

    # The full reduce set: flags from buckets summed over all of it are
    # identical on every rank; anything less needs the pmin fold below.
    all_axes = set()
    for s in syncs:
        all_axes.update(s.psum)
    reduced: List[Optional[jax.Array]] = [None] * len(leaves)
    finite_full = jnp.ones((), jnp.bool_)
    finite_partial = jnp.ones((), jnp.bool_)
    missing_union: set = set()
    prev = None
    for bucket in buckets:
        sync = syncs[bucket[0]]
        if len(bucket) == 1:
            operand = leaves[bucket[0]]
        else:
            operand = _fuse([leaves[j] for j in bucket])
        if overlap_on and len(buckets) > 1:
            operand = _barrier_chain(operand, prev)
        eff = prescale
        if sync.denom > 1:
            inv = 1.0 / sync.denom
            eff = inv if eff is None else eff * inv
        if sync.psum:
            if _wire_applies(operand.dtype, wire):
                r = _wire_sum(operand, sync.psum, wire, prescale=eff)
            else:
                r = jax.lax.psum(_prescale_array(operand, eff), sync.psum)
        else:
            # Fully sharded across every mesh axis: nothing to exchange,
            # only the correction scale applies.
            r = _prescale_array(operand, eff)
        if overlap_on:
            prev = r
        if return_finite and jnp.issubdtype(r.dtype, jnp.inexact):
            flag = jnp.all(jnp.isfinite(r))
            missing = all_axes - set(sync.psum)
            if missing:
                finite_partial = finite_partial & flag
                missing_union.update(missing)
            else:
                finite_full = finite_full & flag
        if len(bucket) == 1:
            reduced[bucket[0]] = r
        else:
            members = [leaves[j] for j in bucket]
            for j, rr in zip(bucket, _unfuse(r, members)):
                reduced[j] = rr
    out = treedef.unflatten(reduced)
    if not return_finite:
        return out
    if missing_union:
        finite_partial = jax.lax.pmin(
            finite_partial.astype(jnp.int32),
            tuple(sorted(missing_union))) > 0
    return out, finite_full & finite_partial


def fused_allreduce(tree, average: bool = True,
                    fusion_threshold: Optional[int] = None,
                    axis_name: str = AXIS,
                    prescale: Optional[float] = None,
                    return_finite: bool = False,
                    wire_dtype=None,
                    overlap: bool = False,
                    grad_order: Optional[Sequence[int]] = None,
                    reduce_axes: Optional[Sequence[GradSync]] = None):
    """Allreduce a pytree with fusion bucketing. Compiled-context only
    (it is the gradient hot path inside the jitted train step).

    ``reduce_axes`` (a per-leaf :class:`GradSync` list from
    :func:`plan_grad_sync`, aligned with the tree's flatten order) switches
    to the N-D spec-grouped plane: leaves bucket within their reduce-axis
    group, each bucket psums over ITS group's axes (tp-sharded weight grads
    over ``dp`` only; replicated leaves over the full mesh), and the
    group's averaging denominator — including the tp psum-transpose
    correction — folds into the bucket's one fused prescale. Requires
    ``average=True`` (the denominators define the averaging semantics) and
    dense leaves (sparse trees stay on the 1-D plane); ``axis_name`` is
    ignored in this mode.

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves are kept
    whole and routed through the two-allgather sparse path — never flattened
    into dense buckets (their integer indices must not be summed).

    ``prescale`` multiplies every bucket by a scalar *before* the reduce —
    one fused multiply on the already-flattened bucket, not one per leaf —
    which is how gradient accumulation folds its ``1/accum_steps`` into the
    same traversal (the reference's ``backward_passes_per_step`` divides by
    the global microbatch count at the same point). The reduce is linear, so
    pre- and post-scaling are equivalent; prescaling keeps the bucketed tree
    the single thing the collective ever sees.

    ``return_finite=True`` returns ``(reduced_tree, all_finite)`` where
    ``all_finite`` is a scalar bool, True iff every float leaf of EVERY
    rank's input was finite — the in-jit bad-step guard's signal. It is
    folded into the same bucket traversal with **zero extra collectives**:
    the reduce is a sum, and IEEE754 sums propagate any NaN/Inf operand
    into the result (Inf−Inf pairs become NaN, overflow becomes Inf), so
    checking ``isfinite`` on each REDUCED bucket while still flat — one
    pass per bucket, before unfusing — sees every rank's poison through
    the psum that already happened. The flag is therefore identical on
    all replicas, which is exactly what a divergence-free skip-step
    decision needs.

    ``wire_dtype`` (``"bf16"`` / ``"fp8"``) puts float buckets on the wire
    in reduced precision: every scale is applied in fp32 before ONE cast on
    send, the collective runs in the wire dtype, and the result is cast
    back to the bucket's original dtype immediately after (fp32
    accumulation downstream; see :func:`_wire_sum` — fp8 adds one scalar
    ``pmax`` per bucket for its dynamic scale, the only extra collective
    any wire format introduces). The bucket PLAN is unchanged — a wire
    cast never merges or splits buckets.

    ``overlap=True`` (or a ``grad_order`` from :func:`probe_grad_order`)
    switches to the backward-overlapped emission: buckets group leaves by
    backward-completion order (:func:`plan_schedule`) and each bucket's
    collective is barrier-chained behind the previous one's result, so the
    per-bucket collectives issue as their gradients complete and XLA hides
    wire time behind the remaining backward compute. Same total collective
    count as the non-overlapped plan over the same leaf multiset — overlap
    reorders, never adds."""
    from .sparse import IndexedSlices, allreduce_indexed_slices

    wire = resolve_wire_dtype(wire_dtype)
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if not leaves:
        return (tree, jnp.ones((), jnp.bool_)) if return_finite else tree
    if reduce_axes is not None:
        if not average:
            raise ValueError(
                "reduce_axes= (the spec-grouped N-D plane) defines "
                "averaging semantics via per-group denominators — "
                "average=False has no meaning there")
        if any(isinstance(l, IndexedSlices) for l in leaves):
            raise ValueError(
                "reduce_axes= requires dense gradients: IndexedSlices "
                "leaves have no per-axis spec grouping (densify with "
                "sparse_as_dense=True)")
        if len(reduce_axes) != len(leaves):
            raise ValueError(
                f"reduce_axes must align with the gradient tree: "
                f"{len(reduce_axes)} GradSync entries for {len(leaves)} "
                f"leaves")
        return _grouped_allreduce(
            leaves, treedef, reduce_axes, fusion_threshold, prescale,
            return_finite, wire, overlap or grad_order is not None,
            grad_order)
    op = Op.AVERAGE if average else Op.SUM
    reduced: List[Optional[jax.Array]] = [None] * len(leaves)
    finite = jnp.ones((), jnp.bool_)

    def _check(x):
        nonlocal finite
        if return_finite and jnp.issubdtype(x.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(x))

    dense_idx = [i for i, l in enumerate(leaves)
                 if not isinstance(l, IndexedSlices)]
    for i in (i for i in range(len(leaves)) if i not in dense_idx):
        s = leaves[i]
        if prescale is not None:
            s = IndexedSlices(_prescale_array(s.values, prescale),
                              s.indices, s.dense_shape)
        r = allreduce_indexed_slices(
            s, average=average, axis_name=axis_name)
        # Allgathered slices carry every rank's raw values, so a local
        # NaN is literally present in each rank's gathered copy.
        _check(r.values)
        reduced[i] = r

    dense = [leaves[i] for i in dense_idx]
    overlap_on = overlap or grad_order is not None
    if overlap_on:
        order_d = None
        if grad_order is not None:
            # Project the full-tree completion order onto the dense
            # subsequence (sparse leaves ride their own allgather path).
            full_to_dense = {fi: di for di, fi in enumerate(dense_idx)}
            order_d = tuple(full_to_dense[i] for i in grad_order
                            if i in full_to_dense)
        buckets = [list(b) for b in
                   plan_schedule(dense, order_d, fusion_threshold).buckets]
    else:
        buckets = plan_buckets(dense, fusion_threshold)

    prev = None
    for bucket in buckets:
        if len(bucket) == 1:
            operand = dense[bucket[0]]
        else:
            operand = _fuse([dense[j] for j in bucket])
        if overlap_on and len(buckets) > 1:
            operand = _barrier_chain(operand, prev)
        if _wire_applies(operand.dtype, wire):
            eff = prescale
            if op is Op.AVERAGE:
                inv = 1.0 / int(axis_size(axis_name))
                eff = inv if eff is None else eff * inv
            r = _wire_sum(operand, axis_name, wire, prescale=eff)
        else:
            r = _reduce_in_trace(
                _prescale_array(operand, prescale), op, axis_name)
        if overlap_on:
            prev = r
        _check(r)
        if len(bucket) == 1:
            reduced[dense_idx[bucket[0]]] = r
        else:
            members = [dense[j] for j in bucket]
            for j, rr in zip(bucket, _unfuse(r, members)):
                reduced[dense_idx[j]] = rr
    out = jax.tree_util.tree_unflatten(treedef, reduced)
    return (out, finite) if return_finite else out


# ---------------------------------------------------------------------------
# ZeRO-1 sharded-update plane (Rajbhandari et al. 2020; Xu et al. 2020,
# "Automatic Cross-Replica Sharding of Weight Update Computation"): the same
# bucket planner that feeds the fused all-reduce instead feeds a
# reduce-scatter — every rank receives the REDUCED 1/N slice of each flat
# bucket, applies the optimizer update to its slice only, and the updated
# slices ride one all-gather back into the full tree. Bytes on the wire are
# unchanged (ring all-reduce = reduce-scatter + all-gather); optimizer-state
# memory and update FLOPs drop by the world size.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ZeroPlan:
    """Static layout of a tree's rank-sharded flat buckets.

    Everything here is trace-time constant (hashable, usable as pytree aux
    data): ``buckets`` are :func:`plan_buckets` index groups over the
    flattened tree, ``sizes``/``padded`` the true and rank-padded flat
    length per bucket (``padded[i]`` is the smallest multiple of
    ``nshards`` >= ``sizes[i]``, so ``lax.psum_scatter(tiled=True)`` splits
    evenly), ``shapes``/``dtypes`` the member leaves' layout for unfusing,
    and ``treedef`` the original tree structure.

    On an N-D mesh (``plan_zero(specs=, mesh=)``) the plan is keyed by the
    reduce-axis tuple of each leaf's PartitionSpec: buckets group within a
    spec group (tp-sharded weight grads never share a bucket with
    replicated leaves), ``shapes``/``sizes``/``padded`` describe the
    LOCAL (per-tp-shard) blocks the in-trace collectives see while
    ``global_shapes`` keeps the mesh-agnostic layout the 2-D canonical
    checkpoint form is defined on, and the per-bucket ``extra_axes`` /
    ``shard_axes`` / ``denoms`` record the group's collective plan:
    reduce-scatter over ``scatter_axis`` (dp), an extra psum over the axes
    the bucket is replicated across, averaged by the group denominator
    (including the tp psum-transpose correction). Bucket MEMBERSHIP is
    planned on global shapes, so it is identical across (dp, tp) reshapes
    of the same axis set."""

    buckets: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    padded: Tuple[int, ...]
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]
    treedef: Any
    nshards: int
    # --- N-D (hybrid-mesh) extension; defaults = the 1-D world plan. ---
    scatter_axis: Optional[str] = None
    denoms: Optional[Tuple[int, ...]] = None
    extra_axes: Optional[Tuple[Tuple[str, ...], ...]] = None
    shard_axes: Optional[Tuple[Tuple[str, ...], ...]] = None
    nonscatter: Tuple[Tuple[str, int], ...] = ()
    leaf_specs: Optional[Tuple[Any, ...]] = None
    global_shapes: Optional[Tuple[Tuple[int, ...], ...]] = None

    @property
    def hybrid(self) -> bool:
        return self.leaf_specs is not None

    def shard_len(self, i: int) -> int:
        return self.padded[i] // self.nshards

    def bucket_denom(self, i: int) -> int:
        return self.nshards if self.denoms is None else self.denoms[i]

    def bucket_extra(self, i: int) -> Tuple[str, ...]:
        return () if self.extra_axes is None else self.extra_axes[i]

    def bucket_shard_axes(self, i: int) -> Tuple[str, ...]:
        return () if self.shard_axes is None else self.shard_axes[i]

    def bucket_ns(self, i: int) -> int:
        """Product of the sizes of the nonscatter axes bucket ``i``'s
        leaves are sharded over — the stacked array's tp-fold factor."""
        sizes = dict(self.nonscatter)
        n = 1
        for a in self.bucket_shard_axes(i):
            n *= int(sizes[a])
        return n

    def shard_shapes(self):
        """Per-bucket stacked-array shape: ``(nshards, shard_len)`` on the
        1-D world; ``(nshards, ns · shard_len)`` on a hybrid mesh, where
        ``ns`` folds the bucket's tp-like shard axes into the trailing dim
        (block ``[:, c·s:(c+1)·s]`` is nonscatter-coordinate ``c``'s dp
        stack). Replicated buckets keep ``ns == 1`` — their state is
        stored once and REPLICATED over tp by sharding, not materialized
        per tp rank."""
        return tuple((self.nshards, self.bucket_ns(i) * self.shard_len(i))
                     for i in range(len(self.buckets)))

    def canonical_sizes(self):
        """Per-bucket length of the world- AND mesh-agnostic canonical
        form: the flat concatenation of the bucket's GLOBAL leaves —
        identical no matter how the saving run split (dp, tp)."""
        if not self.hybrid:
            return self.sizes
        out = []
        for b in self.buckets:
            out.append(sum(int(math.prod(self.global_shapes[j]))
                           for j in b))
        return tuple(out)


def _local_shape(shape, spec, axis_sizes) -> Tuple[int, ...]:
    """The per-device block shape of a leaf laid out by ``spec`` (one mesh
    axis per dim at most — the Megatron layouts this plane supports)."""
    out = list(shape)
    for d, s in enumerate(spec or ()):
        if s is None:
            continue
        axes = (s,) if isinstance(s, str) else tuple(s)
        if len(axes) > 1:
            raise ValueError(
                f"ZeRO spec-grouped plans support one mesh axis per "
                f"tensor dim; got {spec} (dim {d} sharded over {axes})")
        n = int(axis_sizes[axes[0]])
        if out[d] % n:
            raise ValueError(
                f"dim {d} of shape {tuple(shape)} does not divide by the "
                f"{axes[0]}={n} mesh axis (spec {spec})")
        out[d] //= n
    return tuple(out)


def plan_zero(tree, nshards: int,
              fusion_threshold: Optional[int] = None,
              *, specs=None, mesh=None, scatter_axis: str = "dp",
              skip_axes: Tuple[str, ...] = ()) -> ZeroPlan:
    """Build the sharded-update layout for ``tree`` over ``nshards`` ranks.

    Sparse (:class:`~horovod_tpu.ops.sparse.IndexedSlices`) leaves cannot
    be flattened into rank-sharded dense buckets (their integer indices
    must not be summed, and a slice of a slice has no owner rank) — a tree
    carrying them raises; densify first (``sparse_as_dense``) or keep the
    replicated optimizer for sparse models.

    ``specs=`` + ``mesh=`` build the N-D (hybrid-mesh) plan: leaves group
    by their :class:`GradSync` spec group (:func:`plan_grad_sync`), bucket
    membership is scanned on GLOBAL shapes — so the plan (and therefore
    the canonical checkpoint form) is identical across (dp, tp) reshapes
    of the same axis names — and the optimizer state shards over
    ``scatter_axis`` (dp) for tp-sharded and replicated leaves alike.
    ``tree`` holds the global params; ``nshards`` must equal the mesh's
    ``scatter_axis`` size."""
    from .sparse import IndexedSlices
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, IndexedSlices))
    if any(isinstance(l, IndexedSlices) for l in leaves):
        raise ValueError(
            "ZeRO sharded updates require dense gradients: an "
            "IndexedSlices leaf cannot be flattened into rank-sharded "
            "buckets (densify with sparse_as_dense=True, or use the "
            "replicated DistributedOptimizer for sparse models)")
    if nshards < 1:
        raise ValueError(f"nshards must be >= 1, got {nshards}")

    if specs is None:
        buckets = plan_buckets(leaves, fusion_threshold)
        sizes = []
        padded = []
        for b in buckets:
            n = sum(int(math.prod(leaves[j].shape)) for j in b)
            sizes.append(n)
            padded.append(-(-n // nshards) * nshards)
        return ZeroPlan(
            buckets=tuple(tuple(b) for b in buckets),
            sizes=tuple(sizes),
            padded=tuple(padded),
            shapes=tuple(tuple(l.shape) for l in leaves),
            dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
            treedef=treedef,
            nshards=nshards,
        )

    if mesh is None:
        raise ValueError("plan_zero(specs=...) requires mesh= (the named "
                         "hybrid mesh the specs refer to)")
    if scatter_axis not in mesh.shape:
        raise ValueError(
            f"scatter_axis {scatter_axis!r} is not an axis of the mesh "
            f"{dict(mesh.shape)} — ZeRO shards the optimizer state over "
            f"the data-parallel axis")
    if nshards != int(mesh.shape[scatter_axis]):
        raise ValueError(
            f"nshards={nshards} does not match the mesh's "
            f"{scatter_axis}={mesh.shape[scatter_axis]} — the ZeRO shard "
            f"count IS the {scatter_axis} axis size on a hybrid mesh")
    from jax.sharding import PartitionSpec as P
    spec_leaves = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    if len(spec_leaves) != len(leaves):
        raise ValueError(
            f"param_specs tree has {len(spec_leaves)} specs for "
            f"{len(leaves)} parameter leaves — the trees must mirror")
    syncs = plan_grad_sync(spec_leaves, mesh, skip_axes=skip_axes)
    axis_sizes = dict(mesh.shape)
    for spec, sync in zip(spec_leaves, syncs):
        if scatter_axis not in sync.psum:
            raise ValueError(
                f"a parameter with spec {spec} is sharded over the "
                f"scatter axis {scatter_axis!r} — ZeRO-over-{scatter_axis}"
                f" requires params replicated across it (shard weights "
                f"over tp/sp/ep, data over {scatter_axis})")
    buckets = plan_buckets(leaves, fusion_threshold, groups=list(syncs))
    local_shapes = [
        _local_shape(l.shape, spec, axis_sizes)
        for l, spec in zip(leaves, spec_leaves)]
    sizes = []
    padded = []
    denoms = []
    extra = []
    shard_ax = []
    for b in buckets:
        n = sum(int(math.prod(local_shapes[j])) for j in b)
        sizes.append(n)
        padded.append(-(-n // nshards) * nshards)
        sync = syncs[b[0]]
        denoms.append(sync.denom)
        extra.append(tuple(a for a in sync.psum if a != scatter_axis))
        shard_ax.append(sync.shard)
    nonscatter = tuple(
        (a, int(axis_sizes[a])) for a in mesh.axis_names
        if a != scatter_axis and a not in skip_axes)
    return ZeroPlan(
        buckets=tuple(tuple(b) for b in buckets),
        sizes=tuple(sizes),
        padded=tuple(padded),
        shapes=tuple(local_shapes),
        dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
        treedef=treedef,
        nshards=nshards,
        scatter_axis=scatter_axis,
        denoms=tuple(denoms),
        extra_axes=tuple(extra),
        shard_axes=tuple(shard_ax),
        nonscatter=nonscatter,
        leaf_specs=tuple(spec_leaves),
        global_shapes=tuple(tuple(l.shape) for l in leaves),
    )


def _fuse_bucket(leaves, plan: ZeroPlan, i: int):
    """Flatten bucket ``i``'s members into one rank-padded flat vector."""
    flat = _fuse([leaves[j] for j in plan.buckets[i]])
    pad = plan.padded[i] - plan.sizes[i]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def fused_reduce_scatter(tree, plan: ZeroPlan, *,
                         average: bool = True,
                         axis_name: str = AXIS,
                         prescale: Optional[float] = None,
                         return_finite: bool = False,
                         wire_dtype=None,
                         emit_order: Optional[Sequence[int]] = None):
    """Reduce-scatter a pytree into this rank's flat bucket shards.

    Each bucket is flattened, zero-padded to a multiple of the world size,
    optionally prescaled (one fused multiply on the flat bucket — gradient
    accumulation's ``1/accum_steps`` and ``average``'s ``1/size`` fold into
    the same scalar), and fed to one ``lax.psum_scatter`` — rank ``r``
    receives the REDUCED slice ``flat[r*s:(r+1)*s]``. Returns the per-bucket
    shard list (order = plan order).

    ``return_finite=True`` additionally returns a **rank-local** all-finite
    scalar derived from the already-reduced shards: IEEE sums propagate any
    rank's NaN/Inf into the reduced value at that position, which lands on
    exactly one rank's shard — so the flag differs per rank and only the
    AND over ranks is the world-wide verdict. :func:`fused_allgather_params`
    folds that AND into the all-gather the updated shards already ride
    (``and_finite=``), keeping the bad-step guard at zero extra collectives
    in ZeRO mode too.

    ``wire_dtype`` (``"bf16"`` / ``"fp8"``) runs the scatter in reduced
    precision — fp32 prescale, one cast on send, and the received shard
    cast straight back to the bucket's dtype so the optimizer update
    accumulates in fp32 (:func:`_wire_scatter`). ``emit_order`` (a bucket
    permutation from :func:`zero_emit_order`) issues the scatters in
    backward-readiness order behind ``optimization_barrier`` pins — bucket
    MEMBERSHIP (and therefore the sharded state layout and the checkpoint
    canonical form) never changes, only which collective fires first. The
    returned shard list is always in PLAN order.

    Hybrid (N-D) plans: the scatter runs over the plan's ``scatter_axis``
    (dp) with the GROUP denominator — including the tp psum-transpose
    correction — folded into the fp32 prescale; buckets replicated across
    the nonscatter axes take one extra ``lax.psum`` over those axes on the
    already-scattered 1/dp shard (the cheapest place for the Megatron-side
    sum). With ``return_finite`` the rank-local flag is folded with one
    scalar ``pmin`` over the nonscatter axes — tp-sharded buckets take no
    tp collective, so a NaN there is visible to one tp rank only; the
    pmin is the only collective the guard adds on the hybrid plane (the
    1-D plane stays at zero extra).
    """
    wire = resolve_wire_dtype(wire_dtype)
    leaves = plan.treedef.flatten_up_to(tree)
    nb = len(plan.buckets)
    order = tuple(range(nb)) if emit_order is None \
        else tuple(int(i) for i in emit_order)
    if sorted(order) != list(range(nb)):
        raise ValueError(
            f"emit_order must be a permutation of the {nb} bucket "
            f"indices; got {order}")
    shards: List[Optional[jax.Array]] = [None] * nb
    finite = jnp.ones((), jnp.bool_)
    prev = None
    for i in order:
        scale = None
        denom = plan.bucket_denom(i)
        if average and denom > 1:
            scale = 1.0 / denom
        if prescale is not None:
            scale = prescale if scale is None else scale * prescale
        flat = _fuse_bucket(leaves, plan, i)
        if emit_order is not None and nb > 1:
            flat = _barrier_chain(flat, prev)
        if plan.nshards > 1:
            if _wire_applies(flat.dtype, wire):
                shard = _wire_scatter(flat, axis_name, wire, plan.nshards,
                                      prescale=scale)
            else:
                shard = jax.lax.psum_scatter(
                    _prescale_array(flat, scale), axis_name, tiled=True)
        else:
            # Single shard: the reduce is the identity, and nothing rides
            # the wire — no quantization round-trip either.
            shard = _prescale_array(flat, scale)
        extra = plan.bucket_extra(i)
        if extra:
            # Replicated-group bucket on a hybrid mesh: the tp-side sum,
            # taken on the 1/dp shard (dp-fold fewer elements than a
            # pre-scatter psum would touch).
            shard = jax.lax.psum(shard, extra)
        if emit_order is not None:
            prev = shard
        if return_finite and jnp.issubdtype(shard.dtype, jnp.inexact):
            finite = finite & jnp.all(jnp.isfinite(shard))
        shards[i] = shard
    if return_finite and plan.nonscatter:
        finite = jax.lax.pmin(
            finite.astype(jnp.int32),
            tuple(a for a, _ in plan.nonscatter)) > 0
    return (shards, finite) if return_finite else shards


def shard_params(tree, plan: ZeroPlan, *, axis_name: str = AXIS,
                 rank: Optional[int] = None):
    """Slice this rank's flat bucket shards out of a replicated pytree
    (no collective — each rank takes ``flat[rank*s:(rank+1)*s]``). The
    owner index is ``lax.axis_index`` in-trace, or the static ``rank``
    the env-world plane passes (one process = one shard)."""
    leaves = plan.treedef.flatten_up_to(tree)
    idx = jax.lax.axis_index(axis_name) if rank is None else rank
    shards = []
    for i in range(len(plan.buckets)):
        flat = _fuse_bucket(leaves, plan, i)
        s = plan.shard_len(i)
        if plan.nshards == 1:
            shards.append(flat)
        elif rank is None:
            shards.append(jax.lax.dynamic_slice(flat, (idx * s,), (s,)))
        else:
            shards.append(flat[rank * s:(rank + 1) * s])
    return shards


def _unfuse_flat(flats, plan: ZeroPlan):
    """Rebuild the original tree from per-bucket UNPADDED flat vectors."""
    reduced: List[Optional[jax.Array]] = [None] * len(plan.shapes)
    for i, bucket in enumerate(plan.buckets):
        flat = flats[i]
        offset = 0
        for j in bucket:
            n = int(math.prod(plan.shapes[j]))
            reduced[j] = jnp.reshape(flat[offset:offset + n], plan.shapes[j])
            offset += n
    return plan.treedef.unflatten(reduced)


def zero_stacked_spec(plan: ZeroPlan, i: int, axis_name: str = AXIS):
    """PartitionSpec of bucket ``i``'s stacked optimizer-state array:
    ``P(scatter)`` on the 1-D world (``axis_name``), ``P(dp, shard_axes)``
    on a hybrid mesh — the leading dim splits one shard per dp rank, the
    trailing dim splits over the tp-like axes the bucket's leaves are
    sharded over (replicated buckets leave it whole: their state is
    replicated over tp by SHARDING, not materialized per tp rank)."""
    from jax.sharding import PartitionSpec as P
    scatter = plan.scatter_axis if plan.scatter_axis is not None \
        else axis_name
    sa = plan.bucket_shard_axes(i)
    return P(scatter, sa) if sa else P(scatter)


def _ns_coords(plan: ZeroPlan, i: int):
    """Nonscatter coordinates of bucket ``i``'s shard axes, in the
    row-major order ``PartitionSpec(scatter, shard_axes)`` splits the
    stacked array's trailing dim — block ``[:, c·s:(c+1)·s]`` of the
    stacked array is coordinate ``c``'s dp stack."""
    import itertools
    axes = plan.bucket_shard_axes(i)
    sizes = dict(plan.nonscatter)
    for coord in itertools.product(*[range(int(sizes[a])) for a in axes]):
        yield dict(zip(axes, coord))


def _block_index(shape, spec, coord, axis_sizes):
    """Slice tuple selecting the local block of a global array at
    nonscatter coordinate ``coord`` under ``spec``."""
    idx = []
    for d in range(len(shape)):
        s = spec[d] if spec is not None and d < len(spec) else None
        if s is None:
            idx.append(slice(None))
            continue
        a = s if isinstance(s, str) else tuple(s)[0]
        if a not in coord:
            idx.append(slice(None))
            continue
        w = shape[d] // int(axis_sizes[a])
        idx.append(slice(coord[a] * w, (coord[a] + 1) * w))
    return tuple(idx)


def zero_stack_global(leaves, plan: ZeroPlan, i: int) -> np.ndarray:
    """Build bucket ``i``'s stacked optimizer-state array from GLOBAL
    leaves (host-side; init and checkpoint-restore both use it): for each
    nonscatter coordinate, slice the bucket members' local blocks, flatten
    + rank-pad + stack ``[nshards, shard_len]``, and concatenate the
    coordinates along the trailing dim. 1-D plans degrade to the plain
    flatten-pad-stack."""
    axis_sizes = dict(plan.nonscatter)
    s = plan.shard_len(i)
    pad = plan.padded[i] - plan.sizes[i]
    cols = []
    for coord in (_ns_coords(plan, i) if plan.hybrid else ({},)):
        parts = []
        for j in plan.buckets[i]:
            arr = np.asarray(leaves[j])
            if plan.hybrid:
                arr = arr[_block_index(arr.shape, plan.leaf_specs[j],
                                       coord, axis_sizes)]
            parts.append(np.ravel(arr))
        flat = np.concatenate(parts) if len(parts) > 1 else parts[0]
        if pad:
            flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
        cols.append(flat.reshape(plan.nshards, s))
    return cols[0] if len(cols) == 1 else np.concatenate(cols, axis=1)


def zero_unstack_global(stacked, plan: ZeroPlan, i: int) -> List[np.ndarray]:
    """Inverse of :func:`zero_stack_global`: bucket ``i``'s GLOBAL leaves
    from its stacked ``[nshards, ns·shard_len]`` array."""
    axis_sizes = dict(plan.nonscatter)
    stacked = np.asarray(stacked)
    s = plan.shard_len(i)
    out = [np.zeros(plan.global_shapes[j] if plan.hybrid
                    else plan.shapes[j], stacked.dtype)
           for j in plan.buckets[i]]
    for ci, coord in enumerate(_ns_coords(plan, i) if plan.hybrid
                               else ({},)):
        flat = stacked[:, ci * s:(ci + 1) * s].reshape(-1)[:plan.sizes[i]]
        off = 0
        for k, j in enumerate(plan.buckets[i]):
            n = int(math.prod(plan.shapes[j]))
            block = flat[off:off + n].reshape(plan.shapes[j])
            off += n
            if plan.hybrid:
                out[k][_block_index(out[k].shape, plan.leaf_specs[j],
                                    coord, axis_sizes)] = block
            else:
                out[k] = block
    return out


def fused_allgather_params(shards, plan: ZeroPlan, *,
                           axis_name: str = AXIS,
                           and_finite: Optional[jax.Array] = None):
    """Rebuild a full pytree from every rank's updated flat bucket shards:
    one ``all_gather`` per bucket, padding stripped, leaves reshaped.

    ``and_finite`` (a rank-LOCAL boolean from
    :func:`fused_reduce_scatter`'s ``return_finite``) rides the same
    gather: the scalar is appended as one extra element to the first
    inexact bucket's shard, so after gathering every rank sees every
    rank's flag and the AND is replica-identical — the world-wide
    bad-step verdict with **zero** extra collectives. Returns
    ``(tree, all_finite)`` in that case, else just ``tree``.
    """
    nb = len(plan.buckets)
    flag_bucket = None
    if and_finite is not None:
        flag_bucket = next(
            (i for i in range(nb)
             if jnp.issubdtype(jnp.dtype(plan.dtypes[plan.buckets[i][0]]),
                               jnp.inexact)), None)
    shards = list(shards)
    if flag_bucket is not None:
        flag = and_finite.astype(shards[flag_bucket].dtype).reshape(1)
        shards[flag_bucket] = jnp.concatenate([shards[flag_bucket], flag])
    flats = []
    all_finite = None
    for i in range(nb):
        if plan.nshards > 1:
            gathered = all_gather_invariant(shards[i], axis_name, tiled=True)
        else:
            gathered = shards[i]
        if i == flag_bucket:
            s = plan.shard_len(i)
            blocks = gathered.reshape(plan.nshards, s + 1)
            # 1.0/0.0 flags by construction (isfinite output cast to the
            # bucket dtype) — exactly representable in every float dtype.
            all_finite = jnp.all(blocks[:, -1].astype(jnp.float32) > 0.5)
            gathered = blocks[:, :s].reshape(-1)
        flats.append(gathered[:plan.sizes[i]])
    out = _unfuse_flat(flats, plan)
    if and_finite is None:
        return out
    if all_finite is None:
        # No inexact bucket: an all-integer tree is finite by construction,
        # so the local flag (constant True) is already the global verdict.
        all_finite = and_finite
    return out, all_finite
