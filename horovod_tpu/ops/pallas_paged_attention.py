"""Pallas paged decode-attention — the decode-path sibling of the
training flash kernels in :mod:`.pallas_attention`.

One query token per slot attends over that slot's KV *blocks*, gathered
directly from the paged pool via the block table: the grid walks
``(slot, head, logical_block)`` and a scalar-prefetched block table
resolves each logical block to its physical pool index INSIDE the
BlockSpec index map — the kernel never materializes the per-slot
``[max_blocks·block_size, H, dh]`` contiguous view the pure-lax fallback
gathers (at real configs that view is the whole cache re-laid-out per
step; the kernel streams exactly the blocks each slot owns). Online
softmax (running max/sum, fp32 accumulation) across the block axis,
per-slot length masking, blocks past the slot's position skipped
entirely.

Gating discipline mirrors ``pallas_attention``'s ``_fused_bwd_fits``
pattern: the engine flips the kernel on only when
:func:`paged_attention_supported` says the shapes tile on the running
backend (``d_head`` a lane multiple on real TPUs; anything goes in
interpreter mode), and the pure-lax gather fallback — the
bit-identity-bearing reference — keeps the whole stack green everywhere
else. :func:`paged_attention_reference` IS that fallback's math;
``tests/test_paged_kv.py`` pins kernel-vs-reference allclose on CPU
(interpret mode executes the same kernel program the TPU would run).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def paged_attention_supported(d_head: int, block_size: int,
                              interpret: Optional[bool] = None) -> bool:
    """Whether the kernel path runs these shapes: interpreter mode (CPU
    tests) takes anything; a real TPU needs lane-aligned ``d_head`` and
    a sublane-aligned block so Mosaic can tile the K/V blocks."""
    if not _HAS_PALLAS:
        return False
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret:
        return True
    return d_head % 128 == 0 and block_size % 8 == 0


def _paged_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, bs: int, scale: float):
    """Grid (slot, head, logical_block): one [1, d] query row against one
    [bs, d] K/V block (resolved physical by the index maps). Softmax
    state (acc/m/l) persists in scratch across the block axis; blocks
    entirely past the slot's position — and every block of an inactive
    (position < 0) slot — skip all compute, and the normalized output is
    written at the last block step (zeros for a fully-masked row, via
    the safe divide)."""
    s = pl.program_id(0)
    b = pl.program_id(2)
    n_b = pl.num_programs(2)

    @pl.when(b == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)

    pos = pos_ref[s]

    @pl.when((pos >= 0) & (b * bs <= pos))
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale            # [1, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)           # [bs, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [1, bs]
        kpos = b * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        sc = jnp.where(kpos <= pos, sc, -1e30)
        m_prev = m_ref[0, 0]
        m_new = jnp.maximum(m_prev, jnp.max(sc))
        p = jnp.exp(sc - m_new)                             # [1, bs]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[0, 0] = l_ref[0, 0] * alpha + jnp.sum(p)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [1, d]
        m_ref[0, 0] = m_new

    @pl.when(b == n_b - 1)
    def _finish():
        l = l_ref[0, 0]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_call(q, k_pool, v_pool, block_tables, positions,
                sm_scale: float, interpret: bool):
    S, H, d = q.shape
    bs = k_pool.shape[1]
    nb = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, H, nb),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda s, h, b, tbl, pos: (s, h, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda s, h, b, tbl, pos: (tbl[s, b], 0, h, 0)),
            pl.BlockSpec((1, bs, 1, d),
                         lambda s, h, b, tbl, pos: (tbl[s, b], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d),
                               lambda s, h, b, tbl, pos: (s, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, d), jnp.float32),        # acc
            pltpu.SMEM((1, 1), jnp.float32),        # running max
            pltpu.SMEM((1, 1), jnp.float32),        # running sum
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, bs=bs, scale=sm_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, d), q.dtype),
        interpret=interpret,
    )(block_tables, positions, q, k_pool, v_pool)


def paged_decode_attention(q, k_pool, v_pool, block_tables, positions, *,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Decode attention straight from the paged pool.

    Args:
      q: [S, H, d] — one query token per slot.
      k_pool, v_pool: [n_blocks, block_size, H, d] — ONE layer's view of
        the pool (callers index ``cache["k"][layer]``).
      block_tables: [S, max_blocks] int32 physical block per logical
        block, trash-padded past each slot's allocation.
      positions: [S] int32 — attend keys ``0..positions[s]`` inclusive
        (the just-written token); ``< 0`` = inactive row (output zeros).
      sm_scale: softmax scale (default ``1/sqrt(d)``).
      interpret: force interpreter mode (defaults to True off-TPU).

    Returns [S, H, d] in ``q.dtype``. Forward-only (decode never
    differentiates); allclose-pinned against
    :func:`paged_attention_reference`.
    """
    S, H, d = q.shape
    if not _HAS_PALLAS:
        raise RuntimeError(
            "paged_decode_attention needs jax.experimental.pallas; use "
            "the pure-lax fallback (kernel=False) on this build")
    if sm_scale is None:
        sm_scale = float(d) ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not paged_attention_supported(d, k_pool.shape[1],
                                     interpret=interpret):
        raise ValueError(
            f"paged_decode_attention needs d_head%128==0 and "
            f"block_size%8==0 on TPU; got d_head={d}, "
            f"block_size={k_pool.shape[1]} (use the lax gather fallback)")
    return _paged_call(q, k_pool, v_pool,
                       jnp.asarray(block_tables, jnp.int32),
                       jnp.asarray(positions, jnp.int32),
                       float(sm_scale), bool(interpret))


def paged_attention_reference(q, k_pool, v_pool, block_tables, positions,
                              sm_scale: Optional[float] = None):
    """The pure-lax gather fallback's math, standalone: gather each
    slot's blocks into the contiguous [S, M, H, d] view and run the
    ``_cached_attention`` einsum (f32 scores, -1e30 mask, f32 softmax) —
    the same function the contiguous cache path computes, which is the
    whole bit-identity story. Inactive rows (positions < 0) return
    zeros, matching the kernel."""
    S, H, d = q.shape
    nb = block_tables.shape[1]
    bs = k_pool.shape[1]
    if sm_scale is None:
        sm_scale = float(d) ** -0.5
    kg = k_pool[block_tables].reshape(S, nb * bs, H, d)
    vg = v_pool[block_tables].reshape(S, nb * bs, H, d)
    s = jnp.einsum("shd,smhd->shm", q.astype(jnp.float32),
                   kg.astype(jnp.float32)) * sm_scale
    m = jnp.arange(nb * bs, dtype=jnp.int32)
    s = jnp.where(m[None, None, :] <= positions[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shm,smhd->shd", p, vg.astype(jnp.float32))
    out = jnp.where(positions[:, None, None] >= 0, out, 0.0)
    return out.astype(q.dtype)
