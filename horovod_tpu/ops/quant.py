"""Weight-only quantization for inference: int8 with per-channel scales.

The serving memory problem is weights-at-rest, not math: HBM footprint
(and restore I/O) of a big LM is dominated by the parameter bytes, while
the decode hot loop is bandwidth-bound reading them. Storing matmul
weights as int8 with one f32 scale per output channel quarters the bytes;
the dequantize (``q * scale``) happens INSIDE the jitted forward, so XLA
keeps int8 in HBM and fuses the scale multiply into the consuming matmul
— activations and accumulation stay in the model's compute dtype.

:class:`QuantizedTensor` is a NamedTuple, hence automatically a pytree:
quantized param trees jit, ``device_put``, and shard like plain ones
(``restore_for_inference(dtype="int8", mesh=...)`` just works). Symmetric
quantization (no zero point): round-to-nearest onto [-127, 127], scale =
per-channel absmax / 127. Channels are the LAST axis — the output columns
of every ``[in, out]`` matmul weight this framework initializes.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

INT8_MAX = 127.0


class QuantizedTensor(NamedTuple):
    """int8 payload + per-channel (last-axis) f32 scales; a pytree node."""

    q: Any        # int8, the original shape
    scale: Any    # f32 [shape[-1]]

    @property
    def shape(self):
        return np.shape(self.q)


def is_quantized(x) -> bool:
    return isinstance(x, QuantizedTensor)


def quantize(w) -> QuantizedTensor:
    """Symmetric per-channel int8 quantization of a float array (host-side
    numpy — this runs once at restore time, never in the hot path). An
    all-zero channel gets scale 1 so the dequant is exact zero, not 0/0."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1))) \
        if w.ndim > 1 else np.abs(w)
    scale = np.where(absmax > 0, absmax / INT8_MAX, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale), -INT8_MAX, INT8_MAX).astype(np.int8)
    return QuantizedTensor(q=q, scale=scale)


def dequantize(qt: QuantizedTensor, dtype: Optional[Any] = None):
    """``q * scale`` back to float (f32 unless ``dtype``). Works on numpy
    and on traced jax values — the generation forward calls it per use."""
    out = jnp.asarray(qt.q, jnp.float32) * jnp.asarray(qt.scale,
                                                       jnp.float32)
    return out if dtype is None else out.astype(dtype)


def quantize_tree(tree: Any, min_ndim: int = 2) -> Any:
    """Quantize every float leaf with ``ndim >= min_ndim`` (the matmul
    weights); smaller float leaves (norm scales, biases) stay fp32 — they
    are byte-trivial and precision-critical."""
    def _one(x):
        a = np.asarray(x)
        if not np.issubdtype(a.dtype, np.floating):
            return x
        if a.ndim >= min_ndim:
            return quantize(a)
        return a.astype(np.float32)

    return jax.tree_util.tree_map(_one, tree)


def dequantize_tree(tree: Any, dtype: Optional[Any] = None) -> Any:
    """Replace every :class:`QuantizedTensor` node with its dequantized
    array; plain leaves pass through untouched (an unquantized tree is a
    no-op, so forwards can call this unconditionally)."""
    return jax.tree_util.tree_map(
        lambda x: dequantize(x, dtype) if is_quantized(x) else x,
        tree, is_leaf=is_quantized)
