"""Pallas TPU fused 1x1-conv + BatchNorm + activation — ResNet's hot path.

Why this kernel exists
----------------------
The reference's north-star workload is ResNet-50 training
(``examples/keras_imagenet_resnet50.py``). On TPU the measured per-op
roofline (``docs/benchmarks.md``, "the measured roofline bound") shows the
step executing at ~100% of its per-op floor, with the stage-1/2 1x1 convs
pinned at the HBM ceiling (750-900 GB/s, ~50 FLOP/byte): the MFU ceiling is
set by *memory traffic*, not compute. XLA cannot cross convolution HLO
boundaries, so every bottleneck-block chain pays

    conv(write y) -> BN stats(read y) -> BN norm+relu(read y, write z)
    -> next conv(read z)

i.e. four HBM transits per intermediate activation map. This module fuses
the chain into ONE Pallas pass per conv:

    [affine+ReLU prologue] -> matmul (the 1x1 conv) -> [stats epilogue]

so each intermediate makes exactly two transits (one write by its producer,
one read by its consumer). The per-channel BatchNorm arithmetic (mu/sigma
from the streamed sum/sum-of-squares, running-average updates, gamma/beta
folding into a per-channel affine ``a*x + b``) stays in plain jax between
kernels — it is O(C) work, and routing the *stats* (not the normalized
tensor) between ops is what makes jax's chain rule produce the exact
training-mode BatchNorm backward through this op's custom VJP: the
normalize's dependence on mu/sigma flows through the tiny stats graph,
while the VJP handles only the big-tensor terms (one fused backward pass
computing dx, dW, d_affine and injecting the stats cotangents
``dy_eff = dy + ds1 + 2*y*ds2``).

The backward is a single kernel pass reading (x, y, dy) and writing dx,
with dW / da / db accumulated in VMEM across the grid — versus the
unfused path's separate dW matmul, dx matmul, BN-backward reductions and
elementwise passes.

Used by :class:`horovod_tpu.models.resnet.BottleneckBlock` when
``conv_backend="fused"`` (the ``--conv-backend`` knob of the bench/
examples). Off-TPU the kernels run in interpreter mode, bit-matching the
compiled math (tests: ``tests/test_pallas_conv.py``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:  # pallas ships with jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Rows of [M, C] processed per grid step. 1024 amortizes Mosaic's per-step
# overhead while keeping the worst-case working set (stage-2 convC,
# C_out=512) around ~3 MB with double buffering; _pick_rows shrinks it for
# small batches.
_WANT_BM = 1024
# Sublane height of the per-channel stat tensors (s1/s2, ds1/ds2, dab):
# one f32 sublane tile; only row 0 carries data.
_STAT_ROWS = 8


def _pick_rows(m: int, want: int = _WANT_BM) -> int:
    b = want
    while b > 128 and m % b:
        b //= 2
    return b


def fusable(m: int) -> bool:
    """Whether the fused kernel tiles an [M, C] problem (M = N*H*W)."""
    return _HAS_PALLAS and m % 128 == 0


def _fwd_kernel(x_ref, w_ref, ab_ref, y_ref, s1_ref, s2_ref, *,
                prologue: bool, relu: bool):
    i = pl.program_id(0)
    x = x_ref[...]
    if prologue:
        a = ab_ref[0:1, :]
        b = ab_ref[1:2, :]
        u = a * x.astype(jnp.float32) + b
        if relu:
            u = jnp.maximum(u, 0.0)
        # Cast back to the conv input dtype: the unfused graph materializes
        # z = relu(bn(y)) in bf16 before the next conv reads it, so the
        # fused matmul must consume the same rounded values.
        u = u.astype(x_ref.dtype)
    else:
        u = x
    w = w_ref[...].astype(x_ref.dtype)
    y = jax.lax.dot_general(u, w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    yc = y.astype(y_ref.dtype)
    y_ref[...] = yc

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    # Stats of the CAST output (what the unfused BatchNorm sees), f32
    # accumulation. Outputs have a grid-constant index map, so they live in
    # VMEM across the whole grid and are flushed once at the end.
    yf = yc.astype(jnp.float32)
    s1_ref[:1, :] += jnp.sum(yf, axis=0)[None, :]
    s2_ref[:1, :] += jnp.sum(yf * yf, axis=0)[None, :]


def _bwd_kernel(x_ref, y_ref, dy_ref, w_ref, ab_ref, ds_ref,
                dx_ref, dw_ref, dab_ref, *, prologue: bool, relu: bool):
    i = pl.program_id(0)
    x = x_ref[...]
    dy = dy_ref[...].astype(jnp.float32)
    # Stats cotangents: d/dy of (s1 = sum y, s2 = sum y^2).
    ds1 = ds_ref[0:1, :]
    ds2 = ds_ref[1:2, :]
    dy = dy + ds1 + 2.0 * y_ref[...].astype(jnp.float32) * ds2

    if prologue:
        a = ab_ref[0:1, :]
        b = ab_ref[1:2, :]
        xf = x.astype(jnp.float32)
        pre = a * xf + b
        u = jnp.maximum(pre, 0.0) if relu else pre
        u = u.astype(x_ref.dtype)
    else:
        u = x
    dyc = dy.astype(x_ref.dtype)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dab_ref[...] = jnp.zeros_like(dab_ref)

    # dW += u^T dy  (f32 accumulation in the grid-persistent output block)
    dw_ref[...] += jax.lax.dot_general(
        u, dyc, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # du = dy W^T
    w = w_ref[...].astype(x_ref.dtype)
    du = jax.lax.dot_general(dyc, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if prologue:
        if relu:
            du = jnp.where(pre > 0.0, du, 0.0)
        dx_ref[...] = (du * a).astype(dx_ref.dtype)
        dab_ref[:1, :] += jnp.sum(du * xf, axis=0)[None, :]
        dab_ref[1:2, :] += jnp.sum(du, axis=0)[None, :]
    else:
        dx_ref[...] = du.astype(dx_ref.dtype)


def _call_fwd(x, w, ab, prologue, relu, interpret):
    m, cin = x.shape
    cout = w.shape[1]
    bm = _pick_rows(m)
    grid = (m // bm,)
    full = lambda i: (0, 0)
    y, s1, s2 = pl.pallas_call(
        functools.partial(_fwd_kernel, prologue=prologue, relu=relu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), full),
            pl.BlockSpec((_STAT_ROWS, cin), full),
        ],
        out_specs=[
            pl.BlockSpec((bm, cout), lambda i: (i, 0)),
            pl.BlockSpec((_STAT_ROWS, cout), full),
            pl.BlockSpec((_STAT_ROWS, cout), full),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, cout), x.dtype),
            jax.ShapeDtypeStruct((_STAT_ROWS, cout), jnp.float32),
            jax.ShapeDtypeStruct((_STAT_ROWS, cout), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, w, ab)
    return y, s1, s2


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_core(x, w, ab, prologue: bool, relu: bool, interpret: bool):
    return _call_fwd(x, w, ab, prologue, relu, interpret)


def _fused_core_fwd(x, w, ab, prologue, relu, interpret):
    y, s1, s2 = _call_fwd(x, w, ab, prologue, relu, interpret)
    return (y, s1, s2), (x, w, ab, y)


def _fused_core_bwd(prologue, relu, interpret, res, cot):
    x, w, ab, y = res
    dy, ds1, ds2 = cot
    m, cin = x.shape
    cout = w.shape[1]
    bm = _pick_rows(m)
    # ds row 0 = ds1, row 1 = ds2 (rows 2+ of the primal stat outputs carry
    # no data, so their cotangents are zero by construction).
    ds = jnp.concatenate([ds1[:1, :], ds2[:1, :],
                          jnp.zeros((_STAT_ROWS - 2, cout), jnp.float32)],
                         axis=0)
    full = lambda i: (0, 0)
    dx, dw, dab = pl.pallas_call(
        functools.partial(_bwd_kernel, prologue=prologue, relu=relu),
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),     # x
            pl.BlockSpec((bm, cout), lambda i: (i, 0)),    # y
            pl.BlockSpec((bm, cout), lambda i: (i, 0)),    # dy
            pl.BlockSpec((cin, cout), full),               # w
            pl.BlockSpec((_STAT_ROWS, cin), full),         # ab
            pl.BlockSpec((_STAT_ROWS, cout), full),        # ds
        ],
        out_specs=[
            pl.BlockSpec((bm, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), full),
            pl.BlockSpec((_STAT_ROWS, cin), full),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, cin), x.dtype),
            jax.ShapeDtypeStruct((cin, cout), jnp.float32),
            jax.ShapeDtypeStruct((_STAT_ROWS, cin), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, y, dy, w, ab, ds)
    dw = dw.astype(w.dtype)
    dab = dab.astype(ab.dtype)
    if not prologue:
        dab = jnp.zeros_like(dab)
    return dx, dw, dab


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


def fused_linear_bn_act(x2, w, ab: Optional[jax.Array] = None, *,
                        relu: bool = True,
                        interpret: Optional[bool] = None
                        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused [prologue-affine+ReLU] -> 1x1 conv -> stats epilogue.

    Args:
      x2: [M, Cin] activations (M = N*H*W; a [N,H,W,C] map reshaped —
        layout-free on TPU). M must be a multiple of 128 (``fusable``).
      w: [Cin, Cout] float32 conv weight (cast to ``x2.dtype`` on the MXU).
      ab: None (no prologue — the conv consumes ``x2`` raw), or a
        [>=2, Cin] float32 array with row 0 = per-channel scale ``a`` and
        row 1 = shift ``b``: the conv consumes ``relu(a*x + b)`` (the
        folded form of a trained BatchNorm + ReLU) without materializing it.
      relu: apply ReLU in the prologue (ignored without ``ab``).

    Returns ``(y, s1, s2)``: the conv output [M, Cout] in ``x2.dtype`` and
    its per-channel sum / sum-of-squares (f32, shape [8, Cout], row 0
    carries the data) for the consumer-side BatchNorm. Differentiable via a
    single-pass fused backward kernel; cotangents flowing into s1/s2 (i.e.
    the training-mode BatchNorm's dependence on its batch stats) are folded
    into the gradient exactly.
    """
    m, cin = x2.shape
    if not fusable(m):
        raise ValueError(
            f"fused_linear_bn_act needs M % 128 == 0, got M={m} "
            f"(fall back to the XLA path)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    prologue = ab is not None
    if ab is None:
        ab = jnp.zeros((_STAT_ROWS, cin), jnp.float32)
    elif ab.shape[0] != _STAT_ROWS:
        ab = jnp.concatenate(
            [ab[:2].astype(jnp.float32),
             jnp.zeros((_STAT_ROWS - 2, cin), jnp.float32)], axis=0)
    return _fused_core(x2, w.astype(jnp.float32), ab, prologue, relu,
                       interpret)
