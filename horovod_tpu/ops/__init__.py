"""Collective ops (the data plane) — TPU-native analog of the reference's
``horovod/tensorflow/mpi_ops.py`` + ``mpi_ops.cc`` kernels."""

from .collectives import (  # noqa: F401
    Op,
    allreduce,
    allgather,
    broadcast,
    alltoall,
    reducescatter,
    grouped_allreduce,
)
from .fusion import plan_buckets, fused_allreduce  # noqa: F401
from .sparse import IndexedSlices, allreduce_indexed_slices  # noqa: F401
