"""Sparse (embedding) gradients: the ``tf.IndexedSlices`` path.

Reference semantics (``horovod/tensorflow/__init__.py:61-72``): a sparse
gradient is a (values, indices) pair; its "allreduce" is **two allgathers**
(values and indices) — an allreduce in sliced form — with optional division
of values by ``size()``. Exercised by the word2vec example
(``examples/tensorflow_word2vec.py:218-222``).

TPU-native: under SPMD the per-rank slice counts are equal and static, so the
gathers are plain ``lax.all_gather`` (tiled). The gathered IndexedSlices may
contain duplicate indices across ranks — exactly like the reference — and
summation happens when applied to the dense variable (``to_dense`` uses a
scatter-add, matching TF's IndexedSlices application semantics).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..runtime import AXIS
from ..utils.compat import all_gather_invariant


@jax.tree_util.register_pytree_node_class
class IndexedSlices:
    """A sparse gradient: ``dense[indices[i]] += values[i]``.

    Parity: ``tf.IndexedSlices`` as consumed by the reference's sparse
    allreduce branch (``horovod/tensorflow/__init__.py:61-72``).
    """

    def __init__(self, values, indices, dense_shape: Tuple[int, ...]):
        self.values = values
        self.indices = indices
        self.dense_shape = tuple(dense_shape)

    def tree_flatten(self):
        return (self.values, self.indices), self.dense_shape

    @classmethod
    def tree_unflatten(cls, dense_shape, children):
        values, indices = children
        return cls(values, indices, dense_shape)

    def to_dense(self) -> jax.Array:
        """Scatter-add into a dense array (TF IndexedSlices application)."""
        dense = jnp.zeros(self.dense_shape, dtype=self.values.dtype)
        return dense.at[self.indices].add(self.values)

    def __repr__(self):
        return (f"IndexedSlices(values={self.values.shape}, "
                f"indices={self.indices.shape}, dense_shape={self.dense_shape})")


def allreduce_indexed_slices(slices: IndexedSlices, average: bool = True,
                             name: Optional[str] = None,
                             axis_name: str = AXIS) -> IndexedSlices:
    """Sparse allreduce = allgather(values) + allgather(indices)
    (``horovod/tensorflow/__init__.py:61-72``), values scaled by
    ``1/size`` when averaging."""
    del name
    values = all_gather_invariant(slices.values, axis_name, tiled=True)
    indices = all_gather_invariant(slices.indices, axis_name, tiled=True)
    if average:
        values = values / lax.psum(1, axis_name)
    return IndexedSlices(values, indices, slices.dense_shape)
