"""Pallas TPU flash attention — the hot-op kernel for the transformer path.

Blockwise causal attention computed entirely in VMEM with an online softmax
(running max/sum), so the [T, T] score matrix never touches HBM: per grid
step a [BQ, D] query tile is streamed against K/V tiles with MXU matmuls
(f32 accumulation). Differentiable end to end: a custom VJP recomputes the
probability tiles from (q, k, lse) inside dq/dkv kernels, so the backward
pass never materializes scores either. Used by the parallel transformer's
single-shard attention path (``parallel/transformer.py``); the
sequence-parallel path (:func:`horovod_tpu.parallel.ring.ring_attention`)
keeps its own blockwise accumulation across chips.

Off-TPU (CPU tests) the kernels run in interpreter mode, bit-matching the
compiled path's math. `flash_attention` falls back to plain XLA attention
for shapes the kernel doesn't tile (tiny head_dim or sequences not divisible
by the block).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

BLOCK_Q = 128    # minimum tile (tilability floor)
BLOCK_K = 128
# Preferred tile sizes (swept on a v5e chip; _pick_block shrinks them to
# fit short sequences).
_WANT_BQ = 512
_WANT_BK = 512


def _pick_block(t: int, want: int) -> int:
    """Largest power-of-two block <= ``want`` dividing ``t``. Bigger tiles
    amortize Mosaic's per-grid-step overhead; 128 is the floor the
    tilability check guarantees."""
    b = want
    while b > 128 and t % b:
        b //= 2
    return b


def _grid_params(semantics):
    """dimension_semantics lets Mosaic pipeline HBM tile copies against
    compute across grid steps — without it every step stalls on its loads
    (measured ~4x on the backward at T=2048)."""
    return pltpu.CompilerParams(dimension_semantics=semantics)


def _causal_run(qi, kb, bq, bk):
    """A (qi, kb) tile pair contributes under the causal mask iff its
    lowest k position is <= its highest q position."""
    return kb * bk <= qi * bq + bq - 1


def _tile_mask(s, qi, kb, bq, bk):
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, -1e30)


# The kernels work in the LOG2 domain: the caller pre-scales q by
# sm_scale*log2(e) ONCE (a [BH,T,D] pass), so the per-tile [BQ,BK] scale
# multiply disappears and exp becomes the VPU's native exp2. True scores
# A = ln2 * s; probabilities exp2(s-m) == exp(A-A_max) are IDENTICAL, and
# the backward's dq/dk epilogues become *ln2 (ln2 * the caller's c folds
# back to sm_scale). The kernels are VPU-softmax-bound at D=128 (measured:
# fwd 41 TF/s vs matmul passes at 157), so per-tile elementwise passes are
# exactly what to shave.
_LN2 = 0.6931471805599453
LOG2E = 1.4426950408889634


def _scores(q, k, qi, kb, *, causal, bq, bk):
    """Masked log2-domain score tile [BQ, BK] (q arrives pre-scaled),
    shared by forward and both backward kernels so the mask math cannot
    desynchronize. The matmul stays in the input dtype (bf16 MXU passes
    with f32 accumulation); only diagonal-crossing tiles pay the
    iota/select mask."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if causal:
        s = jax.lax.cond(
            kb * bk + bk > qi * bq,
            lambda s: _tile_mask(s, qi, kb, bq, bk),
            lambda s: s, s)
    return s


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                 l_ref, *, causal: bool, bq: int, bk: int,
                 qi_axis: int = 1, kb_axis: int = 2,
                 q_scale: Optional[float] = None):
    """Grid (..., qi, kb): one [BQ, D] × [BK, D] tile pair.

    K/V tiles stream through VMEM (no whole-sequence residency); the
    online-softmax state (acc/m/l) persists in scratch across the kb axis,
    and the normalized output plus the row log2-sum-exp2 (saved for the
    backward pass) are written at the last kb step. Above-diagonal tile
    pairs skip all compute under causal.

    ``q_scale``: the packed-qkv path ships RAW q tiles and scales them on
    load (a [BQ,D] pass) instead of pre-scaling the whole tensor; None =
    q already pre-scaled by the caller (the split-q/k/v path).
    """
    qi = pl.program_id(qi_axis)
    kb = pl.program_id(kb_axis)
    n_kb = pl.num_programs(kb_axis)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = _causal_run(qi, kb, bq, bk) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        if q_scale is not None:
            q = (q.astype(jnp.float32) * q_scale).astype(q_ref.dtype)
        s = _scores(q, k, qi, kb, causal=causal, bq=bq, bk=bk)  # [BQ, BK]
        m_prev = m_ref[:, 0]                             # [BQ]
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp2(s - m_new[:, None])
        alpha = jnp.exp2(m_prev - m_new)
        # Running stats live in lane 0 only (reads are [:, 0]); the full
        # 128-lane broadcast write was two extra [BQ,128] VPU passes per
        # tile (~10% of fwd kernel time on v5e). Only the FINAL lse output
        # below is lane-replicated — that's the wire format the backward's
        # _row_spec tiles expect. (On-chip numerics + bench validated.)
        l_ref[:, :1] = (l_ref[:, 0] * alpha
                        + jnp.sum(p, axis=-1))[:, None]
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, :1] = m_new[:, None]

    @pl.when(kb == n_kb - 1)
    def _finish():
        l = l_ref[:, 0]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe[:, None]).astype(o_ref.dtype)
        if lse_ref is not None:
            # log2 domain, matching the backward's exp2 recompute.
            lse = jnp.where(l == 0.0, -1e30, m_ref[:, 0] + jnp.log2(safe))
            lse_ref[0] = lse[:, None] * jnp.ones_like(lse_ref[0])


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, causal: bool, bq: int, bk: int,
               qi_axis: int = 1, kb_axis: int = 2,
               q_scale: Optional[float] = None,
               dq_scale: float = _LN2):
    """Grid (..., qi, kb): accumulate dq over the kb axis.

    Recomputes the probability tile from (q, k, lse) — the flash-backward
    trade: [BQ, BK] tiles never leave VMEM.
    dA = P ∘ (dO·Vᵀ − Δ), Δ = rowsum(dO ∘ O). Split path: q arrives
    pre-scaled, dq_scale = ln2 (the caller's log2e·sm_scale prescale folds
    the chain rule back to sm_scale). Packed path: q raw + q_scale set,
    dq_scale = sm_scale directly.
    """
    qi = pl.program_id(qi_axis)
    kb = pl.program_id(kb_axis)
    n_kb = pl.num_programs(kb_axis)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = _causal_run(qi, kb, bq, bk) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        if q_scale is not None:
            q = (q.astype(jnp.float32) * q_scale).astype(q_ref.dtype)
        s = _scores(q, k, qi, kb, causal=causal, bq=bq, bk=bk)
        p = jnp.exp2(s - lse_ref[0][:, :1])              # [BQ, BK]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kb - 1)
    def _finish():
        dq_ref[0] = (acc_ref[:] * dq_scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, causal: bool,
                bq: int, bk: int, kb_axis: int = 1, qi_axis: int = 2,
                q_scale: Optional[float] = None,
                dk_scale: float = _LN2):
    """Grid (..., kb, qi): accumulate dk/dv for one K/V tile over all
    contributing Q tiles. dV = Pᵀ·dO. Split path: dK = ln2 · dAᵀ·Q_scaled
    (prescaled q makes ln2 the correct chain factor). Packed path: q raw
    (scaled only for the score recompute), dK = sm_scale · dAᵀ·Q."""
    kb = pl.program_id(kb_axis)
    qi = pl.program_id(qi_axis)
    n_qi = pl.num_programs(qi_axis)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = _causal_run(qi, kb, bq, bk) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        qs = q
        if q_scale is not None:
            qs = (q.astype(jnp.float32) * q_scale).astype(q_ref.dtype)
        s = _scores(qs, k, qi, kb, causal=causal, bq=bq, bk=bk)
        p = jnp.exp2(s - lse_ref[0][:, :1])              # [BQ, BK]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # Pᵀ·dO [BK, D]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # dAᵀ·Q [BK, D]

    @pl.when(qi == n_qi - 1)
    def _finish():
        dk_ref[0] = (dk_acc[:] * dk_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dqkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc, *,
                 causal: bool, bq: int, bk: int,
                 qi_axis: int = 1, kb_axis: int = 2,
                 q_scale: Optional[float] = None,
                 grad_scale: float = _LN2):
    """Fused single-pass backward: dq, dk and dv from ONE visit of each
    (qi, kb) tile pair.

    The split dq / dkv kernels each recompute the probability tile and the
    dO·Vᵀ matmul and each stream q/k/v/do from HBM — and the kernels are
    VPU-softmax-bound (measured fwd 41 vs matmul 157 TF/s), so the second
    exp2 recompute pass is pure waste. Here one grid (…, qi, kb) computes
    s/p/dp/ds once per pair: dq accumulates per-qi in a [BQ, D] scratch
    (written at the kb edge, as before), while dk/dv accumulate into
    full-T [T, D] f32 VMEM scratch across the whole (qi, kb) space and
    are flushed once per (batch, head) at the final step. Halves the
    softmax recompute, the dp matmul and the HBM streaming of the backward
    (7 matmuls + 2 exp2 passes per pair across two kernels -> 5 + 1).
    Costs 2·T·D f32 of VMEM (1 MiB per 2048×128) — callers fall back to
    the split kernels when ``_fused_bwd_fits`` says the residents exceed
    the per-core VMEM budget.
    """
    qi = pl.program_id(qi_axis)
    kb = pl.program_id(kb_axis)
    n_qi = pl.num_programs(qi_axis)
    n_kb = pl.num_programs(kb_axis)

    @pl.when((qi == 0) & (kb == 0))
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(kb == 0)
    def _init_q():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _causal_run(qi, kb, bq, bk) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        qs = q
        if q_scale is not None:
            qs = (q.astype(jnp.float32) * q_scale).astype(q_ref.dtype)
        s = _scores(qs, k, qi, kb, causal=causal, bq=bq, bk=bk)
        p = jnp.exp2(s - lse_ref[0][:, :1])              # [BQ, BK]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dsc = ds.astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            dsc, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        rows = pl.ds(kb * bk, bk)
        dv_acc[rows, :] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # Pᵀ·dO
        dk_acc[rows, :] += jax.lax.dot_general(
            dsc, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # dSᵀ·Q

    @pl.when(kb == n_kb - 1)
    def _fin_q():
        dq_ref[0] = (dq_acc[:] * grad_scale).astype(dq_ref.dtype)

    @pl.when((qi == n_qi - 1) & (kb == n_kb - 1))
    def _fin_kv():
        dk_ref[0] = (dk_acc[:] * grad_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _dqkv_packed_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dqkv_ref, dq_acc, dk_acc, dv_acc, *,
                        causal: bool, bq: int, bk: int, d: int,
                        q_scale: float, grad_scale: float):
    """Packed-path fused backward writing the gradient DIRECTLY in the
    projection's packed column layout.

    Grid (B, H, qi, kb); the single output block is head h's full packed
    column stripe ``[1, T, 3D]`` of d_qkv (columns q|k|v), grid-constant
    over (qi, kb) so it lives in VMEM for the whole (batch, head) visit:
    dq rows land at each qi edge, dk/dv flush from the full-T accumulators
    at the end. This removes the stack+reshape interleave the previous
    backward needed (measured ~0.52 ms/layer of concatenate fusions plus
    the copies around three [B,T,H*D] intermediates — the gradient now
    exists in exactly one materialization).
    """
    qi = pl.program_id(2)
    kb = pl.program_id(3)
    n_qi = pl.num_programs(2)
    n_kb = pl.num_programs(3)

    @pl.when((qi == 0) & (kb == 0))
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(kb == 0)
    def _init_q():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = _causal_run(qi, kb, bq, bk) if causal else True

    @pl.when(run)
    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        qs = (q.astype(jnp.float32) * q_scale).astype(q_ref.dtype)
        s = _scores(qs, k, qi, kb, causal=causal, bq=bq, bk=bk)
        p = jnp.exp2(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dsc = ds.astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            dsc, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        rows = pl.ds(kb * bk, bk)
        dv_acc[rows, :] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[rows, :] += jax.lax.dot_general(
            dsc, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kb - 1)
    def _fin_q():
        dqkv_ref[0, pl.ds(qi * bq, bq), 0:d] = \
            (dq_acc[:] * grad_scale).astype(dqkv_ref.dtype)

    @pl.when((qi == n_qi - 1) & (kb == n_kb - 1))
    def _fin_kv():
        dqkv_ref[0, :, d:2 * d] = \
            (dk_acc[:] * grad_scale).astype(dqkv_ref.dtype)
        dqkv_ref[0, :, 2 * d:3 * d] = dv_acc[:].astype(dqkv_ref.dtype)


# Per-core VMEM the fused backward may claim (v4/v5 generations carry
# ~16 MiB/core; override for parts that differ). Read once at import so
# every rank traces the same graph — a trace-time env read could diverge
# across ranks (the HVD_FUSED_PARTS lesson, ADVICE r5).
_VMEM_BUDGET_BYTES = int(os.environ.get("HVD_VMEM_BUDGET_MB", "16")) * 2**20


def _fused_bwd_fits(T: int, D: int, itemsize: int, *, bq: int, bk: int,
                    packed: bool) -> bool:
    """Whether the fused single-pass backward's VMEM residents fit the
    per-core budget — the gate deciding fused vs split dq/dkv kernels.

    The fused kernel's full-T dk/dv accumulators make its footprint grow
    with sequence length, so a static T ceiling (the old
    ``_FUSED_BWD_MAX_T = 8192``, sized for D=128 bf16) admitted shapes
    that failed to compile at larger D or f32 and rejected small-D shapes
    that fit fine. Summing the actual residents instead:

    * scratch: dq_acc [bq, D] + dk/dv accumulators 2×[T, D], all f32;
    * output block(s), grid-constant so VMEM-resident for a whole
      (batch, head) visit: packed [T, 3D] vs split dq [bq, D] + full-T
      dk/dv 2×[T, D], in the input dtype;
    * streamed input tiles (q/do [bq, D], k/v [bk, D], two [bq, lanes]
      f32 stat tiles), doubled — Mosaic double-buffers pipelined streams.
    """
    scratch = 4 * (bq * D + 2 * T * D)
    out = (T * 3 * D if packed else (bq + 2 * T) * D) * itemsize
    tiles = ((2 * bq + 2 * bk) * D * itemsize
             + 2 * bq * _STAT_LANES * 4)
    return scratch + out + 2 * tiles <= _VMEM_BUDGET_BYTES


# Lane width of the per-row stat tensors (lse, delta) on the wire between
# kernels. Only lane 0 carries data; 8 lanes (one f32 sublane tile) keeps
# Mosaic layouts happy while cutting the streamed stat traffic 16x vs the
# old 128-lane replication: at the bench config BH = B*H = 8*16 = 128,
# T = 2048, so a 128-lane f32 stat was 128*2048*128*4 = 134 MB per stat
# per kernel per layer — pure HBM burn for a [BH, T] statistic.
_STAT_LANES = 8


def _row_spec(block_rows, which):
    """BlockSpec for per-row stats [BH, T, _STAT_LANES]; kernels read
    column 0 only."""
    return pl.BlockSpec((1, block_rows, _STAT_LANES), which)


def _fwd_pallas(q, k, v, causal: bool, interpret: bool,
                with_lse: bool = True):
    """q/k/v: [BH, T, D], q PRE-SCALED by sm_scale*log2e ->
    (o [BH, T, D], lse2 [BH, T, _STAT_LANES] f32 | None).

    ``with_lse=False`` (the no-grad primal) drops the lse output — Mosaic
    can't dead-code-eliminate an output buffer, and at long T the f32 lse
    write outweighs the bf16 output itself."""
    BH, T, D = q.shape
    bq = _pick_block(T, _WANT_BQ)
    bk = _pick_block(T, _WANT_BK)
    grid = (BH, T // bq, T // bk)
    base = functools.partial(_attn_kernel, causal=causal, bq=bq, bk=bk)
    if with_lse:
        kernel = base
        out_specs = [
            pl.BlockSpec((1, bq, D), lambda bh, qi, kb: (bh, qi, 0)),
            _row_spec(bq, lambda bh, qi, kb: (bh, qi, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, T, _STAT_LANES), jnp.float32),
        ]
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
            base(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref)
        out_specs = pl.BlockSpec((1, bq, D), lambda bh, qi, kb: (bh, qi, 0))
        out_shape = jax.ShapeDtypeStruct((BH, T, D), q.dtype)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),            # acc
            pltpu.VMEM((bq, 128), jnp.float32),          # running max
            pltpu.VMEM((bq, 128), jnp.float32),          # running sum
        ],
        compiler_params=_grid_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return (out if with_lse else (out, None))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal: bool, interpret: bool):
    """q arrives pre-scaled by sm_scale*log2e (see _flash_bhtd); the VJP
    therefore returns dq in the SCALED domain and jax's chain rule through
    the caller's multiply restores the true dq."""
    o, _ = _fwd_pallas(q, k, v, causal, interpret, with_lse=False)
    return o


def _flash_core_fwd(q, k, v, causal, interpret):
    o, lse = _fwd_pallas(q, k, v, causal, interpret)
    # lse is already the narrow [BH, T, _STAT_LANES] wire format; keep it
    # whole in the residuals (slicing to one lane and re-broadcasting in
    # backward would cost two device copies to save 7 f32 lanes).
    return o, (q, k, v, o, lse)


def _flash_core_bwd(causal, interpret, res, do):
    q, k, v, o, lse = res
    BH, T, D = q.shape
    bq = _pick_block(T, _WANT_BQ)
    bk = _pick_block(T, _WANT_BK)
    # Δ_i = Σ_d dO ∘ O — cheap elementwise reduction, XLA fuses it;
    # widened to _STAT_LANES like lse so the kernels read [BQ, 8] tiles.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [BH, T, 1]
    delta = jnp.broadcast_to(delta, (BH, T, _STAT_LANES))
    qkv_spec_q = pl.BlockSpec((1, bq, D), lambda bh, qi, kb: (bh, qi, 0))
    qkv_spec_k = pl.BlockSpec((1, bk, D), lambda bh, qi, kb: (bh, kb, 0))
    if _fused_bwd_fits(T, D, q.dtype.itemsize, bq=bq, bk=bk, packed=False):
        full = pl.BlockSpec((1, T, D), lambda bh, qi, kb: (bh, 0, 0))
        return pl.pallas_call(
            functools.partial(_dqkv_kernel, causal=causal, bq=bq, bk=bk),
            grid=(BH, T // bq, T // bk),
            in_specs=[qkv_spec_q, qkv_spec_k, qkv_spec_k, qkv_spec_q,
                      _row_spec(bq, lambda bh, qi, kb: (bh, qi, 0)),
                      _row_spec(bq, lambda bh, qi, kb: (bh, qi, 0))],
            out_specs=[qkv_spec_q, full, full],
            out_shape=[
                jax.ShapeDtypeStruct((BH, T, D), q.dtype),
                jax.ShapeDtypeStruct((BH, T, D), k.dtype),
                jax.ShapeDtypeStruct((BH, T, D), v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                            pltpu.VMEM((T, D), jnp.float32),
                            pltpu.VMEM((T, D), jnp.float32)],
            compiler_params=_grid_params(
                ("parallel", "arbitrary", "arbitrary")),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, bq=bq, bk=bk),
        grid=(BH, T // bq, T // bk),
        in_specs=[qkv_spec_q, qkv_spec_k, qkv_spec_k, qkv_spec_q,
                  _row_spec(bq, lambda bh, qi, kb: (bh, qi, 0)),
                  _row_spec(bq, lambda bh, qi, kb: (bh, qi, 0))],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, qi, kb: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_grid_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv iterate the OTHER way: one K/V tile accumulated over Q tiles.
    kv_q = pl.BlockSpec((1, bq, D), lambda bh, kb, qi: (bh, qi, 0))
    kv_k = pl.BlockSpec((1, bk, D), lambda bh, kb, qi: (bh, kb, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, bq=bq, bk=bk),
        grid=(BH, T // bk, T // bq),
        in_specs=[kv_q, kv_k, kv_k, kv_q,
                  _row_spec(bq, lambda bh, kb, qi: (bh, qi, 0)),
                  _row_spec(bq, lambda bh, kb, qi: (bh, qi, 0))],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, kb, qi: (bh, kb, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, kb, qi: (bh, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=_grid_params(("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


# ---------------------------------------------------------------------------
# Packed-qkv path: consume the fused QKV projection output [B, T, H*3*D]
# (HEAD-major columns, i.e. reshape [B, T, H, 3, D]) DIRECTLY via BlockSpec
# index maps — no [B,T,H,D] -> [BH,T,D] transposes on either side of the
# kernels (measured ~11 ms/step of layout copies at the LM bench config).
# The attention output comes back as [B, T, H*D], exactly what the output
# projection consumes. q is scaled inside the kernels (a [BQ,D] pass).
# ---------------------------------------------------------------------------


def _qkv_specs(H, D, bq, bk):
    """BlockSpecs into the packed [B, T, H*3*D] array for grid
    (B, H, qi, kb): column block (h*3 + kind) is head h's q/k/v slice."""
    q = pl.BlockSpec((1, bq, D), lambda b, h, qi, kb: (b, qi, h * 3 + 0))
    k = pl.BlockSpec((1, bk, D), lambda b, h, qi, kb: (b, kb, h * 3 + 1))
    v = pl.BlockSpec((1, bk, D), lambda b, h, qi, kb: (b, kb, h * 3 + 2))
    return q, k, v


def _fwd_pallas_qkv(qkv, H, D, causal, sm_scale, interpret,
                    with_lse=True):
    B, T, _ = qkv.shape
    bq = _pick_block(T, _WANT_BQ)
    bk = _pick_block(T, _WANT_BK)
    grid = (B, H, T // bq, T // bk)
    c = sm_scale * LOG2E
    base = functools.partial(_attn_kernel, causal=causal, bq=bq, bk=bk,
                             qi_axis=2, kb_axis=3, q_scale=c)
    sq, sk, sv = _qkv_specs(H, D, bq, bk)
    o_spec = pl.BlockSpec((1, bq, D), lambda b, h, qi, kb: (b, qi, h))
    # Stats shaped [B*H, T, S]: index maps may do arithmetic on grid ids.
    stat_spec = pl.BlockSpec((1, bq, _STAT_LANES),
                             lambda b, h, qi, kb: (b * H + h, qi, 0))
    if with_lse:
        kernel = base
        out_specs = [o_spec, stat_spec]
        out_shape = [
            jax.ShapeDtypeStruct((B, T, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B * H, T, _STAT_LANES), jnp.float32),
        ]
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
            base(q_ref, k_ref, v_ref, o_ref, None, acc_ref, m_ref, l_ref)
        out_specs = o_spec
        out_shape = jax.ShapeDtypeStruct((B, T, H * D), qkv.dtype)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[sq, sk, sv],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=_grid_params(
            ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qkv, qkv, qkv)
    return (out if with_lse else (out, None))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _flash_qkv_core(qkv, H: int, causal: bool, sm_scale: float,
                    interpret: bool):
    D = qkv.shape[-1] // (3 * H)
    o, _ = _fwd_pallas_qkv(qkv, H, D, causal, sm_scale, interpret,
                           with_lse=False)
    return o


def _flash_qkv_core_fwd(qkv, H, causal, sm_scale, interpret):
    D = qkv.shape[-1] // (3 * H)
    o, lse = _fwd_pallas_qkv(qkv, H, D, causal, sm_scale, interpret)
    return o, (qkv, o, lse)


def _flash_qkv_core_bwd(H, causal, sm_scale, interpret, res, do):
    qkv, o, lse = res
    B, T, _ = qkv.shape
    D = qkv.shape[-1] // (3 * H)
    bq = _pick_block(T, _WANT_BQ)
    bk = _pick_block(T, _WANT_BK)
    c = sm_scale * LOG2E
    delta = jnp.sum(
        (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
            B, T, H, D),
        axis=-1)                                        # [B, T, H]
    delta = jnp.broadcast_to(
        delta.transpose(0, 2, 1).reshape(B * H, T, 1),
        (B * H, T, _STAT_LANES))
    sq, sk, sv = _qkv_specs(H, D, bq, bk)
    do_q = pl.BlockSpec((1, bq, D), lambda b, h, qi, kb: (b, qi, h))
    stat_q = pl.BlockSpec((1, bq, _STAT_LANES),
                          lambda b, h, qi, kb: (b * H + h, qi, 0))
    if _fused_bwd_fits(T, D, qkv.dtype.itemsize, bq=bq, bk=bk, packed=True):
        packed = pl.BlockSpec((1, T, 3 * D), lambda b, h, qi, kb: (b, 0, h))
        d_qkv = pl.pallas_call(
            functools.partial(_dqkv_packed_kernel, causal=causal, bq=bq,
                              bk=bk, d=D, q_scale=c, grad_scale=sm_scale),
            grid=(B, H, T // bq, T // bk),
            in_specs=[sq, sk, sv, do_q, stat_q, stat_q],
            out_specs=packed,
            out_shape=jax.ShapeDtypeStruct((B, T, H * 3 * D), qkv.dtype),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32),
                            pltpu.VMEM((T, D), jnp.float32),
                            pltpu.VMEM((T, D), jnp.float32)],
            compiler_params=_grid_params(
                ("parallel", "parallel", "arbitrary", "arbitrary")),
            interpret=interpret,
        )(qkv, qkv, qkv, do, lse, delta)
        return (d_qkv,)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, bq=bq, bk=bk,
                          qi_axis=2, kb_axis=3, q_scale=c,
                          dq_scale=sm_scale),
        grid=(B, H, T // bq, T // bk),
        in_specs=[sq, sk, sv, do_q, stat_q, stat_q],
        out_specs=pl.BlockSpec((1, bq, D),
                               lambda b, h, qi, kb: (b, qi, h)),
        out_shape=jax.ShapeDtypeStruct((B, T, H * D), qkv.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_grid_params(
            ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qkv, qkv, qkv, do, lse, delta)

    # dk/dv iterate the OTHER way: grid (B, H, kb, qi).
    kv_sq = pl.BlockSpec((1, bq, D), lambda b, h, kb, qi: (b, qi, h * 3))
    kv_sk = pl.BlockSpec((1, bk, D),
                         lambda b, h, kb, qi: (b, kb, h * 3 + 1))
    kv_sv = pl.BlockSpec((1, bk, D),
                         lambda b, h, kb, qi: (b, kb, h * 3 + 2))
    kv_do = pl.BlockSpec((1, bq, D), lambda b, h, kb, qi: (b, qi, h))
    kv_stat = pl.BlockSpec((1, bq, _STAT_LANES),
                           lambda b, h, kb, qi: (b * H + h, qi, 0))
    kv_out = pl.BlockSpec((1, bk, D), lambda b, h, kb, qi: (b, kb, h))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, bq=bq, bk=bk,
                          kb_axis=2, qi_axis=3, q_scale=c,
                          dk_scale=sm_scale),
        grid=(B, H, T // bk, T // bq),
        in_specs=[kv_sq, kv_sk, kv_sv, kv_do, kv_stat, kv_stat],
        out_specs=[kv_out, kv_out],
        out_shape=[
            jax.ShapeDtypeStruct((B, T, H * D), qkv.dtype),
            jax.ShapeDtypeStruct((B, T, H * D), qkv.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=_grid_params(
            ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qkv, qkv, qkv, do, lse, delta)
    # Interleave back into the packed head-major (H, 3, D) column layout.
    d_qkv = jnp.stack(
        [g.reshape(B, T, H, D) for g in (dq, dk, dv)],
        axis=3).reshape(B, T, H * 3 * D)
    return (d_qkv,)


_flash_qkv_core.defvjp(_flash_qkv_core_fwd, _flash_qkv_core_bwd)


def qkv_flash_tilable(T: int, d_head: int) -> bool:
    """Whether the packed-qkv kernel path tiles these dims."""
    return (_HAS_PALLAS and T % BLOCK_Q == 0 and T % BLOCK_K == 0
            and d_head % 128 == 0)


def flash_attention_qkv(qkv, n_heads: int, *, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        interpret: Optional[bool] = None):
    """Attention straight from the packed QKV projection output.

    Args:
      qkv: [B, T, n_heads*3*d_head], HEAD-major columns (i.e. reshapes to
        [B, T, n_heads, 3, d_head] — the layout the parallel transformer's
        fused projection produces).
      n_heads: head count (d_head inferred).
    Returns: [B, T, n_heads*d_head] attention output, ready for the output
    projection. Differentiable (custom VJP; dq/dk/dv re-interleave into
    the packed gradient). Requires ``qkv_flash_tilable``; callers fall
    back to the split path otherwise.
    """
    B, T, cols = qkv.shape
    D = cols // (3 * n_heads)
    if sm_scale is None:
        sm_scale = float(D) ** -0.5
    if not qkv_flash_tilable(T, D):
        raise ValueError(
            f"flash_attention_qkv needs T%128==0 and d_head%128==0; got "
            f"T={T}, d_head={D} (use the split flash_attention fallback)")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_qkv_core(qkv, n_heads, causal, sm_scale, interpret)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale",
                                             "interpret"))
def _flash_bhtd(q, k, v, causal: bool, sm_scale: float, interpret: bool):
    """q/k/v: [BH, T, D] -> [BH, T, D]. Differentiable (custom VJP with
    Pallas dq/dkv kernels — the score matrix never touches HBM in either
    direction). q is pre-scaled here (one cheap [BH,T,D] pass) so the
    kernels run scale-free in the log2 domain; jax's chain rule through
    this multiply restores the true dq from the kernel's scaled-domain
    output."""
    q = (q.astype(jnp.float32) * (sm_scale * LOG2E)).astype(q.dtype)
    return _flash_core(q, k, v, causal, interpret)


# Above roughly this many bytes of [B, H, T, T] f32 scores, the dense XLA
# path risks HBM exhaustion and the blockwise kernel wins by never
# materializing them. Measured on a v5e chip (B=1 H=8 D=128, causal,
# bf16): XLA is FASTER wherever the dense scores fit (8k: 19 vs 24 ms;
# 16k: 52 vs 69 ms) and the kernel is within ~1.3x; at 32k (34 GB of
# scores > 16 GB HBM) only the kernel runs (232 ms). So "auto" switches
# for MEMORY, not speed — 4 GiB leaves room for params/activations/
# optimizer state sharing HBM with the scores in a real training step.
# NOTE those numbers are inference-only; for TRAINING the dense path also
# saves the score tensors for backward, so memory binds far earlier than
# this forward-pass cutover — training code should pass backend="pallas"
# explicitly (TransformerConfig.attn_backend defaults to it).
_SCORE_BYTES_CUTOVER = 4 * 1024 ** 3


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    backend: str = "auto",
                    interpret: Optional[bool] = None):
    """Multi-head attention: XLA by default, Pallas kernel for long context.

    Args:
      q, k, v: [B, T, H, D].
      causal: apply the causal mask.
      sm_scale: softmax scale (default 1/sqrt(D)).
      backend: "auto" (XLA unless the score tensor would exceed ~4 GiB —
        measured on the target platform XLA's fused attention outruns
        Mosaic until memory becomes the binding constraint), "pallas", or
        "xla".
      interpret: force kernel interpreter mode (defaults to True off-TPU).

    Differentiable on every path (the Pallas path via a custom VJP whose
    dq/dk/dv are themselves Pallas kernels). The kernel requires T
    divisible by 128 and D a multiple of 128; other shapes always take the
    XLA path.
    """
    B, T, H, D = q.shape
    if sm_scale is None:
        sm_scale = float(D) ** -0.5
    tilable = qkv_flash_tilable(T, D)
    if backend == "auto":
        score_bytes = 4 * B * H * T * T
        backend = "pallas" if (tilable
                               and score_bytes > _SCORE_BYTES_CUTOVER) \
            else "xla"
    if backend == "xla" or not tilable:
        return _xla_attention(q, k, v, causal, sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, sm_scale,
                      interpret)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _xla_attention(q, k, v, causal, sm_scale):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        pos = jnp.arange(q.shape[1])
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
