"""Pallas TPU flash attention — the hot-op kernel for the transformer path.

Blockwise causal attention computed entirely in VMEM with an online softmax
(running max/sum), so the [T, T] score matrix never touches HBM: per grid
step a [BLOCK_Q, D] query tile is streamed against K/V tiles with MXU
matmuls (f32 accumulation). Used by the parallel transformer's single-shard
attention path (``parallel/transformer.py``) when the dense score tensor
would exhaust HBM; the sequence-parallel path
(:func:`horovod_tpu.parallel.ring.ring_attention`) keeps its own blockwise
accumulation across chips.

Off-TPU (CPU tests) the kernel runs in interpreter mode, bit-matching the
compiled path's math. `flash_attention` falls back to plain XLA attention
for shapes the kernel doesn't tile (tiny head_dim or sequences not divisible
by the block).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

BLOCK_Q = 128
BLOCK_K = 128


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                 causal: bool, sm_scale: float):
    """Grid (bh, qi, kb): one [BLOCK_Q, D] × [BLOCK_K, D] tile pair.

    K/V tiles stream through VMEM (small blocks — no whole-sequence
    residency); the online-softmax state (acc/m/l) persists in scratch
    across the kb axis, and the normalized output is written at the last
    kb step. Above-diagonal tile pairs skip all compute under causal.
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = (kb * BLOCK_K <= qi * BLOCK_Q + BLOCK_Q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale      # [BQ, D]
        k = k_ref[0].astype(jnp.float32)                 # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [BQ, BK]
        if causal:
            q_pos = qi * BLOCK_Q + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 0)
            k_pos = kb * BLOCK_K + jax.lax.broadcasted_iota(
                jnp.int32, (BLOCK_Q, BLOCK_K), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_prev = m_ref[:, 0]                             # [BQ]
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = (l_ref[:, 0] * alpha
                    + jnp.sum(p, axis=-1))[:, None] * jnp.ones_like(l_ref)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new[:, None] * jnp.ones_like(m_ref)

    @pl.when(kb == n_kb - 1)
    def _finish():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "sm_scale",
                                             "interpret"))
def _flash_bhtd(q, k, v, causal: bool, sm_scale: float, interpret: bool):
    """q/k/v: [BH, T, D] -> [BH, T, D]."""
    BH, T, D = q.shape
    grid = (BH, T // BLOCK_Q, T // BLOCK_K)
    kernel = functools.partial(_attn_kernel, causal=causal,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BLOCK_Q, D), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((1, BLOCK_K, D), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((1, BLOCK_K, D), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK_Q, D),
                               lambda bh, qi, kb: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, D), jnp.float32),       # acc
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),     # running max
            pltpu.VMEM((BLOCK_Q, 128), jnp.float32),     # running sum
        ],
        interpret=interpret,
    )(q, k, v)


# Above roughly this many bytes of [B, H, T, T] f32 scores, the dense XLA
# path risks HBM exhaustion and the blockwise kernel wins by never
# materializing them. Measured on a v5e chip (B=1 H=8 D=128, causal,
# bf16): XLA is FASTER wherever the dense scores fit (8k: 19 vs 24 ms;
# 16k: 52 vs 69 ms) and the kernel is within ~1.3x; at 32k (34 GB of
# scores > 16 GB HBM) only the kernel runs (232 ms). So "auto" switches
# for MEMORY, not speed — 4 GiB leaves room for params/activations/
# optimizer state sharing HBM with the scores in a real training step.
_SCORE_BYTES_CUTOVER = 4 * 1024 ** 3


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    backend: str = "auto",
                    interpret: Optional[bool] = None):
    """Multi-head attention: XLA by default, Pallas kernel for long context.

    Args:
      q, k, v: [B, T, H, D].
      causal: apply the causal mask.
      sm_scale: softmax scale (default 1/sqrt(D)).
      backend: "auto" (XLA unless the score tensor would exceed ~4 GiB —
        measured on the target platform XLA's fused attention outruns
        Mosaic until memory becomes the binding constraint), "pallas", or
        "xla".
      interpret: force kernel interpreter mode (defaults to True off-TPU).

    The kernel requires T divisible by 128 and D a multiple of 128; other
    shapes always take the XLA path.
    """
    B, T, H, D = q.shape
    if sm_scale is None:
        sm_scale = float(D) ** -0.5
    tilable = (_HAS_PALLAS and T % BLOCK_Q == 0 and T % BLOCK_K == 0
               and D % 128 == 0)
    if backend == "auto":
        score_bytes = 4 * B * H * T * T
        backend = "pallas" if (tilable
                               and score_bytes > _SCORE_BYTES_CUTOVER) \
            else "xla"
    if backend == "xla" or not tilable:
        return _xla_attention(q, k, v, causal, sm_scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, sm_scale,
                      interpret)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _xla_attention(q, k, v, causal, sm_scale):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * sm_scale
    if causal:
        pos = jnp.arange(q.shape[1])
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
