"""Named collectives: allreduce / allgather / broadcast (+ TPU-era extras).

Reference parity
----------------
* Graph-op wrappers ``_allreduce/allgather/broadcast`` with auto-generated
  cross-rank matching names (``mpi_ops.py:127-190``); semantic ``allreduce``
  with average-vs-sum and the sparse path (``horovod/tensorflow/__init__.py:
  43-79``); ``HorovodAllreduce/Allgather/Broadcast`` kernels
  (``mpi_ops.cc:1752-1915``).
* Allgather concatenates along the first dimension (``MPI_Allgatherv``
  executor, ``mpi_ops.cc:735-812``).
* Broadcast takes a ``root_rank`` and the root's tensor passes through
  (``mpi_ops.cc:1855-1893``).

TPU-native design
-----------------
Two execution contexts, one API:

1. **Inside compiled code** (``shard_map`` over the world mesh — the hot
   path, used by ``DistributedOptimizer`` inside the jitted train step):
   the call lowers directly to an XLA collective over the ``"hvd"`` ICI axis
   (``lax.psum`` / ``lax.all_gather`` / one-hot-mask ``psum`` broadcast).
   XLA schedules and overlaps these; no negotiation is needed because SPMD
   tracing already imposes one global order (SURVEY §7 design stance —
   the reference's coordinator exists only because TF 1.x graph execution is
   cross-rank nondeterministic, ``mpi_ops.cc:1198-1247``).

2. **Eager, op-at-a-time** (outside jit — metrics averaging, epoch
   broadcast, checkpoint-resume sync): the call is dispatched through a
   cached single-collective executable on the mesh. Per-rank inputs are
   jax.Arrays sharded over the world axis on their leading dim (the
   single-controller encoding of "each rank passes its own tensor");
   replicated/host inputs mean every rank contributes the same value. In
   multi-process mode the host coordination plane (``horovod_tpu.coord``)
   additionally validates name-keyed requests across processes, with the
   reference's exact error taxonomy (``ConstructMPIResponse``,
   ``mpi_ops.cc:266-474``).
"""

from __future__ import annotations

import enum
import functools
import re
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import runtime
from ..runtime import AXIS
from ..utils.compat import all_gather_invariant


class Op(enum.Enum):
    """Reduction op. The reference supports summation with optional
    averaging (``average=`` bool, ``horovod/tensorflow/__init__.py:43``);
    MIN/MAX/PRODUCT are TPU-era extras."""

    SUM = "sum"
    AVERAGE = "average"
    MIN = "min"
    MAX = "max"
    PRODUCT = "product"


_name_counter = 0


def _auto_name(kind: str, name: Optional[str]) -> str:
    """Auto-generate the cross-rank matching key (parity: ``mpi_ops.py:132-145``
    names ops ``HorovodAllreduce_<sanitized tensor name>``)."""
    global _name_counter
    if name is None:
        _name_counter += 1
        name = f"tensor_{_name_counter}"
    return f"Horovod{kind}_" + re.sub(r"[^a-zA-Z0-9_]", "_", str(name))


def _in_trace() -> bool:
    return runtime._in_world_trace()


# ---------------------------------------------------------------------------
# In-trace primitives (compiled data plane over ICI).
# ---------------------------------------------------------------------------

def _reduce_in_trace(x, op: Op, axis_name: str = AXIS):
    if op is Op.AVERAGE:
        return lax.pmean(x, axis_name)
    if op is Op.SUM:
        return lax.psum(x, axis_name)
    if op is Op.MIN:
        return lax.pmin(x, axis_name)
    if op is Op.MAX:
        return lax.pmax(x, axis_name)
    if op is Op.PRODUCT:
        # No lax.pprod; exp/log is lossy — use all_gather+prod (rarely hot).
        return jnp.prod(all_gather_invariant(x, axis_name), axis=0)
    raise ValueError(f"unknown op {op}")


def _broadcast_in_trace(x, root_rank: int, axis_name: str = AXIS):
    """One-hot-mask ``psum`` broadcast (SURVEY §2.5 TPU equivalent of
    ``MPI_Bcast``, ``mpi_ops.cc:1134-1136``): zero everywhere but the root,
    then sum over the axis. The root's tensor passes through bit-exact for
    ints; for floats, +0.0 of zeros is exact."""
    idx = lax.axis_index(axis_name)
    orig_dtype = x.dtype
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.int8)
    # where(), not x*mask: multiply-by-zero would propagate NaN/Inf from
    # non-root ranks — and re-syncing diverged replicas is broadcast's main
    # job (§5.4 consistency protocol).
    out = lax.psum(jnp.where(idx == root_rank, x, jnp.zeros_like(x)),
                   axis_name)
    return out.astype(orig_dtype)


# ---------------------------------------------------------------------------
# Eager dispatch: cached single-collective executables on the world mesh.
# Parity note: the reference caches nothing (every session.run re-hits the
# negotiation); we cache compiled executables per (kind, shape, dtype, flags)
# — SURVEY §7 "per-(shape,dtype) executable caching".
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _eager_fn(mesh_key, kind: str, per_rank: bool, squeeze: bool, op: Op,
              root_rank: int):
    mesh = runtime.mesh()
    in_spec = P(AXIS) if per_rank else P()

    out_spec = P()
    if kind == "allreduce":
        def f(x):
            return _reduce_in_trace(x, op)
    elif kind == "allgather":
        def f(x):
            return all_gather_invariant(x, AXIS, tiled=True)
    elif kind == "broadcast":
        def f(x):
            return _broadcast_in_trace(x, root_rank)
    elif kind == "alltoall":
        # Per-rank results differ; the output stays sharded over the world
        # axis (each rank's block is its own exchange result).
        out_spec = P(AXIS)

        def f(x):
            return lax.all_to_all(x, AXIS, 0, 0, tiled=True)
    elif kind == "reducescatter":
        out_spec = P(AXIS)
        if op not in (Op.SUM, Op.AVERAGE):
            raise ValueError(
                f"compiled reducescatter supports SUM/AVERAGE; got {op}")

        def f(x):
            out = lax.psum_scatter(x, AXIS, tiled=True)
            return out / runtime.size() if op is Op.AVERAGE else out
    else:
        raise ValueError(kind)

    if squeeze:
        # Stacked per-rank encoding: the [size, ...] leading axis shards to a
        # size-1 block per rank; the rank's tensor is block[0].
        inner = f
        f = lambda x: inner(x[0])  # noqa: E731

    return jax.jit(
        jax.shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec))


def _is_per_rank(x) -> bool:
    """A jax.Array whose leading dim is split over the world axis encodes
    "each rank passes its own tensor" under a single controller."""
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    spec = sharding.spec
    return len(spec) > 0 and (
        spec[0] == AXIS or (isinstance(spec[0], tuple) and AXIS in spec[0]))


def _eager_dispatch(kind: str, x, name: str, *, op: Op = Op.SUM,
                    root_rank: int = 0, plane: str = "auto"):
    w = runtime.world()
    x = jnp.asarray(x)
    per_rank = _is_per_rank(x)

    if w.coord is not None:
        # Multi-process eager plane: negotiate + validate the name-keyed
        # request across processes before dispatch (host DCN plane).
        return w.coord.collective(kind, x, name, op=op, root_rank=root_rank,
                                  plane=plane)
    if plane != "auto":
        raise ValueError(
            f"plane={plane!r} is a multi-process eager-plane knob (star vs "
            f"client-to-client ring); this world has no coordination plane")

    if kind in ("alltoall", "reducescatter"):
        if not per_rank:
            raise ValueError(
                f"eager single-controller {kind} needs input sharded over "
                f"the world axis on dim 0 (each rank's block is its tensor); "
                f"got a replicated/host value — use shard_batch or a "
                f"NamedSharding(P('{AXIS}'))")
        # Global dim 0 = size × per-rank block; each block must again split
        # `size` ways inside the exchange, so the global dim needs size².
        if x.ndim < 1 or x.shape[0] % (w.size * w.size):
            raise ValueError(
                f"single-controller eager {kind} needs a global first "
                f"dimension divisible by size²={w.size * w.size} (per-rank "
                f"blocks of size a multiple of {w.size}); got shape "
                f"{tuple(x.shape)}")
        squeeze = False
    else:
        squeeze = per_rank and x.ndim >= 1 and x.shape[0] == w.size

    tl = w.timeline
    if tl is not None:
        # Single-controller: negotiation is synthesized (SPMD needs none);
        # the processing phase wraps the real dispatch activities
        # (docs/timeline.md nested-activity model, mpi_ops.cc:623-635).
        tl.negotiate_instant(name, kind.upper(), ready_ranks=range(w.size))
        tl.start(name, kind.upper())
        tl.activity_start(name, "SCHEDULE")
    try:
        fn = _eager_fn(runtime._generation, kind, per_rank, squeeze, op,
                       root_rank)
        if tl is not None:
            tl.activity_end(name)
            tl.activity_start(name, "XLA_EXECUTE")
        out = fn(x)
    except BaseException as e:
        # Close every opened B event so a failed dispatch (invalid op for
        # the kind, XLA error) cannot leave the trace unbalanced.
        if tl is not None:
            tl.abort(name, error=str(e))
        raise
    if tl is not None:
        tl.activity_end(name)
        tl.end(name, out)
    return out


# ---------------------------------------------------------------------------
# Public API.
# ---------------------------------------------------------------------------

def allreduce(tensor, average: bool = True, name: Optional[str] = None,
              op: Optional[Op] = None, axis_name: str = AXIS,
              plane: str = "auto"):
    """Sum (or average) ``tensor`` across all ranks.

    Parity: ``hvd.allreduce`` (``horovod/tensorflow/__init__.py:43-79``) —
    ``average=True`` divides by ``size()``. The sparse
    ``tf.IndexedSlices`` branch (allgather of values+indices,
    ``__init__.py:61-72``) lives in :func:`horovod_tpu.ops.sparse.
    allreduce_indexed_slices` and is auto-taken for
    :class:`~horovod_tpu.ops.sparse.IndexedSlices` inputs.

    Inside a ``shard_map`` over the world mesh this is a single XLA
    ``all-reduce`` over ICI; eagerly it dispatches a cached compiled
    collective (single-controller) or the host coordination plane
    (multi-process). ``plane`` routes the multi-process eager data plane
    per call — ``"auto"`` (``HOROVOD_RING_THRESHOLD`` elects), ``"star"``
    (coordinator relay) or ``"ring"`` (client-to-client) — the analog of
    the reference's per-call ``device_dense=`` placement knob
    (``horovod/tensorflow/__init__.py:43-55``, ``docs/gpus.md:40-45``);
    ignored in-trace (XLA owns the compiled plane).
    """
    from .sparse import IndexedSlices, allreduce_indexed_slices
    resolved = op if op is not None else (Op.AVERAGE if average else Op.SUM)
    if isinstance(tensor, IndexedSlices):
        if resolved not in (Op.SUM, Op.AVERAGE):
            raise ValueError(
                f"op={resolved} is not supported for sparse (IndexedSlices) "
                "allreduce; the sliced form only composes under SUM/AVERAGE "
                "(reference semantics, horovod/tensorflow/__init__.py:61-72)")
        return allreduce_indexed_slices(
            tensor, average=(resolved is Op.AVERAGE), name=name)

    if _in_trace():
        return _reduce_in_trace(tensor, resolved, axis_name)
    return _eager_dispatch("allreduce", tensor,
                           _auto_name("Allreduce", name), op=resolved,
                           plane=plane)


def allgather(tensor, name: Optional[str] = None, axis_name: str = AXIS,
              plane: str = "auto"):
    """Concatenate each rank's tensor along dim 0.

    Parity: ``hvd.allgather`` (``mpi_ops.py:151-167``) / ``MPI_Allgatherv``
    executor (``mpi_ops.cc:735-812``). Ranks may differ in the first
    dimension only — in compiled SPMD code shapes are static and equal; the
    variable-first-dim case is served eagerly by the coordination plane
    (negotiated sizes, ``mpi_ops.cc:345-405``) or in-trace via
    :func:`allgather_ragged`.
    """
    if _in_trace():
        return all_gather_invariant(tensor, axis_name, tiled=True)
    return _eager_dispatch("allgather", tensor, _auto_name("Allgather", name),
                           plane=plane)


def allgather_ragged(tensor, valid_size, max_size: int,
                     name: Optional[str] = None, axis_name: str = AXIS):
    """Variable-first-dim allgather under XLA static shapes.

    Each rank holds ``tensor`` padded to ``max_size`` rows, of which
    ``valid_size`` are real. Returns ``(gathered, sizes)`` where
    ``gathered`` is ``[size * max_size, ...]`` with each rank's block
    zero-padded past its ``valid_size``, and ``sizes`` is the per-rank
    valid-size vector — the in-trace analog of the negotiated
    ``tensor_sizes`` in the reference's allgather response
    (``mpi_message.h:94-139``, ``mpi_ops.cc:345-405``).
    """
    del name
    n = jnp.shape(tensor)[0]
    if n > max_size:
        # Error parity with the coordinator's negotiated-size path: an
        # input larger than the negotiated maximum is a validation error
        # (ConstructMPIResponse allgather sizing, mpi_ops.cc:345-405), not
        # a silent truncation.
        raise ValueError(
            f"Mismatched ALLGATHER tensor shapes: tensor has {n} rows but "
            f"max_size is {max_size}; allgather_ragged cannot truncate "
            f"(grow max_size or slice the input)")
    if not isinstance(valid_size, jax.core.Tracer):
        vs = int(valid_size)
        if not 0 <= vs <= max_size:
            raise ValueError(
                f"Mismatched ALLGATHER tensor shapes: valid_size {vs} is "
                f"outside [0, max_size={max_size}]; an oversized "
                f"valid_size would silently drop rows past max_size "
                f"(negotiated-size parity, mpi_ops.cc:345-405)")
    else:
        # Data-dependent valid_size inside jit cannot raise; clamp so an
        # out-of-range value cannot corrupt the mask or the sizes vector.
        valid_size = jnp.clip(valid_size, 0, max_size)
    if n != max_size:
        pad = [(0, max_size - n)] + [(0, 0)] * (tensor.ndim - 1)
        tensor = jnp.pad(tensor, pad)
    row = jnp.arange(max_size)
    keep = (row < valid_size).reshape((max_size,) + (1,) * (tensor.ndim - 1))
    tensor = jnp.where(keep, tensor, jnp.zeros_like(tensor))
    gathered = all_gather_invariant(tensor, axis_name, tiled=True)
    sizes = all_gather_invariant(jnp.asarray(valid_size, jnp.int32), axis_name)
    return gathered, sizes


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              axis_name: str = AXIS, plane: str = "auto"):
    """Every rank receives the root's tensor.

    Parity: ``hvd.broadcast`` (``mpi_ops.py:170-190``) / ``MPI_Bcast``
    executor (``mpi_ops.cc:1113-1140``; root passes input through,
    ``mpi_ops.cc:1869-1870``).
    """
    if runtime.is_initialized() and not 0 <= root_rank < runtime.size():
        # Parity: the coordinator validates root_rank (ConstructMPIResponse,
        # mpi_ops.cc:408-435); an impossible root must fail loudly, not
        # silently produce zeros from an all-false mask.
        raise ValueError(
            f"root_rank {root_rank} is out of range for world size "
            f"{runtime.size()}")
    if _in_trace():
        return _broadcast_in_trace(tensor, root_rank, axis_name)
    return _eager_dispatch("broadcast", tensor,
                           _auto_name("Broadcast", name), root_rank=root_rank,
                           plane=plane)


def alltoall(tensor, split_axis: int = 0, concat_axis: int = 0,
             name: Optional[str] = None, axis_name: str = AXIS,
             plane: str = "auto"):
    """All-to-all exchange (TPU-era extra; not in reference v0.11.2 —
    needed by all-to-all sequence/context parallelism, SURVEY §5.7).

    In-trace: ``lax.all_to_all`` over ICI. Eagerly: dim 0 is split into
    ``size`` blocks and rank ``r`` receives block ``r`` from every rank,
    concatenated — via the host coordination plane (multi-process) or a
    compiled exchange on the mesh (single-controller; the input must be
    sharded over the world axis, each rank's block being its tensor).
    """
    if _in_trace():
        return lax.all_to_all(tensor, axis_name, split_axis, concat_axis,
                              tiled=True)
    if split_axis != 0 or concat_axis != 0:
        raise NotImplementedError(
            "eager alltoall supports split_axis=0/concat_axis=0; transpose "
            "first or call in-trace under shard_map")
    return _eager_dispatch("alltoall", tensor, _auto_name("Alltoall", name),
                           plane=plane)


def reducescatter(tensor, average: bool = False,
                  name: Optional[str] = None, op: Optional[Op] = None,
                  axis_name: str = AXIS, plane: str = "auto"):
    """Reduce-scatter (TPU-era extra): reduce across ranks, then rank ``r``
    keeps block ``r`` of the first dimension.

    In-trace: ``lax.psum_scatter`` over ICI (SUM/AVERAGE). Eagerly:
    host coordination plane (multi-process; any reduction op) or compiled
    exchange (single-controller, input sharded over the world axis).
    """
    resolved = op if op is not None else (Op.AVERAGE if average else Op.SUM)
    if _in_trace():
        if resolved not in (Op.SUM, Op.AVERAGE):
            raise ValueError(
                f"in-trace reducescatter supports SUM/AVERAGE (XLA "
                f"reduce-scatter is a sum); got {resolved}")
        out = lax.psum_scatter(tensor, axis_name, tiled=True)
        if resolved is Op.AVERAGE:
            out = out / runtime.size()
        return out
    return _eager_dispatch("reducescatter", tensor,
                           _auto_name("Reducescatter", name), op=resolved,
                           plane=plane)


# ---------------------------------------------------------------------------
# Async eager API (reference model: ComputeAsync kernels + done callbacks,
# mpi_ops.cc:1752-1772 — dozens of collectives negotiate concurrently from
# TF's executor threads, feeding coordinator-side fusion). Handles are
# redeemed out-of-order-safe with synchronize().
# ---------------------------------------------------------------------------

class _DoneHandle:
    """Pre-completed handle (single-controller eager dispatch is already a
    single compiled call; there is nothing to overlap)."""

    def __init__(self, result):
        self._result = result


def _submit_async(kind: str, x, name: Optional[str], *, op: Op = Op.SUM,
                  root_rank: int = 0):
    if _in_trace():
        raise RuntimeError(
            f"{kind}_async_ is an eager API; inside compiled code use the "
            f"synchronous form — XLA already overlaps collectives")
    w = runtime.world()
    full_name = _auto_name(kind.capitalize(), name)
    if w.coord is not None:
        return w.coord.submit(kind, jnp.asarray(x), full_name, op=op,
                              root_rank=root_rank)
    return _DoneHandle(_eager_dispatch(kind, jnp.asarray(x), full_name,
                                       op=op, root_rank=root_rank))


def allreduce_async_(tensor, average: bool = True,
                     name: Optional[str] = None, op: Optional[Op] = None):
    """Non-blocking :func:`allreduce`; returns a handle for
    :func:`synchronize`. Overlapped submissions negotiate concurrently and
    are fused by the coordinator (64 MiB same-dtype batching)."""
    resolved = op if op is not None else (Op.AVERAGE if average else Op.SUM)
    return _submit_async("allreduce", tensor, name, op=resolved)


def allgather_async_(tensor, name: Optional[str] = None):
    """Non-blocking :func:`allgather`; returns a handle."""
    return _submit_async("allgather", tensor, name)


def broadcast_async_(tensor, root_rank: int = 0,
                     name: Optional[str] = None):
    """Non-blocking :func:`broadcast`; returns a handle."""
    if runtime.is_initialized() and not 0 <= root_rank < runtime.size():
        raise ValueError(
            f"root_rank {root_rank} is out of range for world size "
            f"{runtime.size()}")
    return _submit_async("broadcast", tensor, name, root_rank=root_rank)


def synchronize(handle):
    """Block until an async handle's collective completes; returns the
    result. Handles may be synchronized in any order."""
    if isinstance(handle, _DoneHandle):
        return handle._result
    return handle.client.wait(handle)


# ---------------------------------------------------------------------------
# Object collectives (TPU-era extras; later Horovod's broadcast_object /
# allgather_object). Arbitrary picklable Python objects ride the eager
# plane as uint8 payloads — epoch metadata, config dicts, vocabularies.
# ---------------------------------------------------------------------------

def broadcast_object(obj=None, root_rank: int = 0,
                     name: Optional[str] = None):
    """Every process receives the root process's picklable object.

    Object collectives operate over PROCESSES (objects are host-side
    metadata — resume epochs, config dicts, vocabularies), so ``root_rank``
    is a PROCESS index; under a single controller there is one host and
    this is the identity. Non-root ranks may pass anything (ignored). Two
    rounds: the payload length first (non-roots cannot know it), then the
    bytes.
    """
    import pickle

    import numpy as np

    w = runtime.world()
    if w.process_count == 1:
        return obj
    base = _auto_name("BroadcastObject", name)
    # Root test must use process_index, not controller_rank: with >1 device
    # per process the controller_rank is process_index * local_device_count,
    # and the coord-plane broadcast below keys roots by process index.
    payload = np.frombuffer(pickle.dumps(obj), np.uint8) \
        if w.process_index == root_rank else np.zeros(0, np.uint8)
    n = broadcast(jnp.asarray([payload.size], jnp.int32),
                  root_rank=root_rank, name=base + ".len")
    length = int(np.asarray(n)[0])
    buf = np.zeros(length, np.uint8)
    buf[:payload.size] = payload[:length]
    out = broadcast(jnp.asarray(buf), root_rank=root_rank,
                    name=base + ".bytes")
    return pickle.loads(np.asarray(out).tobytes())


def allgather_object(obj, name: Optional[str] = None) -> list:
    """Gather every process's picklable object; returns the process-ordered
    list on all processes (ragged payloads ride the negotiated-size
    allgather)."""
    import pickle

    import numpy as np

    w = runtime.world()
    if w.process_count == 1:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), np.uint8).reshape(-1, 1)
    base = _auto_name("AllgatherObject", name)
    lens = np.asarray(allgather(jnp.asarray([payload.shape[0]], jnp.int32),
                                name=base + ".len"))
    blob = np.asarray(allgather(jnp.asarray(payload), name=base + ".bytes"))
    out, off = [], 0
    for ln in lens.reshape(-1):
        ln = int(ln)
        out.append(pickle.loads(blob[off:off + ln].tobytes()))
        off += ln
    return out


def grouped_allreduce(tensors, average: bool = True,
                      name: Optional[str] = None,
                      fusion_threshold: Optional[int] = None,
                      axis_name: str = AXIS):
    """Allreduce a pytree of tensors as fused flat buckets.

    This is the TPU-native tensor fusion (reference: coordinator-side fusion
    of consecutive same-dtype responses into one 64 MiB-capped buffer,
    ``mpi_ops.cc:1395-1422``; semantics doc ``docs/tensor-fusion.md:6-28``).
    See :mod:`horovod_tpu.ops.fusion`.
    """
    from .fusion import fused_allreduce
    del name
    return fused_allreduce(tensors, average=average,
                           fusion_threshold=fusion_threshold,
                           axis_name=axis_name)
