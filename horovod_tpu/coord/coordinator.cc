// hvdcoord — host coordination core for horovod_tpu.
//
// TPU-native analog of the reference's native runtime
// (horovod/tensorflow/mpi_ops.cc): a rank-0 coordinator counts name-keyed
// collective announcements from every rank, validates them across ranks with
// the same error taxonomy (ConstructMPIResponse, mpi_ops.cc:266-474), detects
// stalls (CheckForStalledTensors, mpi_ops.cc:1153-1196), plans tensor fusion
// (mpi_ops.cc:1395-1422) and executes the *eager host data plane* — the
// op-at-a-time collectives issued outside compiled XLA programs (metric
// averaging, epoch broadcast, init-time weight sync). The compiled data plane
// (gradient psum over ICI) never touches this code; XLA schedules it.
//
// Transport: length-prefixed binary messages over TCP (DCN stand-in) in a
// star topology — every rank (including 0) connects as a client to the
// coordinator server thread. This replaces the reference's
// MPI_Send/Probe/Recv of FlatBuffers (mpi_ops.cc:1319-1374); the message
// *content* is the same information, the wire format is our own.
//
// Threading model mirrors the reference's single-owner discipline
// (SURVEY §5.2): all coordinator state is owned by the server thread; each
// client has a receiver thread feeding a completed-op map guarded by one
// mutex + condvar; enqueue serializes sends with a socket mutex.

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <memory>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace hvdcoord {

// ---------------------------------------------------------------------------
// Protocol constants (values are wire ABI; keep stable).
// ---------------------------------------------------------------------------

enum class ReqType : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  // TPU-era extras (compiled-plane parity: ops/collectives.py alltoall /
  // reducescatter; not in reference v0.11.2).
  kAlltoall = 3,
  kReducescatter = 4,
  // Large-payload allreduce announced WITHOUT its payload: the data plane
  // is a client-to-client chunked ring (reduce-scatter + allgather), the
  // bandwidth-optimal algorithm the reference gets from MPI_Allreduce
  // (mpi_ops.cc:1061-1064 — every real MPI rings large messages). The
  // coordinator only negotiates/validates and ships the ring plan; payload
  // bytes never transit rank 0, so per-rank traffic is 2·(N-1)/N · bytes
  // independent of world size (vs the star's N·bytes coordinator
  // ingress/egress).
  kAllreduceRing = 5,
  // Large allgather on the same ring plane: each rank's block circulates
  // N-1 hops, so per-rank traffic is ~(output - own block) — the star
  // would push N x output through the coordinator's egress. Ragged first
  // dims ride the same negotiated sizes the star allgather uses (the
  // reference's MPI_Allgatherv ring, mpi_ops.cc:788-808).
  kAllgatherRing = 6,
  // Large broadcast (root-elected): chunk-pipelined chain from the root.
  kBroadcastRing = 7,
  // Large alltoall on the peer data plane: direct pairwise block exchange
  // over the full-duplex peer-socket mesh (every rank sends N-1 blocks
  // straight to their destinations), so per-rank traffic is
  // (N-1)/N · payload independent of world size — the star would relay
  // N · payload through rank 0 in each direction.
  kAlltoallRing = 8,
  // Large reducescatter: the reduce-scatter PHASE of the ring allreduce
  // alone (each rank ends owning its fully-reduced block); per-rank
  // traffic (N-1)/N · payload, again world-size independent.
  kReducescatterRing = 9,
};
enum class RespType : uint8_t {
  kAllreduce = 0,
  kAllgather = 1,
  kBroadcast = 2,
  kError = 3,
  kShutdown = 4,
  kAlltoall = 5,
  kReducescatter = 6,
  kAllreduceRing = 7,  // carries the ring plan (peer addresses), no payload
  kAllgatherRing = 8,  // ring plan + negotiated per-rank first dims
  // Ragged allgathers can legitimately STRADDLE the ring threshold (some
  // ranks' blocks above it, some below — no config skew involved). The
  // coordinator resolves the mix by asking the ring announcers to
  // resubmit with their payload (one extra round trip, mixed case only).
  kResubmitStar = 9,
  // Large broadcast over the ring as a chunk-pipelined CHAIN from the
  // root: per-link traffic is exactly the payload (the star's
  // coordinator egress is N x payload) — the bandwidth model inside
  // MPI_Bcast (mpi_ops.cc:1134-1136). Only the ROOT elects (it alone
  // ships payload); non-roots follow the plan.
  kBroadcastRing = 10,
  kAlltoallRing = 11,       // mesh plan: direct pairwise block exchange
  kReducescatterRing = 12,  // ring plan: reduce-scatter phase only
  // World abort (v6): a rank died (socket closed without a clean shutdown)
  // or went silent past HVD_HEARTBEAT_TIMEOUT. Broadcast to every
  // surviving rank so every blocked hvdcoord_wait fails fast with the dead
  // rank's identity (-> Python WorkerFailureError) instead of hanging.
  kAbort = 13,
  // Pending live resize (v7): pushed to every rank the moment an admin
  // resize request is accepted (sizes = {target_world, new_coord_port,
  // generation}); also piggybacked on every heartbeat ack. Purely
  // advisory — ranks act on it at their next step boundary
  // (horovod_tpu.elastic.ResizeCoordinator), never mid-collective.
  kResizeNotice = 14,
};

// Reduction op for allreduce/reducescatter. The reference supports SUM only
// (MPI_SUM, mpi_ops.cc:1061-1064); MIN/MAX/PROD close the asymmetry with the
// compiled plane's Op enum (average = SUM + client-side divide).
enum class RedOp : uint8_t { kSum = 0, kMin = 1, kMax = 2, kProd = 3 };

const char* RedOpName(RedOp o) {
  switch (o) {
    case RedOp::kSum: return "SUM";
    case RedOp::kMin: return "MIN";
    case RedOp::kMax: return "MAX";
    case RedOp::kProd: return "PRODUCT";
  }
  return "UNKNOWN";
}

// Dtypes: the reference's nine (mpi_message.h:26-36) plus bfloat16 (TPU era).
enum class DType : uint8_t {
  kU8 = 0, kI8 = 1, kU16 = 2, kI16 = 3, kI32 = 4, kI64 = 5,
  kF32 = 6, kF64 = 7, kBool = 8, kBF16 = 9,
};

const char* DTypeName(DType t) {
  switch (t) {
    case DType::kU8: return "uint8";
    case DType::kI8: return "int8";
    case DType::kU16: return "uint16";
    case DType::kI16: return "int16";
    case DType::kI32: return "int32";
    case DType::kI64: return "int64";
    case DType::kF32: return "float32";
    case DType::kF64: return "float64";
    case DType::kBool: return "bool";
    case DType::kBF16: return "bfloat16";
  }
  return "unknown";
}

const char* ReqTypeName(ReqType t) {
  switch (t) {
    case ReqType::kAllreduce: return "ALLREDUCE";
    case ReqType::kAllgather: return "ALLGATHER";
    case ReqType::kBroadcast: return "BROADCAST";
    case ReqType::kAlltoall: return "ALLTOALL";
    case ReqType::kReducescatter: return "REDUCESCATTER";
    // Distinct names so a mixed star/ring announcement (skewed
    // HOROVOD_RING_THRESHOLD across ranks) produces a self-explaining
    // mismatch error.
    case ReqType::kAllreduceRing: return "ALLREDUCE_RING";
    case ReqType::kAllgatherRing: return "ALLGATHER_RING";
    case ReqType::kBroadcastRing: return "BROADCAST_RING";
    case ReqType::kAlltoallRing: return "ALLTOALL_RING";
    case ReqType::kReducescatterRing: return "REDUCESCATTER_RING";
  }
  return "UNKNOWN";
}

// Defense-in-depth for direct/nonconforming clients: a request whose type
// byte is outside the known enum must become a NAMED validation error, not
// fall through response-construction switches into a default-initialized
// Response (protocol-version checks already reject mixed builds at hello).
bool KnownReqType(ReqType t) {
  return static_cast<uint8_t>(t) <=
         static_cast<uint8_t>(ReqType::kReducescatterRing);
}

int DTypeSize(DType t) {
  switch (t) {
    case DType::kU8: case DType::kI8: case DType::kBool: return 1;
    case DType::kU16: case DType::kI16: case DType::kBF16: return 2;
    case DType::kI32: case DType::kF32: return 4;
    case DType::kI64: case DType::kF64: return 8;
  }
  return 1;
}

// ---------------------------------------------------------------------------
// Wire helpers: length-prefixed frames of {u8 tag, payload}.
// ---------------------------------------------------------------------------

enum class MsgTag : uint8_t {
  kRequest = 1,
  kResponse = 2,
  kShutdown = 3,
  kHelloAck = 4,
  // Liveness plane (v6): clients beat every ~HVD_HEARTBEAT_TIMEOUT/4; the
  // coordinator acks each beat. Either side going silent past the timeout
  // is a worker/coordinator failure, not a stall — the world ABORTS
  // (RespType::kAbort) instead of hanging, the failure mode the reference
  // inherits from MPI (a dead rank wedges MPI_Allreduce forever;
  // CheckForStalledTensors only *warns*, mpi_ops.cc:1153-1196).
  kHeartbeat = 5,
  kHeartbeatAck = 6,
  // Admin plane (v7): an operator (or the supervising tpurun) connects to
  // the coordinator port AFTER world formation and requests a live resize
  // of the world — the Elastic-Horovod "host discovery" role, inverted:
  // instead of the launcher polling a discovery script, the resize intent
  // is pushed into the running world through the plane that already talks
  // to every rank. kResizeRequest{target} with target=0 is a pure status
  // query (world size + pending resize), used by tpurun's supervision
  // loop to learn when it must spawn new ranks.
  kResizeRequest = 7,
  kResizeReply = 8,
};

// Wire protocol version; bumped on incompatible frame-layout changes. Both
// sides are built from this one source so a mismatch means two ranks loaded
// different builds — exactly the cross-rank config skew init must reject
// (the analog of the reference's per-tensor placement validation,
// mpi_ops.cc:439-449, moved to init time where TPU worlds can check it).
// v5: ring election extended to alltoall/reducescatter; hello may carry an
// advertise-address suffix (HOROVOD_RING_ADVERTISE_ADDR).
// v6: liveness plane — kHeartbeat/kHeartbeatAck frames and the kAbort
// response (fail-fast worker-failure detection, HVD_HEARTBEAT_TIMEOUT).
// v7: live-resize plane — post-formation admin connections
// (kResizeRequest/kResizeReply), the kResizeNotice push, and the pending-
// resize payload appended to every kHeartbeatAck (ranks learn of a pending
// resize at a step boundary with ZERO extra collectives on the hot path).
constexpr int32_t kProtocolVersion = 7;

// ---------------------------------------------------------------------------
// Env parsing. atoll/atof would silently truncate ("4M" -> 4) or zero out
// garbage, degrading performance with no diagnostic; reject trailing
// characters loudly and keep the default instead.
// ---------------------------------------------------------------------------

long long ParseEnvI64(const char* name, long long dflt,
                      bool* parsed_ok = nullptr) {
  if (parsed_ok) *parsed_ok = false;
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  errno = 0;
  long long out = strtoll(v, &end, 10);
  if (end == v || *end != '\0' || errno == ERANGE) {
    fprintf(stderr,
            "hvdcoord: ignoring malformed %s=\"%s\" (expected a plain "
            "integer; size suffixes like \"4M\" are not supported) — "
            "using default %lld\n",
            name, v, dflt);
    return dflt;
  }
  if (parsed_ok) *parsed_ok = true;
  return out;
}

double ParseEnvF64(const char* name, double dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  errno = 0;
  double out = strtod(v, &end);
  if (end == v || *end != '\0' || errno == ERANGE) {
    fprintf(stderr,
            "hvdcoord: ignoring malformed %s=\"%s\" (expected a plain "
            "number) — using default %g\n",
            name, v, dflt);
    return dflt;
  }
  return out;
}

struct Request {
  int32_t rank = -1;
  ReqType type = ReqType::kAllreduce;
  DType dtype = DType::kF32;
  RedOp red_op = RedOp::kSum;
  int32_t root_rank = -1;
  std::vector<int64_t> shape;
  std::string name;
  std::string payload;  // tensor bytes (empty for non-root broadcast)
};

struct Response {
  RespType type = RespType::kAllreduce;
  std::string name;
  std::string error;
  std::vector<int64_t> sizes;  // allgather: per-rank first dims
  std::string payload;         // result bytes
  // Fusion (reference: MPIResponse.tensor_names[] >1 entries => fused,
  // mpi_message.h:94-139; decision mpi_ops.cc:1395-1422): a fused response
  // carries the concatenated results of several same-dtype allreduces in one
  // frame; the client splits by per-name byte counts.
  std::vector<std::string> fused_names;
  std::vector<int64_t> fused_nbytes;
  // Ring plan (kAllreduceRing): "ip:port" peer data-plane addresses indexed
  // by rank; clients run the chunked ring among themselves.
  std::vector<std::string> ring_peers;
  // dtype: on the wire for ring plans (sizes non-root broadcast
  // buffers); otherwise coordinator-local bookkeeping.
  DType dtype = DType::kF32;
  std::vector<int64_t> shape;                 // output shape (timeline args)
  std::vector<std::string> per_rank_payloads; // alltoall/reducescatter
};

class Buf {
 public:
  void PutU8(uint8_t v) { data_.push_back(static_cast<char>(v)); }
  void PutI32(int32_t v) { Raw(&v, 4); }
  void PutI64(int64_t v) { Raw(&v, 8); }
  void PutStr(const std::string& s) {
    PutI64(static_cast<int64_t>(s.size()));
    data_.append(s);
  }
  void Raw(const void* p, size_t n) {
    data_.append(reinterpret_cast<const char*>(p), n);
  }
  const std::string& str() const { return data_; }

 private:
  std::string data_;
};

class Reader {
 public:
  explicit Reader(const std::string& d) : d_(d) {}
  uint8_t GetU8() { return static_cast<uint8_t>(d_[off_++]); }
  int32_t GetI32() { int32_t v; memcpy(&v, d_.data() + off_, 4); off_ += 4; return v; }
  int64_t GetI64() { int64_t v; memcpy(&v, d_.data() + off_, 8); off_ += 8; return v; }
  std::string GetStr() {
    int64_t n = GetI64();
    std::string s = d_.substr(off_, n);
    off_ += n;
    return s;
  }

 private:
  const std::string& d_;
  size_t off_ = 0;
};

std::string EncodeRequest(const Request& r) {
  Buf b;
  b.PutU8(static_cast<uint8_t>(MsgTag::kRequest));
  b.PutI32(r.rank);
  b.PutU8(static_cast<uint8_t>(r.type));
  b.PutU8(static_cast<uint8_t>(r.dtype));
  b.PutU8(static_cast<uint8_t>(r.red_op));
  b.PutI32(r.root_rank);
  b.PutU8(static_cast<uint8_t>(r.shape.size()));
  for (int64_t d : r.shape) b.PutI64(d);
  b.PutStr(r.name);
  b.PutStr(r.payload);
  return b.str();
}

Request DecodeRequest(Reader& rd) {
  Request r;
  r.rank = rd.GetI32();
  r.type = static_cast<ReqType>(rd.GetU8());
  r.dtype = static_cast<DType>(rd.GetU8());
  r.red_op = static_cast<RedOp>(rd.GetU8());
  r.root_rank = rd.GetI32();
  int nd = rd.GetU8();
  for (int i = 0; i < nd; i++) r.shape.push_back(rd.GetI64());
  r.name = rd.GetStr();
  r.payload = rd.GetStr();
  return r;
}

std::string EncodeResponse(const Response& r) {
  Buf b;
  b.PutU8(static_cast<uint8_t>(MsgTag::kResponse));
  b.PutU8(static_cast<uint8_t>(r.type));
  b.PutStr(r.name);
  b.PutStr(r.error);
  b.PutI32(static_cast<int32_t>(r.sizes.size()));
  for (int64_t s : r.sizes) b.PutI64(s);
  b.PutI32(static_cast<int32_t>(r.fused_names.size()));
  for (size_t i = 0; i < r.fused_names.size(); i++) {
    b.PutStr(r.fused_names[i]);
    b.PutI64(r.fused_nbytes[i]);
  }
  b.PutI32(static_cast<int32_t>(r.ring_peers.size()));
  for (const auto& p : r.ring_peers) b.PutStr(p);
  // dtype AND shape ride the wire for ring PLANS: a non-root broadcast
  // client has no stash, so the plan itself must size the receive buffer
  // (shape was coordinator-local before v5 — the r3 chain sized non-root
  // buffers from an empty shape).
  b.PutU8(static_cast<uint8_t>(r.dtype));
  b.PutU8(static_cast<uint8_t>(r.shape.size()));
  for (int64_t d : r.shape) b.PutI64(d);
  b.PutStr(r.payload);
  return b.str();
}

Response DecodeResponse(Reader& rd) {
  Response r;
  r.type = static_cast<RespType>(rd.GetU8());
  r.name = rd.GetStr();
  r.error = rd.GetStr();
  int n = rd.GetI32();
  for (int i = 0; i < n; i++) r.sizes.push_back(rd.GetI64());
  int nf = rd.GetI32();
  for (int i = 0; i < nf; i++) {
    r.fused_names.push_back(rd.GetStr());
    r.fused_nbytes.push_back(rd.GetI64());
  }
  int np = rd.GetI32();
  for (int i = 0; i < np; i++) r.ring_peers.push_back(rd.GetStr());
  r.dtype = static_cast<DType>(rd.GetU8());
  int nd = rd.GetU8();
  for (int i = 0; i < nd; i++) r.shape.push_back(rd.GetI64());
  r.payload = rd.GetStr();
  return r;
}

// Framed socket IO. Returns false on EOF/error.
bool SendFrame(int fd, std::mutex& mu, const std::string& body) {
  std::lock_guard<std::mutex> l(mu);
  uint64_t len = body.size();
  std::string frame(reinterpret_cast<char*>(&len), 8);
  frame += body;
  size_t off = 0;
  while (off < frame.size()) {
    ssize_t n = ::send(fd, frame.data() + off, frame.size() - off, MSG_NOSIGNAL);
    if (n <= 0) return false;
    off += static_cast<size_t>(n);
  }
  return true;
}

bool RecvAll(int fd, void* p, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, reinterpret_cast<char*>(p) + off, n - off, 0);
    if (r <= 0) return false;
    off += static_cast<size_t>(r);
  }
  return true;
}

// Frames above this are protocol violations (a stray/hostile connection
// sending a garbage 64-bit length must not trigger a std::bad_alloc that
// terminates the coordinator); 16 GiB comfortably exceeds any real tensor
// the host eager plane carries.
constexpr uint64_t kMaxFrameBytes = 1ull << 34;

bool RecvFrame(int fd, std::string* body) {
  uint64_t len;
  if (!RecvAll(fd, &len, 8)) return false;
  if (len > kMaxFrameBytes) return false;
  body->resize(len);
  return len == 0 || RecvAll(fd, &(*body)[0], len);
}

// ---------------------------------------------------------------------------
// Reduction kernels (host eager plane; SUM like the reference's MPI_SUM path,
// mpi_ops.cc:1061-1064).
// ---------------------------------------------------------------------------

template <typename T>
void ReduceIntoRaw(RedOp op, char* acc, const char* in, size_t nbytes) {
  T* a = reinterpret_cast<T*>(acc);
  const T* b = reinterpret_cast<const T*>(in);
  size_t n = nbytes / sizeof(T);
  switch (op) {
    case RedOp::kSum:
      for (size_t i = 0; i < n; i++) a[i] += b[i];
      return;
    case RedOp::kMin:
      for (size_t i = 0; i < n; i++) a[i] = std::min(a[i], b[i]);
      return;
    case RedOp::kMax:
      for (size_t i = 0; i < n; i++) a[i] = std::max(a[i], b[i]);
      return;
    case RedOp::kProd:
      for (size_t i = 0; i < n; i++) a[i] *= b[i];
      return;
  }
}

// bfloat16: widen to float, reduce, narrow (round-to-nearest-even).
void ReduceIntoBF16(RedOp op, char* accp, const char* inp, size_t nbytes) {
  uint16_t* a = reinterpret_cast<uint16_t*>(accp);
  const uint16_t* b = reinterpret_cast<const uint16_t*>(inp);
  size_t n = nbytes / 2;
  for (size_t i = 0; i < n; i++) {
    uint32_t av = static_cast<uint32_t>(a[i]) << 16;
    uint32_t bv = static_cast<uint32_t>(b[i]) << 16;
    float af, bf;
    memcpy(&af, &av, 4);
    memcpy(&bf, &bv, 4);
    switch (op) {
      case RedOp::kSum: af += bf; break;
      case RedOp::kMin: af = std::min(af, bf); break;
      case RedOp::kMax: af = std::max(af, bf); break;
      case RedOp::kProd: af *= bf; break;
    }
    uint32_t out;
    memcpy(&out, &af, 4);
    // round-to-nearest-even on the dropped 16 bits
    uint32_t rounded = out + 0x7FFF + ((out >> 16) & 1);
    a[i] = static_cast<uint16_t>(rounded >> 16);
  }
}

void ReducePayloadRaw(DType t, RedOp op, char* acc, const char* in,
                      size_t nbytes) {
  switch (t) {
    case DType::kU8: return ReduceIntoRaw<uint8_t>(op, acc, in, nbytes);
    case DType::kI8: return ReduceIntoRaw<int8_t>(op, acc, in, nbytes);
    case DType::kU16: return ReduceIntoRaw<uint16_t>(op, acc, in, nbytes);
    case DType::kI16: return ReduceIntoRaw<int16_t>(op, acc, in, nbytes);
    case DType::kI32: return ReduceIntoRaw<int32_t>(op, acc, in, nbytes);
    case DType::kI64: return ReduceIntoRaw<int64_t>(op, acc, in, nbytes);
    case DType::kF32: return ReduceIntoRaw<float>(op, acc, in, nbytes);
    case DType::kF64: return ReduceIntoRaw<double>(op, acc, in, nbytes);
    case DType::kBool: {
      // bool: SUM/MAX = logical OR, MIN/PROD = logical AND (the lattice
      // forms the reference's MPI byte-sum reduces to for 0/1 values).
      uint8_t* a = reinterpret_cast<uint8_t*>(acc);
      const uint8_t* b = reinterpret_cast<const uint8_t*>(in);
      bool is_or = (op == RedOp::kSum || op == RedOp::kMax);
      for (size_t i = 0; i < nbytes; i++)
        a[i] = is_or ? (a[i] || b[i]) : (a[i] && b[i]);
      return;
    }
    case DType::kBF16: return ReduceIntoBF16(op, acc, in, nbytes);
  }
}

void ReducePayload(DType t, RedOp op, std::string* acc, const std::string& in) {
  ReducePayloadRaw(t, op, &(*acc)[0], in.data(), in.size());
}

// Reduce every announced payload (requests[1..n)) into *acc, striping the
// byte range across a few threads for large tensors: the coordinator's
// host reduction is O(size · bytes) on one thread otherwise — fine on the
// reference's per-rank design (each rank reduces its own ops), but here
// rank 0 does the whole world's star-plane work (VERDICT r3 weak #4).
// Stripes are element-aligned; each thread walks all ranks within its
// range (one pass through cache per stripe). Engaged only for >=256 KiB
// payloads and >1 available core; HOROVOD_COORD_REDUCE_THREADS overrides
// the thread count (0/1 forces the serial path — also how tests exercise
// the striped path on a 1-core host by setting it >1).
void ReduceAllStriped(DType t, RedOp op, std::string* acc,
                      const std::vector<Request>& requests) {
  const size_t nbytes = acc->size();
  static bool env_parsed = false;
  static const long long kThreads = [] {
    long long v = ParseEnvI64("HOROVOD_COORD_REDUCE_THREADS",
                              std::thread::hardware_concurrency(),
                              &env_parsed);
    return v < 0 ? 0 : v;
  }();
  const size_t esz = static_cast<size_t>(DTypeSize(t));
  // Default (env unset): up to 4 stripes — past that the reduce is memory
  // -bandwidth bound on most hosts. An EXPLICIT override is honored up to
  // 16 (clamped loudly; silent caps hide why raising the knob stops
  // helping).
  // "Explicit" = set AND parseable (parsed_ok from the shared parser): a
  // malformed value falls back to ParseEnvI64's default
  // (hardware_concurrency) and must then also get the default 4-stripe
  // cap, or the "using default" warning would lie.
  long long want = env_parsed ? kThreads : std::min<long long>(kThreads, 4);
  if (want > 16) {
    static std::atomic<bool> warned{false};
    if (!warned.exchange(true))
      fprintf(stderr,
              "hvdcoord: HOROVOD_COORD_REDUCE_THREADS=%lld clamped to 16 "
              "(stripe cap)\n", want);
    want = 16;
  }
  int stripes = (nbytes >= (256u << 10) && want > 1)
                    ? static_cast<int>(want)
                    : 1;
  if (stripes <= 1) {
    for (size_t r = 1; r < requests.size(); r++)
      ReducePayload(t, op, acc, requests[r].payload);
    return;
  }
  const size_t elems = nbytes / esz;
  std::vector<std::thread> ts;
  ts.reserve(stripes);
  for (int s = 0; s < stripes; s++) {
    const size_t lo = elems * s / stripes * esz;
    const size_t hi = elems * (s + 1) / stripes * esz;
    ts.emplace_back([&, lo, hi] {
      for (size_t r = 1; r < requests.size(); r++)
        ReducePayloadRaw(t, op, &(*acc)[lo],
                         requests[r].payload.data() + lo, hi - lo);
    });
  }
  for (auto& th : ts) th.join();
}

// ---------------------------------------------------------------------------
// Chrome-trace timeline (reference: timeline.cc; doc docs/timeline.md).
// Written by the coordinator only, covering every rank's readiness.
// ---------------------------------------------------------------------------

class Timeline {
 public:
  void Open(const std::string& path) {
    f_ = fopen(path.c_str(), "w");
    if (f_) fputs("[\n", f_);
    start_ = Now();
  }
  ~Timeline() { Close(); }
  void Close() {
    if (f_) {
      fputs("{}]\n", f_);
      fclose(f_);
      f_ = nullptr;
    }
  }
  bool enabled() const { return f_ != nullptr; }

  int64_t Now() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  int Pid(const std::string& name) {
    auto it = pids_.find(name);
    if (it != pids_.end()) return it->second;
    int pid = static_cast<int>(pids_.size()) + 1;
    pids_[name] = pid;
    fprintf(f_,
            "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%d,"
            "\"args\":{\"name\":\"%s\"}},\n", pid, name.c_str());
    return pid;
  }

  // args_json, when non-empty, is a preformatted JSON object attached to the
  // event (the reference's End logs output dtype+shape, timeline.cc:203-220).
  // Every event carries tid 0: Perfetto and some catapult builds need a tid
  // to pair B/E durations within a pid.
  void Event(const std::string& name, const char* ph, const char* ev,
             const std::string& args_json = "") {
    if (!f_) return;
    std::lock_guard<std::mutex> l(mu_);
    if (args_json.empty()) {
      fprintf(f_,
              "{\"name\":\"%s\",\"ph\":\"%s\",\"pid\":%d,\"tid\":0,"
              "\"ts\":%lld},\n",
              ev, ph, Pid(name), static_cast<long long>(Now() - start_));
    } else {
      fprintf(f_,
              "{\"name\":\"%s\",\"ph\":\"%s\",\"pid\":%d,\"tid\":0,"
              "\"ts\":%lld,\"args\":%s},\n",
              ev, ph, Pid(name), static_cast<long long>(Now() - start_),
              args_json.c_str());
    }
    fflush(f_);
  }

  // Typed transitions enforcing the reference's per-tensor state machine
  // UNKNOWN→NEGOTIATING→TOP_LEVEL→ACTIVITY (timeline.h:37-42, asserted in
  // timeline.cc:118-135). A call out of order aborts: an unbalanced B/E
  // stream corrupts the whole trace, so misuse must fail loudly. All typed
  // calls happen on the coordinator thread; states_ needs no lock.
  void NegotiateStart(const std::string& name, const char* op) {
    Expect(name, State::kUnknown, "NegotiateStart");
    states_[name] = {State::kNegotiating, 0};
    Event(name, "B", (std::string("NEGOTIATE_") + op).c_str());
  }
  void NegotiateRankReady(const std::string& name, int rank) {
    Expect(name, State::kNegotiating, "NegotiateRankReady");
    std::ostringstream ev;
    ev << "rank_" << rank << "_ready";
    Event(name, "i", ev.str().c_str());
  }
  void NegotiateEnd(const std::string& name, const char* op) {
    Expect(name, State::kNegotiating, "NegotiateEnd");
    states_[name] = {State::kUnknown, 0};
    Event(name, "E", (std::string("NEGOTIATE_") + op).c_str());
  }
  void Start(const std::string& name, const char* op) {
    Expect(name, State::kUnknown, "Start");
    states_[name] = {State::kTopLevel, 0};
    Event(name, "B", op);
  }
  void ActivityStart(const std::string& name, const char* act) {
    auto& st = states_[name];
    if (st.s != State::kTopLevel && st.s != State::kActivity)
      Violate(name, "ActivityStart");
    st.s = State::kActivity;
    st.depth++;
    Event(name, "B", act);
  }
  void ActivityEnd(const std::string& name, const char* act) {
    auto& st = states_[name];
    if (st.s != State::kActivity) Violate(name, "ActivityEnd");
    st.depth--;
    if (st.depth == 0) st.s = State::kTopLevel;
    Event(name, "E", act);
  }
  void End(const std::string& name, const std::string& args_json = "") {
    Expect(name, State::kTopLevel, "End");
    states_.erase(name);
    Event(name, "E", "", args_json);
  }

 private:
  enum class State { kUnknown, kNegotiating, kTopLevel, kActivity };
  struct TState {
    State s = State::kUnknown;
    int depth = 0;
  };

  void Violate(const std::string& name, const char* call) {
    fprintf(stderr, "[hvdcoord] timeline state violation: %s(%s)\n", call,
            name.c_str());
    abort();
  }
  void Expect(const std::string& name, State want, const char* call) {
    auto it = states_.find(name);
    State s = it == states_.end() ? State::kUnknown : it->second.s;
    if (s != want) Violate(name, call);
  }

  FILE* f_ = nullptr;
  int64_t start_ = 0;
  std::mutex mu_;
  std::unordered_map<std::string, int> pids_;
  std::unordered_map<std::string, TState> states_;
};

// ---------------------------------------------------------------------------
// Coordinator (rank-0 server thread).
// ---------------------------------------------------------------------------

struct PendingTensor {
  std::vector<Request> requests;   // one per announced rank
  std::vector<bool> announced;     // by rank
  std::chrono::steady_clock::time_point first_seen;
  int count = 0;
};

// Whether a hello's ring advertise-address suffix is a well-formed IPv4
// literal ("a.b.c.d" or "a.b.c.d:port", port 1-65535). Conforming clients
// validate HOROVOD_RING_ADVERTISE_ADDR before sending it (Client::Hello
// below); the coordinator re-validates at hello so a NONconforming
// client's garbage address gets a named hello rejection HERE instead of
// being distributed in ring plans and surfacing one op later as connector
// failures on OTHER ranks.
static bool ValidAdvertiseAddr(const std::string& a) {
  size_t colon = a.find(':');
  std::string ip = a.substr(0, colon);
  in_addr probe{};
  if (ip.empty() || inet_pton(AF_INET, ip.c_str(), &probe) != 1)
    return false;
  if (colon == std::string::npos) return true;
  const char* s = a.c_str() + colon + 1;
  char* end = nullptr;
  errno = 0;
  long p = strtol(s, &end, 10);
  return end != s && *end == '\0' && errno != ERANGE && p >= 1 &&
         p <= 65535;
}

class Coordinator {
 public:
  Coordinator(int size, int port, int64_t fusion_threshold, double stall_secs,
              const std::string& timeline_path)
      : size_(size), port_(port), fusion_threshold_(fusion_threshold),
        stall_secs_(stall_secs) {
    // Batch-window width (the reference's 5 ms background-tick period,
    // mpi_ops.cc:1295); tunable for latency-sensitive eager workloads.
    tick_ms_ = static_cast<int>(ParseEnvI64("HOROVOD_COORD_TICK_MS", 5));
    if (tick_ms_ < 0) tick_ms_ = 0;
    // Liveness deadline (seconds; 0 disables). A rank whose last frame —
    // heartbeat or otherwise — is older than this is declared dead and the
    // world ABORTS. The Elastic-Horovod-era fix for the reference's
    // warn-only stall handling (mpi_ops.cc:1153-1196).
    heartbeat_timeout_ = ParseEnvF64("HVD_HEARTBEAT_TIMEOUT", 30.0);
    if (heartbeat_timeout_ < 0) heartbeat_timeout_ = 0;
    // Resize generation: how many live resizes this job has been through
    // (exported to re-formed/new ranks as HVD_RESIZE_GENERATION so the
    // re-initialized coordinator numbers the NEXT resize correctly and
    // sync-collective names never collide across resizes).
    resize_generation_ =
        static_cast<int32_t>(ParseEnvI64("HVD_RESIZE_GENERATION", 0));
    if (resize_generation_ < 0) resize_generation_ = 0;
    if (!timeline_path.empty()) timeline_.Open(timeline_path);
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        listen(listen_fd_, size_) != 0) {
      perror("hvdcoord: coordinator bind/listen");
      ok_ = false;
      return;
    }
    thread_ = std::thread(&Coordinator::Serve, this);
  }

  ~Coordinator() {
    // Live-resize handoff: when the world tears this plane down to
    // re-form (rank 0 calls hvdcoord_shutdown mid-resize), an accepted
    // resize the supervising launcher has NOT yet fetched would vanish
    // with us — and with it the launcher's only way to learn the new
    // port / spawn grow ranks. Hold the teardown briefly (bounded; the
    // launcher polls ~2x/second) until one admin query has seen the
    // pending triple. Skipped when the serve thread already exited
    // (abort path) or nothing is pending.
    if (resize_fetch_pending_.load() && !serve_done_.load()) {
      double linger = ParseEnvF64("HVD_RESIZE_LINGER", 2.0);
      auto deadline = std::chrono::steady_clock::now() +
                      std::chrono::duration<double>(linger < 0 ? 0 : linger);
      while (std::chrono::steady_clock::now() < deadline &&
             resize_fetch_pending_.load() && !serve_done_.load())
        std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
    shutdown_.store(true);
    if (listen_fd_ >= 0) ::close(listen_fd_);
    if (thread_.joinable()) thread_.join();
    for (int fd : client_fds_)
      if (fd >= 0) ::close(fd);
  }

  bool ok() const { return ok_; }

  // Deterministic-fault-injection hook (HVD_FAULT_SPEC coord:mute@step=N):
  // stop acking client heartbeats so every client observes a silent
  // coordinator and fails over — the only way to exercise the
  // dead-coordinator detection path without a real network partition.
  void set_mute_acks(bool m) { mute_acks_.store(m); }

 private:
  void Serve() {
    // Accept exactly `size` clients; client's first frame is its hello
    // {rank, size, protocol version}. Cross-rank config skew (wrong world
    // size, mismatched build) and malformed/duplicate hellos are rejected
    // with a named error WITHOUT killing the accept loop — a stray
    // connection must not take down the whole world's coordinator
    // (membership-fault hardening; the reference's MPI world membership is
    // fixed by mpirun so it never faces this, but it does validate
    // cross-rank consistency per tensor, mpi_ops.cc:439-449 — here the
    // world-level part happens once, at init).
    client_fds_.assign(size_, -1);
    int accepted = 0;
    while (accepted < size_ && !shutdown_.load()) {
      // Poll-before-accept: a blocked accept() is not reliably woken by
      // closing the listen fd, so a world torn down DURING formation
      // (e.g. its ranks aborted before all peers connected) must not
      // wedge the destructor's thread join forever.
      pollfd lp{listen_fd_, POLLIN, 0};
      int pn = ::poll(&lp, 1, 100);
      if (pn < 0 || (lp.revents & (POLLERR | POLLNVAL | POLLHUP))) {
        serve_done_.store(true);
        return;
      }
      if (pn == 0) continue;  // timeout: re-check shutdown_
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {  // listen socket closed (shutdown path)
        serve_done_.store(true);
        return;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      // Bound the hello read: a connection that opens and sends nothing (a
      // port scanner, a load-balancer health probe) must not block the
      // accept loop and lock real ranks out of the world.
      timeval hello_timeout{/*tv_sec=*/5, /*tv_usec=*/0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &hello_timeout,
                 sizeof(hello_timeout));
      std::string hello;
      std::string reject;
      std::string advertise;
      int32_t rank = -1;
      int32_t peer_port = 0;
      bool got = RecvFrame(fd, &hello);
      if (got && hello.size() == 12) {
        // Pre-v4 builds sent a 12-byte {rank, size, version} hello: read
        // far enough to emit the SPECIFIC version-mismatch diagnostic
        // instead of the generic malformed-frame one.
        int32_t cver;
        memcpy(&rank, hello.data(), 4);
        memcpy(&cver, hello.data() + 8, 4);
        std::ostringstream o;
        o << "protocol version mismatch: coordinator speaks v"
          << kProtocolVersion << ", rank " << rank << " speaks v" << cver
          << " (pre-v4 build; mixed horovod_tpu builds in one world)";
        reject = o.str();
      } else if (!got || hello.size() < 16) {
        reject = "malformed hello frame (client/coordinator build mismatch?)";
      } else {
        int32_t csize, cver;
        memcpy(&rank, hello.data(), 4);
        memcpy(&csize, hello.data() + 4, 4);
        memcpy(&cver, hello.data() + 8, 4);
        memcpy(&peer_port, hello.data() + 12, 4);
        // Optional suffix: the rank's advertised ring data-plane address
        // (HOROVOD_RING_ADVERTISE_ADDR) for NAT/multi-homed hosts where
        // the getpeername() source IP is not reachable by ring neighbors.
        if (hello.size() > 16) advertise = hello.substr(16);
        std::ostringstream o;
        if (cver != kProtocolVersion) {
          o << "protocol version mismatch: coordinator speaks v"
            << kProtocolVersion << ", rank " << rank << " speaks v" << cver
            << " (mixed horovod_tpu builds in one world)";
          reject = o.str();
        } else if (csize != size_) {
          o << "world size mismatch: coordinator was launched with size "
            << size_ << ", but rank " << rank << " was launched with size "
            << csize << " (check HVD_SIZE / launcher -np on every host)";
          reject = o.str();
        } else if (rank < 0 || rank >= size_) {
          o << "out-of-range rank " << rank << " for world size " << size_;
          reject = o.str();
        } else if (client_fds_[rank] != -1) {
          o << "duplicate rank " << rank
            << " (two processes claim the same rank; check HVD_RANK)";
          reject = o.str();
        } else if (!advertise.empty() && !ValidAdvertiseAddr(advertise)) {
          o << "malformed ring advertise address \"" << advertise
            << "\" from rank " << rank << " (expected an IPv4 literal "
            << "\"a.b.c.d\" or \"a.b.c.d:port\" with port 1-65535; "
            << "check HOROVOD_RING_ADVERTISE_ADDR on that host)";
          reject = o.str();
        }
      }
      Buf ack;
      ack.PutU8(static_cast<uint8_t>(MsgTag::kHelloAck));
      ack.PutU8(reject.empty() ? 1 : 0);
      ack.PutStr(reject);
      SendFrame(fd, send_mu_, ack.str());
      if (!reject.empty()) {
        fprintf(stderr, "hvdcoord: rejecting client: %s\n", reject.c_str());
        ::close(fd);
        continue;
      }
      // Admitted: back to blocking reads (the tick loop polls first).
      timeval no_timeout{0, 0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &no_timeout,
                 sizeof(no_timeout));
      client_fds_[rank] = fd;
      // Record the rank's ring data-plane address: its advertised address
      // if it announced one (NAT / multi-homed hosts), else the IP this
      // connection came from + the peer-listen port from the hello.
      {
        if (peer_addrs_.empty()) peer_addrs_.assign(size_, std::string());
        std::ostringstream a;
        if (!advertise.empty()) {
          if (advertise.find(':') != std::string::npos)
            a << advertise;  // full "ip:port" override
          else
            a << advertise << ":" << peer_port;
        } else {
          sockaddr_in peer{};
          socklen_t plen = sizeof(peer);
          char ip[INET_ADDRSTRLEN] = "127.0.0.1";
          if (getpeername(fd, reinterpret_cast<sockaddr*>(&peer), &plen) ==
              0)
            inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof(ip));
          a << ip << ":" << peer_port;
        }
        peer_addrs_[rank] = a.str();
      }
      accepted++;
    }

    // Tick loop. The reference's background thread ticks every 5 ms
    // (mpi_ops.cc:1293-1295): every message that arrived within a tick is
    // drained BEFORE responses are planned, which is what lets concurrent
    // announcements (the async API's in-flight batch) fuse. Mirror that
    // with a batch window: on first arrival, keep ingesting until the
    // window expires and the sockets are drained, then plan responses.
    // This bounds per-collective latency at ~tick_ms (the reference's
    // negotiation latency floor) while letting in-flight batches coalesce.
    // One extra poll slot for the listen socket: it stays open after
    // world formation so ADMIN connections (live-resize requests / status
    // queries, MsgTag::kResizeRequest) can reach a running job. Stray
    // connections cost one bounded read and a close — they cannot wedge
    // or kill the world's coordinator.
    std::vector<pollfd> pfds(size_ + 1);
    int done_ranks = 0;
    // Liveness bookkeeping starts once the world is fully formed: any
    // frame (request, shutdown, heartbeat) from a rank refreshes its
    // last_seen; a rank silent past HVD_HEARTBEAT_TIMEOUT aborts the
    // world. done_[] marks ranks that sent a clean kShutdown — their
    // subsequent disconnect is benign, anyone else's is a worker failure.
    last_seen_.assign(size_, std::chrono::steady_clock::now());
    done_.assign(size_, false);
    while (!shutdown_.load()) {
      for (int i = 0; i < size_; i++)
        pfds[i] = {client_fds_[i], POLLIN, 0};
      pfds[size_] = {listen_fd_, POLLIN, 0};
      int n = ::poll(pfds.data(), pfds.size(), /*ms=*/5);
      if (n < 0) break;
      if (n > 0 && (pfds[size_].revents & POLLIN)) {
        HandleAdminConnection();
        n--;
      }
      if (n > 0) {
        // Quiescence batching: keep ingesting while frames keep arriving
        // within a short grace interval, capped at tick_ms total. A burst
        // of async submits (frames µs–ms apart) coalesces into one fusion
        // pass; a lone synchronous collective pays only the grace (~1 ms),
        // not the full tick — better than the reference's unconditional
        // 5 ms floor (mpi_ops.cc:1295).
        int grace_ms = tick_ms_ > 5 ? tick_ms_ / 5 : 1;
        auto deadline = std::chrono::steady_clock::now() +
                        std::chrono::milliseconds(tick_ms_);
        while (n > 0 && !shutdown_.load()) {
          for (int i = 0; i < size_; i++) {
            if (!(pfds[i].revents & POLLIN)) continue;
            std::string body;
            if (!RecvFrame(client_fds_[i], &body)) {
              if (done_[i]) {
                // Clean-shutdown rank closing its socket: benign. Forget
                // the fd so poll stops watching it.
                ::close(client_fds_[i]);
                client_fds_[i] = -1;
                continue;
              }
              // A rank died mid-run (process killed -> kernel closed its
              // socket). The reference's analog hangs every other rank
              // inside MPI forever; here the world fails fast with the
              // dead rank's identity.
              BroadcastAbort(i, "disconnected without a clean shutdown "
                                "(process crashed or was killed?)");
              serve_done_.store(true);
              return;
            }
            last_seen_[i] = std::chrono::steady_clock::now();
            Reader rd(body);
            MsgTag tag = static_cast<MsgTag>(rd.GetU8());
            if (tag == MsgTag::kHeartbeat) {
              if (!mute_acks_.load()) {
                Buf ack;
                ack.PutU8(static_cast<uint8_t>(MsgTag::kHeartbeatAck));
                // v7: every ack carries the pending-resize triple (0,0,gen
                // when none) — ranks learn of a pending resize on the
                // liveness plane they already pay for, with zero extra
                // collectives on the training hot path.
                ack.PutI32(pending_resize_target_);
                ack.PutI32(pending_resize_port_);
                ack.PutI32(resize_generation_ +
                           (pending_resize_target_ ? 1 : 0));
                SendFrame(client_fds_[i], send_mu_, ack.str());
              }
              continue;
            }
            if (tag == MsgTag::kShutdown) {
              done_[i] = true;
              if (++done_ranks == size_) {
                BroadcastShutdown();
                ResizeHandoffLinger();
                serve_done_.store(true);
                return;
              }
              continue;
            }
            Request req = DecodeRequest(rd);
            Ingest(std::move(req));
          }
          for (int i = 0; i < size_; i++)
            pfds[i] = {client_fds_[i], POLLIN, 0};
          pfds[size_] = {listen_fd_, POLLIN, 0};
          auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                          deadline - std::chrono::steady_clock::now())
                          .count();
          int wait = left > 0 ? static_cast<int>(
                                    std::min<int64_t>(left, grace_ms))
                              : 0;
          n = ::poll(pfds.data(), pfds.size(), wait);
          if (n < 0) break;
          if (n > 0 && (pfds[size_].revents & POLLIN)) {
            // Admin connection arriving mid-batch: consume it here or the
            // re-poll would spin on it until the tick deadline.
            HandleAdminConnection();
            n--;
          }
        }
      }
      DrainReady();
      CheckStalls();
      if (CheckHeartbeats()) {
        serve_done_.store(true);
        return;
      }
    }
    serve_done_.store(true);
  }

  // Clean-shutdown tail of a live resize: the world's ranks all tore
  // down to re-form, but the supervising launcher may not have fetched
  // the pending triple yet (its admin poll runs ~2x/second; a fast
  // quiesce can beat it). Keep answering admin connections briefly so
  // the handoff cannot be lost — without this, a grow's new ranks would
  // never be spawned. Bounded hard at 10 s so an unsupervised job still
  // exits.
  void ResizeHandoffLinger() {
    if (!resize_fetch_pending_.load()) return;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::seconds(10);
    while (!shutdown_.load() && resize_fetch_pending_.load() &&
           std::chrono::steady_clock::now() < deadline) {
      pollfd lp{listen_fd_, POLLIN, 0};
      int pn = ::poll(&lp, 1, 50);
      if (pn < 0 || (lp.revents & (POLLERR | POLLNVAL | POLLHUP)))
        return;
      if (pn > 0 && (lp.revents & POLLIN)) HandleAdminConnection();
    }
  }

  // IncrementTensorCount semantics (mpi_ops.cc:233-258).
  void Ingest(Request req) {
    auto& p = table_[req.name];
    if (p.requests.empty()) {
      p.announced.assign(size_, false);
      p.first_seen = std::chrono::steady_clock::now();
      arrival_order_.push_back(req.name);
      if (timeline_.enabled()) {
        // Phase 1 "NEGOTIATE_<OP>" (timeline.cc:107-140 naming).
        timeline_.NegotiateStart(req.name, ReqTypeName(req.type));
      }
    }
    if (timeline_.enabled()) {
      timeline_.NegotiateRankReady(req.name, req.rank);
    }
    if (!p.announced[req.rank]) {
      p.announced[req.rank] = true;
      p.count++;
      p.requests.push_back(std::move(req));
    }
    // Duplicate announcement from the same rank for an in-flight name is
    // dropped (Python auto-naming makes names unique per call).
  }

  // Process fully-announced tensors in strict arrival order, fusing
  // consecutive same-dtype allreduce responses within the threshold into one
  // frame — the reference's coordinator-side tensor fusion
  // (mpi_ops.cc:1395-1422: same response type, same dtype, size-capped,
  // stop at the first non-fusable response so request order is preserved).
  // The compiled data plane has its own fusion (ops/fusion.py gradient
  // bucketing); this is the host eager plane's, fed by the async API's
  // in-flight concurrency (reference: ComputeAsync kernels,
  // mpi_ops.cc:1752-1772).
  // A fully-announced allgather may mix ALLGATHER (payload shipped) and
  // ALLGATHER_RING (payload held back) when per-rank block sizes straddle
  // HOROVOD_RING_THRESHOLD — a legitimate ragged input, not config skew.
  // Resolve by demoting: tell the ring announcers to resubmit as star,
  // un-count them, and keep the op pending until the payloads arrive.
  bool DemoteMixedGatherRing(const std::string& name, PendingTensor* p) {
    bool star = false, ring = false;
    for (auto& r : p->requests) {
      star = star || r.type == ReqType::kAllgather;
      ring = ring || r.type == ReqType::kAllgatherRing;
    }
    if (!star || !ring) return false;
    Response resp;
    resp.type = RespType::kResubmitStar;
    resp.name = name;
    std::string body = EncodeResponse(resp);
    for (auto it = p->requests.begin(); it != p->requests.end();) {
      if (it->type == ReqType::kAllgatherRing) {
        SendFrame(client_fds_[it->rank], send_mu_, body);
        p->announced[it->rank] = false;
        p->count--;
        it = p->requests.erase(it);
      } else {
        ++it;
      }
    }
    return true;
  }

  void DrainReady() {
    std::vector<std::string> ready;
    for (auto it = arrival_order_.begin(); it != arrival_order_.end();) {
      auto t = table_.find(*it);
      if (t != table_.end() && t->second.count == size_) {
        if (DemoteMixedGatherRing(*it, &t->second)) {
          ++it;  // stays pending until the star resubmissions land
          continue;
        }
        ready.push_back(*it);
        it = arrival_order_.erase(it);
      } else {
        ++it;
      }
    }
    std::vector<Response> resps;
    resps.reserve(ready.size());
    for (auto& name : ready) resps.push_back(BuildResponse(name));

    size_t i = 0;
    while (i < resps.size()) {
      if (!Fusable(resps[i]) || fusion_threshold_ <= 0) {
        Emit(resps[i]);
        i++;
        continue;
      }
      // Extend the fusion group while the next response is fusable with the
      // head (same dtype, cumulative bytes under the threshold).
      size_t j = i + 1;
      int64_t total = static_cast<int64_t>(resps[i].payload.size());
      while (j < resps.size() && Fusable(resps[j]) &&
             resps[j].dtype == resps[i].dtype &&
             total + static_cast<int64_t>(resps[j].payload.size()) <=
                 fusion_threshold_) {
        total += static_cast<int64_t>(resps[j].payload.size());
        j++;
      }
      if (j - i == 1) {
        Emit(resps[i]);
      } else {
        EmitFused(resps, i, j);
      }
      i = j;
    }
  }

  static bool Fusable(const Response& r) {
    return r.type == RespType::kAllreduce && r.per_rank_payloads.empty() &&
           !r.payload.empty();
  }

  // ConstructMPIResponse parity (mpi_ops.cc:266-474): cross-rank validation
  // with the reference's error taxonomy, then host execution.
  Response BuildResponse(const std::string& name) {
    auto it = table_.find(name);
    auto requests = std::move(it->second.requests);
    table_.erase(it);

    Response resp;
    resp.name = name;
    std::ostringstream err;

    if (timeline_.enabled()) {
      // Close phase 1 with the first-arrived request's op (the name the
      // NEGOTIATE_* begin event used); the top-level processing event opens
      // below once validation passes (timeline.cc:142-166 Start).
      timeline_.NegotiateEnd(name, ReqTypeName(requests.front().type));
    }

    // Order requests by rank for deterministic gather concat.
    std::sort(requests.begin(), requests.end(),
              [](const Request& a, const Request& b) { return a.rank < b.rank; });

    // Unknown type bytes (a direct/nonconforming client; conforming mixed
    // builds are already rejected at hello by the version check) must
    // produce a named error, never reach the op switches below.
    for (auto& r : requests) {
      if (!KnownReqType(r.type)) {
        err << "Unknown collective operation type "
            << static_cast<int>(r.type) << " announced by rank " << r.rank
            << " (nonconforming client).";
        resp.type = RespType::kError;
        resp.error = err.str();
        return resp;
      }
    }

    DType dtype = requests[0].dtype;
    resp.dtype = dtype;
    for (auto& r : requests) {
      if (r.dtype != dtype) {
        err << "Mismatched data types: One rank had type " << DTypeName(dtype)
            << ", but another rank had type " << DTypeName(r.dtype) << ".";
        resp.type = RespType::kError;
        resp.error = err.str();
        return resp;
      }
    }
    // Broadcast family: only the ROOT ships payload, so only its
    // election decides star vs ring; non-roots always announce plain
    // BROADCAST. Normalize before the mismatch check (a ring
    // announcement from a NON-root is left un-normalized and caught as
    // a genuine mismatch below).
    bool bcast_ring = false;
    {
      bool family = true;
      for (auto& r : requests)
        family = family && (r.type == ReqType::kBroadcast ||
                            r.type == ReqType::kBroadcastRing);
      if (family) {
        bool roots_only = true;
        bool any_ring = false;
        for (auto& r : requests)
          if (r.type == ReqType::kBroadcastRing) {
            any_ring = true;
            roots_only = roots_only && r.rank == r.root_rank;
          }
        if (any_ring && roots_only) {
          bcast_ring = true;
          for (auto& r : requests) r.type = ReqType::kBroadcast;
        }
      }
    }

    ReqType op = requests[0].type;
    for (auto& r : requests) {
      if (r.type != op) {
        err << "Mismatched collective operations: One rank did an "
            << ReqTypeName(op) << ", but another rank did an "
            << ReqTypeName(r.type) << ".";
        resp.type = RespType::kError;
        resp.error = err.str();
        return resp;
      }
    }

    // A kBroadcastRing that survived normalization means every announcer
    // sent it from a NON-root rank (only possible with a nonconforming or
    // direct client — conforming non-roots always announce plain
    // BROADCAST). It must not skip root validation below.
    if (op == ReqType::kBroadcastRing) {
      err << "BROADCAST_RING announced by a non-root rank (only the "
          << "broadcast root elects the ring plane; nonconforming client).";
      resp.type = RespType::kError;
      resp.error = err.str();
      return resp;
    }

    if (op == ReqType::kAllreduce || op == ReqType::kReducescatter ||
        op == ReqType::kAllreduceRing ||
        op == ReqType::kReducescatterRing) {
      RedOp rop = requests[0].red_op;
      for (auto& r : requests) {
        if (r.red_op != rop) {
          err << "Mismatched reduction ops: One rank requested "
              << RedOpName(rop) << ", but another rank requested "
              << RedOpName(r.red_op) << ".";
          resp.type = RespType::kError;
          resp.error = err.str();
          return resp;
        }
      }
    }

    if (op == ReqType::kAllreduce || op == ReqType::kBroadcast ||
        op == ReqType::kAlltoall || op == ReqType::kReducescatter ||
        op == ReqType::kAllreduceRing || op == ReqType::kAlltoallRing ||
        op == ReqType::kReducescatterRing) {
      const auto& shape = requests[0].shape;
      for (auto& r : requests) {
        if (r.shape != shape) {
          err << "Mismatched " << ReqTypeName(op)
              << " tensor shapes: One rank sent a tensor of shape "
              << ShapeStr(shape)
              << ", but another rank sent a tensor of shape "
              << ShapeStr(r.shape) << ".";
          resp.type = RespType::kError;
          resp.error = err.str();
          return resp;
        }
      }
    }

    if (op == ReqType::kAllgather || op == ReqType::kAllgatherRing) {
      const auto& shape0 = requests[0].shape;
      if (shape0.empty()) {
        err << "Rank zero tried to ALLGATHER a rank-zero tensor.";
        resp.type = RespType::kError;
        resp.error = err.str();
        return resp;
      }
      resp.sizes.assign(size_, 0);
      for (auto& r : requests) {
        if (r.shape.size() != shape0.size()) {
          err << "Mismatched ALLGATHER tensor shapes: One rank sent a tensor "
              << "of rank " << shape0.size()
              << ", but another rank sent a tensor of rank "
              << r.shape.size() << ".";
          resp.type = RespType::kError;
          resp.error = err.str();
          return resp;
        }
        for (size_t d = 1; d < shape0.size(); d++) {
          if (r.shape[d] != shape0[d]) {
            err << "Mismatched ALLGATHER tensor shapes: One rank sent a "
                << "tensor with dimension " << d << " equal to " << shape0[d]
                << ", but another rank sent a tensor with dimension " << d
                << " equal to " << r.shape[d] << ".";
            resp.type = RespType::kError;
            resp.error = err.str();
            return resp;
          }
        }
        resp.sizes[r.rank] = r.shape[0];
      }
    }

    if (op == ReqType::kBroadcast) {
      int root = requests[0].root_rank;
      if (root < 0 || root >= size_) {
        // Out-of-range root is rejected here too (the public Python API
        // range-checks, but a direct client call must not index out of
        // bounds; reference root validation: ConstructMPIResponse region
        // mpi_ops.cc:408-435).
        err << "Invalid BROADCAST root rank " << root << ": world size is "
            << size_ << ".";
        resp.type = RespType::kError;
        resp.error = err.str();
        return resp;
      }
      for (auto& r : requests) {
        if (r.root_rank != root) {
          err << "Mismatched BROADCAST root ranks: One rank specified root "
              << "rank " << root << ", but another rank specified root rank "
              << r.root_rank << ".";
          resp.type = RespType::kError;
          resp.error = err.str();
          return resp;
        }
      }
    }

    // Payload byte counts must match the announced shapes: the host
    // executors trust the shapes (the striped reduce indexes every rank's
    // payload by the ACCUMULATOR's extent; concat trusts per-rank dim-0),
    // so a nonconforming client shipping a short payload would otherwise
    // cause an out-of-bounds read that can kill the coordinator — the
    // same threat class as the unknown-type and non-root-ring checks.
    // Conforming clients always match; ring announcements ship no bytes.
    if (op == ReqType::kAllreduce || op == ReqType::kAllgather ||
        op == ReqType::kAlltoall || op == ReqType::kReducescatter ||
        op == ReqType::kBroadcast) {
      for (auto& r : requests) {
        int64_t elems = 1;
        for (int64_t d : r.shape) elems *= d;
        size_t want = static_cast<size_t>(elems) *
                      static_cast<size_t>(DTypeSize(r.dtype));
        if (op == ReqType::kBroadcast) {
          // Only the root ships payload; a ring-elected root stashed its
          // bytes client-side, so its announcement is empty too.
          want = (r.rank == requests[0].root_rank && !bcast_ring) ? want : 0;
        }
        if (r.payload.size() != want) {
          err << "Mismatched payload size: rank " << r.rank
              << " announced shape " << ShapeStr(r.shape) << " ("
              << want << " bytes of " << DTypeName(r.dtype)
              << ") but shipped " << r.payload.size()
              << " bytes (nonconforming client).";
          resp.type = RespType::kError;
          resp.error = err.str();
          return resp;
        }
      }
    }

    if (op == ReqType::kAlltoall || op == ReqType::kReducescatter ||
        op == ReqType::kAlltoallRing || op == ReqType::kReducescatterRing) {
      const auto& shape0 = requests[0].shape;
      if (shape0.empty() || shape0[0] % size_ != 0) {
        err << ReqTypeName(op) << " requires a first dimension divisible by "
            << "the world size " << size_ << ", got shape "
            << ShapeStr(shape0) << ".";
        resp.type = RespType::kError;
        resp.error = err.str();
        return resp;
      }
    }

    // Execute the host data plane. The top-level processing event wraps a
    // named activity per op (reference nested activities,
    // mpi_ops.cc:623-635 / docs/timeline.md:25-43; MPI_ALLREDUCE et al.
    // become host-plane SUM/CONCAT/BCAST/ALLTOALL/REDUCESCATTER).
    const char* act = nullptr;
    switch (op) {
      case ReqType::kAllreduce: act = "SUM"; break;
      case ReqType::kAllgather: act = "CONCAT"; break;
      case ReqType::kBroadcast: act = "BCAST"; break;
      case ReqType::kAlltoall: act = "ALLTOALL"; break;
      case ReqType::kReducescatter: act = "REDUCESCATTER"; break;
      case ReqType::kAllreduceRing: act = "RING_PLAN"; break;
      case ReqType::kAllgatherRing: act = "RING_PLAN"; break;
      case ReqType::kBroadcastRing: act = "RING_PLAN"; break;
      case ReqType::kAlltoallRing: act = "RING_PLAN"; break;
      case ReqType::kReducescatterRing: act = "RING_PLAN"; break;
    }
    if (timeline_.enabled()) {
      timeline_.Start(resp.name, ReqTypeName(op));  // top-level Start
      timeline_.ActivityStart(resp.name, act);
    }
    switch (op) {
      case ReqType::kAllreduce: {
        resp.type = RespType::kAllreduce;
        resp.shape = requests[0].shape;
        resp.payload = requests[0].payload;
        ReduceAllStriped(dtype, requests[0].red_op, &resp.payload, requests);
        break;
      }
      case ReqType::kAllgather: {
        resp.type = RespType::kAllgather;
        resp.shape = requests[0].shape;
        resp.shape[0] = 0;
        for (auto& r : requests) {
          resp.payload += r.payload;  // rank order
          resp.shape[0] += r.shape[0];
        }
        break;
      }
      case ReqType::kBroadcast: {
        if (bcast_ring) {
          // Chain plan: no payload through the coordinator; sizes[0]
          // carries the root for the clients' chain orientation.
          resp.type = RespType::kBroadcastRing;
          resp.shape = requests[0].shape;
          resp.sizes = {requests[0].root_rank};
          resp.ring_peers = peer_addrs_;
          break;
        }
        resp.type = RespType::kBroadcast;
        resp.shape = requests[0].shape;
        resp.payload = requests[requests[0].root_rank].payload;
        break;
      }
      case ReqType::kAlltoall: {
        // Rank r's result = concat over senders s of block r of s's tensor
        // (lax.all_to_all split_axis=0, concat_axis=0 semantics).
        resp.type = RespType::kAlltoall;
        resp.shape = requests[0].shape;
        size_t block = requests[0].payload.size() / size_;
        resp.per_rank_payloads.assign(size_, std::string());
        for (int r = 0; r < size_; r++) {
          resp.per_rank_payloads[r].reserve(block * size_);
          for (int s = 0; s < size_; s++)
            resp.per_rank_payloads[r] +=
                requests[s].payload.substr(r * block, block);
        }
        break;
      }
      case ReqType::kAllreduceRing: {
        // No host execution: ship the ring plan; clients move the data
        // among themselves (reduce-scatter + allgather over the rank ring).
        resp.type = RespType::kAllreduceRing;
        resp.shape = requests[0].shape;
        resp.ring_peers = peer_addrs_;
        break;
      }
      case ReqType::kAllgatherRing: {
        // resp.sizes (per-rank first dims) was filled by the allgather
        // validation above; clients circulate their blocks themselves.
        resp.type = RespType::kAllgatherRing;
        resp.shape = requests[0].shape;
        resp.ring_peers = peer_addrs_;
        break;
      }
      case ReqType::kAlltoallRing: {
        // Mesh plan: clients exchange blocks pairwise among themselves.
        resp.type = RespType::kAlltoallRing;
        resp.shape = requests[0].shape;
        resp.ring_peers = peer_addrs_;
        break;
      }
      case ReqType::kReducescatterRing: {
        // Ring plan: clients run the reduce-scatter phase themselves.
        resp.type = RespType::kReducescatterRing;
        resp.shape = requests[0].shape;
        resp.ring_peers = peer_addrs_;
        break;
      }
      case ReqType::kBroadcastRing:
        break;  // unreachable: rejected above (non-root BROADCAST_RING)
      case ReqType::kReducescatter: {
        // Sum all tensors, rank r receives block r of the first dimension
        // (lax.psum_scatter tiled semantics).
        resp.type = RespType::kReducescatter;
        resp.shape = requests[0].shape;
        resp.shape[0] /= size_;
        std::string sum = requests[0].payload;
        ReduceAllStriped(dtype, requests[0].red_op, &sum, requests);
        size_t block = sum.size() / size_;
        resp.per_rank_payloads.assign(size_, std::string());
        for (int r = 0; r < size_; r++)
          resp.per_rank_payloads[r] = sum.substr(r * block, block);
        break;
      }
    }
    if (timeline_.enabled()) timeline_.ActivityEnd(resp.name, act);
    return resp;
  }

  // End-event args: output dtype + shape (timeline.cc:203-220 parity).
  static std::string TimelineArgs(const Response& r) {
    std::ostringstream o;
    o << "{\"dtype\":\"" << DTypeName(r.dtype) << "\",\"shape\":"
      << ShapeStr(r.shape) << "}";
    return o.str();
  }

  void Emit(Response& resp) {
    if (resp.type == RespType::kError) {
      // Validation failed before the top-level event opened; the ERROR
      // send is its own top-level pair.
      if (timeline_.enabled()) timeline_.Start(resp.name, "ERROR");
      std::string body = EncodeResponse(resp);
      for (int r = 0; r < size_; r++)
        SendFrame(client_fds_[r], send_mu_, body);
      if (timeline_.enabled()) timeline_.End(resp.name);
      return;
    }
    if (timeline_.enabled()) timeline_.ActivityStart(resp.name, "RESPOND");
    if (resp.per_rank_payloads.empty()) {
      std::string body = EncodeResponse(resp);
      for (int r = 0; r < size_; r++)
        SendFrame(client_fds_[r], send_mu_, body);
    } else {
      // alltoall/reducescatter: each rank receives its own result slice.
      for (int r = 0; r < size_; r++) {
        resp.payload = resp.per_rank_payloads[r];
        SendFrame(client_fds_[r], send_mu_, EncodeResponse(resp));
      }
    }
    if (timeline_.enabled()) {
      timeline_.ActivityEnd(resp.name, "RESPOND");
      timeline_.End(resp.name, TimelineArgs(resp));  // top-level
    }
  }

  // Fused emission: one frame answering resps[lo, hi) at once
  // (mpi_ops.cc:1395-1422 response batching; tensor_names[] >1 ⇒ fused).
  void EmitFused(std::vector<Response>& resps, size_t lo, size_t hi) {
    Response out;
    out.type = RespType::kAllreduce;
    out.name = resps[lo].name;
    for (size_t k = lo; k < hi; k++) {
      out.fused_names.push_back(resps[k].name);
      out.fused_nbytes.push_back(
          static_cast<int64_t>(resps[k].payload.size()));
      out.payload += resps[k].payload;
      if (timeline_.enabled())
        timeline_.ActivityStart(resps[k].name, "RESPOND");
    }
    std::string body = EncodeResponse(out);
    for (int r = 0; r < size_; r++) SendFrame(client_fds_[r], send_mu_, body);
    if (timeline_.enabled()) {
      for (size_t k = lo; k < hi; k++) {
        timeline_.ActivityEnd(resps[k].name, "RESPOND");
        timeline_.End(resps[k].name, TimelineArgs(resps[k]));
      }
    }
  }

  void BroadcastShutdown() {
    Response resp;
    resp.type = RespType::kShutdown;
    resp.name = "__shutdown__";
    std::string body = EncodeResponse(resp);
    for (int r = 0; r < size_; r++)
      if (client_fds_[r] >= 0) SendFrame(client_fds_[r], send_mu_, body);
  }

  // Declare the world dead because of `dead_rank`: every surviving rank's
  // blocked hvdcoord_wait fails fast with the dead rank's identity
  // (-> WorkerFailureError) instead of hanging on collectives that can
  // never complete. Sent to the dead rank too when its socket is still up
  // (alive-but-silent ranks deserve the diagnosis as much as survivors).
  void BroadcastAbort(int dead_rank, const std::string& why) {
    Response resp;
    resp.type = RespType::kAbort;
    resp.name = "__abort__";
    std::ostringstream o;
    o << "worker failure: rank " << dead_rank << " " << why
      << "; aborting the world — in-flight and future collectives on "
      << "every rank fail with this error";
    resp.error = o.str();
    fprintf(stderr, "hvdcoord: %s\n", resp.error.c_str());
    std::string body = EncodeResponse(resp);
    for (int r = 0; r < size_; r++)
      if (client_fds_[r] >= 0) SendFrame(client_fds_[r], send_mu_, body);
  }

  // -- admin plane (v7): live-resize ingress -------------------------------
  // One bounded request/reply exchange per connection, handled inline on
  // the serve thread: accept, read ONE frame under a short timeout, reply,
  // close. A resize request records the pending target and pushes a
  // kResizeNotice to every rank; ranks quiesce at their next step boundary
  // (horovod_tpu.elastic.ResizeCoordinator) — the coordinator itself never
  // interrupts in-flight collectives.

  // Reserve a port for the NEW world's coordinator: bind an ephemeral
  // socket, record its port, close it. The standard free-port probe (same
  // race tolerance as the launcher's): the port is handed to every rank in
  // the notice, and the re-formed rank 0 binds it within the connect
  // budget of the others.
  static int32_t ProbeFreePort() {
    int s = ::socket(AF_INET, SOCK_STREAM, 0);
    if (s < 0) return 0;
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_ANY);
    a.sin_port = 0;
    int32_t port = 0;
    socklen_t alen = sizeof(a);
    if (::bind(s, reinterpret_cast<sockaddr*>(&a), sizeof(a)) == 0 &&
        getsockname(s, reinterpret_cast<sockaddr*>(&a), &alen) == 0)
      port = ntohs(a.sin_port);
    ::close(s);
    return port;
  }

  void BroadcastResizeNotice() {
    Response resp;
    resp.type = RespType::kResizeNotice;
    resp.name = "__resize__";
    resp.sizes = {pending_resize_target_, pending_resize_port_,
                  resize_generation_ + 1};
    std::string body = EncodeResponse(resp);
    for (int r = 0; r < size_; r++)
      if (client_fds_[r] >= 0 && !done_.empty() && !done_[r])
        SendFrame(client_fds_[r], send_mu_, body);
  }

  // Admin requests are a few bytes; anything bigger is not ours. The cap
  // keeps a hostile length prefix from allocating kMaxFrameBytes on the
  // training host (RecvFrame's general bound exists for tensor payloads).
  static constexpr uint64_t kMaxAdminFrameBytes = 4096;

  // Bounded-WALL-CLOCK read: SO_RCVTIMEO only bounds each recv, so a
  // drip-feeding client (1 byte/second) could otherwise park the serve
  // thread for minutes and starve heartbeat acks into a world abort.
  static bool RecvAllDeadline(int fd, void* p, size_t n,
                              std::chrono::steady_clock::time_point dl) {
    size_t off = 0;
    while (off < n) {
      if (std::chrono::steady_clock::now() >= dl) return false;
      ssize_t r = ::recv(fd, reinterpret_cast<char*>(p) + off, n - off, 0);
      if (r <= 0) return false;  // EOF, error, or SO_RCVTIMEO tick
      off += static_cast<size_t>(r);
    }
    return true;
  }

  static bool RecvAdminFrame(int fd, std::string* body) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::seconds(2);
    uint64_t len;
    if (!RecvAllDeadline(fd, &len, 8, deadline)) return false;
    if (len > kMaxAdminFrameBytes) return false;
    body->resize(len);
    return len == 0 || RecvAllDeadline(fd, &(*body)[0], len, deadline);
  }

  void HandleAdminConnection() {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    // Handled inline on the serve thread: keep the stall window a
    // connection can inflict small (a held-open probe costs one second,
    // not five — this port shares the hello port's trusted-cluster
    // model, but a stray health checker must not starve heartbeat acks
    // into an HVD_HEARTBEAT_TIMEOUT abort).
    timeval admin_timeout{/*tv_sec=*/1, /*tv_usec=*/0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &admin_timeout,
               sizeof(admin_timeout));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &admin_timeout,
               sizeof(admin_timeout));
    std::string body;
    bool ok = false;
    bool accepted_now = false;
    bool supervisor_fetch = false;
    std::string msg;
    if (!RecvAdminFrame(fd, &body) || body.size() < 5 ||
        static_cast<MsgTag>(body[0]) != MsgTag::kResizeRequest) {
      // Port scanner / health probe / mixed-build admin: close without
      // reply beyond the error frame; the world is unaffected.
      msg = "malformed admin frame (expected kResizeRequest)";
    } else {
      Reader rd(body);
      rd.GetU8();  // tag
      int32_t target = rd.GetI32();
      // target 0 = anyone's status query; -1 = the SUPERVISING launcher's
      // status poll — only the latter releases the teardown-handoff
      // linger (a third-party operator's query must not consume the
      // launcher's one chance to learn the grow spawns).
      if (target == 0 || target == -1) {
        ok = true;
        supervisor_fetch = target == -1;
      } else if (target < 0) {
        std::ostringstream o;
        o << "invalid resize target " << target;
        msg = o.str();
      } else if (target == 1 && size_ > 1) {
        msg = "resizing a multi-process world to a single rank is not "
              "supported (the coordination plane needs >= 2 ranks); "
              "relaunch with -np 1 instead (the canonical checkpoint "
              "form restores at any world size)";
      } else if (target == size_ && pending_resize_target_ == 0) {
        std::ostringstream o;
        o << "world is already size " << size_ << "; nothing to resize";
        msg = o.str();
      } else if (pending_resize_target_ != 0) {
        if (target == pending_resize_target_) {
          ok = true;  // idempotent re-request of the same resize
        } else {
          std::ostringstream o;
          o << "resize to " << pending_resize_target_
            << " already pending (generation " << resize_generation_ + 1
            << "); the world must quiesce and re-form before another "
            << "resize can be requested";
          msg = o.str();
        }
      } else {
        int32_t port = ProbeFreePort();
        if (port == 0) {
          msg = "could not reserve a coordinator port for the new world";
        } else {
          pending_resize_target_ = target;
          pending_resize_port_ = port;
          ok = true;
          accepted_now = true;
          // The supervising launcher must see this pending resize at
          // least once (its status poll, or a later idempotent
          // re-request) before the old plane may die — see
          // ResizeHandoffLinger.
          resize_fetch_pending_.store(true);
          fprintf(stderr,
                  "hvdcoord: live resize requested: world %d -> %d "
                  "(generation %d, new coordinator port %d); notifying "
                  "ranks — they quiesce at their next step boundary\n",
                  size_, target, resize_generation_ + 1, port);
          BroadcastResizeNotice();
        }
      }
    }
    Buf reply;
    reply.PutU8(static_cast<uint8_t>(MsgTag::kResizeReply));
    reply.PutU8(ok ? 1 : 0);
    reply.PutStr(msg);
    reply.PutI32(size_);
    reply.PutI32(pending_resize_target_);
    reply.PutI32(pending_resize_port_);
    reply.PutI32(resize_generation_ + (pending_resize_target_ ? 1 : 0));
    bool sent = SendFrame(fd, send_mu_, reply.str());
    ::close(fd);
    // Only the SUPERVISOR's status poll (target = -1) releases the
    // teardown linger: it is the party that must learn the triple to
    // spawn grow ranks. Operator queries and the accepting request pass
    // through without consuming the handoff.
    if (sent && ok && pending_resize_target_ && !accepted_now
        && supervisor_fetch)
      resize_fetch_pending_.store(false);
  }

  // Liveness sweep: a rank (not cleanly shut down) whose last frame is
  // older than HVD_HEARTBEAT_TIMEOUT is dead or wedged — abort. Returns
  // true when the world was aborted (the serve loop must exit).
  bool CheckHeartbeats() {
    if (heartbeat_timeout_ <= 0) return false;
    auto now = std::chrono::steady_clock::now();
    for (int i = 0; i < size_; i++) {
      if (done_[i] || client_fds_[i] < 0) continue;
      double silent =
          std::chrono::duration<double>(now - last_seen_[i]).count();
      if (silent > heartbeat_timeout_) {
        std::ostringstream o;
        o << "went silent (no heartbeat for " << silent
          << " s > HVD_HEARTBEAT_TIMEOUT=" << heartbeat_timeout_
          << " s; process wedged or network partitioned?)";
        BroadcastAbort(i, o.str());
        return true;
      }
    }
    return false;
  }

  // CheckForStalledTensors parity (mpi_ops.cc:1153-1196): warn on stderr for
  // tensors waiting > stall_secs with only a subset of ranks ready.
  void CheckStalls() {
    auto now = std::chrono::steady_clock::now();
    if (std::chrono::duration<double>(now - last_stall_check_).count() <
        stall_secs_)
      return;
    last_stall_check_ = now;
    bool preamble = false;
    for (auto& name : arrival_order_) {
      auto it = table_.find(name);
      if (it == table_.end()) continue;
      double waited =
          std::chrono::duration<double>(now - it->second.first_seen).count();
      if (waited > stall_secs_) {
        if (!preamble) {
          fprintf(stderr,
                  "WARNING: One or more tensors were submitted to be reduced, "
                  "gathered or broadcasted by subset of ranks and are waiting "
                  "for remainder of ranks for more than %.0f seconds. This may "
                  "indicate that different ranks are trying to submit "
                  "different tensors or that only subset of ranks is "
                  "submitting tensors, which will cause deadlock.\n",
                  stall_secs_);
          fprintf(stderr, "Stalled ops:");
          preamble = true;
        }
        fprintf(stderr, "\n%s [ready ranks:", name.c_str());
        for (int r = 0; r < size_; r++)
          if (it->second.announced[r]) fprintf(stderr, " %d", r);
        fprintf(stderr, "]");
      }
    }
    if (preamble) fprintf(stderr, "\n");
  }

  static std::string ShapeStr(const std::vector<int64_t>& s) {
    std::ostringstream o;
    o << "[";
    for (size_t i = 0; i < s.size(); i++) o << (i ? "," : "") << s[i];
    o << "]";
    return o.str();
  }

  int size_;
  int port_;
  int64_t fusion_threshold_;
  double stall_secs_;
  int tick_ms_ = 5;
  double heartbeat_timeout_ = 30.0;
  // Pending live resize (admin plane, v7). Written and read on the serve
  // thread only (admin connections are handled inline in the tick loop);
  // the fetch/serve-done flags are additionally read by the destructor
  // (teardown-linger handoff) and are atomic.
  int32_t pending_resize_target_ = 0;  // 0 = none
  int32_t pending_resize_port_ = 0;    // coordinator port for the NEW world
  int32_t resize_generation_ = 0;
  std::atomic<bool> resize_fetch_pending_{false};
  std::atomic<bool> serve_done_{false};
  std::atomic<bool> mute_acks_{false};
  std::vector<std::chrono::steady_clock::time_point> last_seen_;
  std::vector<bool> done_;
  bool ok_ = true;
  int listen_fd_ = -1;
  std::vector<int> client_fds_;
  std::thread thread_;
  std::atomic<bool> shutdown_{false};
  std::mutex send_mu_;
  Timeline timeline_;

  std::unordered_map<std::string, PendingTensor> table_;  // MessageTable
  std::vector<std::string> peer_addrs_;  // rank -> "ip:port" ring data plane
  std::vector<std::string> arrival_order_;
  std::chrono::steady_clock::time_point last_stall_check_ =
      std::chrono::steady_clock::now();
};

// ---------------------------------------------------------------------------
// Client (every rank, incl. 0): sends requests, receiver thread completes ops.
// ---------------------------------------------------------------------------

class Client {
 public:
  Client(int rank, int size, const std::string& host, int port)
      : rank_(rank), size_(size) {
    // Ring data-plane threshold (bytes): collectives at or above it skip
    // the star and move data client-to-client. 0 disables. Must agree
    // across ranks (skew produces a self-explaining ALLREDUCE vs
    // ALLREDUCE_RING mismatch error at negotiation).
    ring_threshold_ = ParseEnvI64("HOROVOD_RING_THRESHOLD", 4 << 20);
    if (ring_threshold_ < 0) ring_threshold_ = 0;
    if (rank_ == 0 && getenv("HOROVOD_RING_THRESHOLD"))
      fprintf(stderr, "hvdcoord: ring threshold resolved to %lld bytes\n",
              static_cast<long long>(ring_threshold_));
    // Strict stall mode: Wait() fails with a StalledError after this many
    // seconds (0 = off; the reference only warns, mpi_ops.cc:1153-1196).
    stall_timeout_secs_ = ParseEnvF64("HOROVOD_STALL_TIMEOUT", 0.0);
    if (stall_timeout_secs_ < 0) stall_timeout_secs_ = 0;
    // Ring data-plane IO bound (seconds): peer connect/accept and every
    // per-chunk send/recv must finish within it, so a rank dying mid-ring
    // degrades to a TransportError on the survivors instead of an
    // unbounded block on a silent socket.
    ring_io_secs_ =
        static_cast<int>(ParseEnvI64("HOROVOD_RING_IO_TIMEOUT", 30));
    if (ring_io_secs_ < 1) ring_io_secs_ = 1;
    // Liveness deadline, symmetric with the coordinator's: this client
    // beats every ~timeout/4 and expects acks; no ack for a full timeout
    // means the coordinator is dead or wedged -> abort locally (0 = off).
    heartbeat_timeout_ = ParseEnvF64("HVD_HEARTBEAT_TIMEOUT", 30.0);
    if (heartbeat_timeout_ < 0) heartbeat_timeout_ = 0;
    peer_fds_.assign(size_, -1);
    // Peer-listen socket for the ring data plane (ephemeral port, announced
    // in the hello; the left ring neighbor connects here).
    peer_listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (peer_listen_fd_ >= 0) {
      int pone = 1;
      setsockopt(peer_listen_fd_, SOL_SOCKET, SO_REUSEADDR, &pone,
                 sizeof(pone));
      sockaddr_in paddr{};
      paddr.sin_family = AF_INET;
      paddr.sin_addr.s_addr = htonl(INADDR_ANY);
      paddr.sin_port = 0;
      if (bind(peer_listen_fd_, reinterpret_cast<sockaddr*>(&paddr),
               sizeof(paddr)) == 0 &&
          listen(peer_listen_fd_, size) == 0) {  // mesh: several peers connect at once
        socklen_t alen = sizeof(paddr);
        if (getsockname(peer_listen_fd_,
                        reinterpret_cast<sockaddr*>(&paddr), &alen) == 0)
          peer_port_ = ntohs(paddr.sin_port);
      }
      if (peer_port_ == 0) {
        ::close(peer_listen_fd_);
        peer_listen_fd_ = -1;
      }
    }
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
    // Retry connect under a wall-clock budget with bounded exponential
    // backoff: the coordinator may not be up yet (launcher spawns ranks
    // concurrently; a restarted world reopens on a fresh port). The old
    // fixed 50 ms x 600 schedule hammered the host during long restarts
    // and gave no knob for slow multi-host bring-up.
    double connect_budget = ParseEnvF64("HVD_COORD_CONNECT_TIMEOUT", 30.0);
    if (connect_budget < 0) connect_budget = 0;
    auto cdeadline = std::chrono::steady_clock::now() +
                     std::chrono::duration<double>(connect_budget);
    int backoff_ms = 10;
    for (;;) {
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr),
                    sizeof(addr)) == 0) {
        connected_ = true;
        break;
      }
      if (std::chrono::steady_clock::now() >= cdeadline) break;
      std::this_thread::sleep_for(std::chrono::milliseconds(backoff_ms));
      backoff_ms = std::min(backoff_ms * 2, 1000);
      ::close(fd_);
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    }
    if (!connected_) {
      std::ostringstream o;
      o << "could not connect to coordinator at " << host << ":" << port
        << " within HVD_COORD_CONNECT_TIMEOUT=" << connect_budget
        << " s (coordinator not started, wrong HVD_COORD_ADDR, or rank 0 "
        << "crashed during bring-up?)";
      init_error_ = o.str();
      return;
    }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    int32_t ver = kProtocolVersion;
    int32_t pport = peer_port_;
    std::string hello;
    hello.append(reinterpret_cast<char*>(&rank_), 4);
    hello.append(reinterpret_cast<char*>(&size_), 4);
    hello.append(reinterpret_cast<char*>(&ver), 4);
    hello.append(reinterpret_cast<char*>(&pport), 4);
    // Optional suffix: explicit ring data-plane address for NAT or
    // multi-homed hosts where the coordinator's getpeername() view of us
    // is not reachable by our ring neighbors ("ip" or "ip:port").
    // Validate the IPv4 literal HERE (same loud-rejection standard as
    // ParseEnvI64): a hostname or typo would otherwise zero out
    // inet_pton in every peer's connector and surface 30 s later as a
    // generic TransportError pointing nowhere.
    if (const char* adv = getenv("HOROVOD_RING_ADVERTISE_ADDR")) {
      std::string a(adv);
      // Shared with the coordinator's hello-side re-validation
      // (ValidAdvertiseAddr): both ends must agree on what is
      // well-formed, or a value one side accepts gets rejected (or
      // distributed) by the other. The port must parse fully and fit
      // uint16, or the peers' connectors would atoi a prefix and burn
      // the full IO timeout connecting to the wrong port.
      if (!ValidAdvertiseAddr(a)) {
        fprintf(stderr,
                "hvdcoord: ignoring malformed HOROVOD_RING_ADVERTISE_ADDR"
                "=\"%s\" (expected an IPv4 literal \"a.b.c.d\" or "
                "\"a.b.c.d:port\" with port 1-65535; hostnames are not "
                "resolved) — falling back to the getpeername-derived "
                "address\n",
                adv);
      } else {
        hello.append(a);
      }
    }
    SendFrame(fd_, send_mu_, hello);
    // Synchronous ack: the coordinator validates {rank, size, version}
    // before admitting us — misconfigured worlds fail HERE with a message,
    // not minutes later with a hang.
    std::string ackbody;
    if (!RecvFrame(fd_, &ackbody) || ackbody.empty() ||
        static_cast<MsgTag>(ackbody[0]) != MsgTag::kHelloAck) {
      init_error_ = "coordinator closed the connection during handshake";
      connected_ = false;
      return;
    }
    Reader rd(ackbody);
    rd.GetU8();  // tag
    bool ok = rd.GetU8() != 0;
    std::string msg = rd.GetStr();
    if (!ok) {
      init_error_ = msg;
      connected_ = false;
      return;
    }
    recv_thread_ = std::thread(&Client::RecvLoop, this);
    if (heartbeat_timeout_ > 0) {
      last_ack_ms_.store(NowMs());
      hb_thread_ = std::thread(&Client::HeartbeatLoop, this);
    }
  }

 public:
  const std::string& init_error() const { return init_error_; }

 private:
  std::string init_error_;

 public:

  ~Client() { Shutdown(); }

  bool connected() const { return connected_; }

  void Shutdown() {
    if (shutdown_.exchange(true)) return;
    if (connected_) {
      Buf b;
      b.PutU8(static_cast<uint8_t>(MsgTag::kShutdown));
      SendFrame(fd_, send_mu_, b.str());
    }
    {
      // Wake any waiters so they observe shutdown instead of blocking.
      std::lock_guard<std::mutex> l(mu_);
      cv_.notify_all();
    }
    if (hb_thread_.joinable()) hb_thread_.join();
    if (recv_thread_.joinable()) recv_thread_.join();
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
    ClosePeerFds();  // recv thread has exited; safe to own the table now
    if (peer_listen_fd_ >= 0) { ::close(peer_listen_fd_); peer_listen_fd_ = -1; }
  }

  bool Enqueue(const Request& req) {
    if (!connected_) return false;
    return SendFrame(fd_, send_mu_, EncodeRequest(req));
  }

  // Whether the client-to-client data plane can run on this rank (the
  // ephemeral peer-listen socket bound successfully at init).
  bool peer_plane_available() const { return peer_listen_fd_ >= 0; }

  // Enqueue with ring election: a large collective is announced WITHOUT
  // its payload (k*Ring); the bytes stay here until the coordinator's
  // ring/mesh plan arrives, then move client-to-client. Everything else
  // takes the star. `flags` is the per-call plane override (the analog of
  // the reference's per-call device_dense=/device_sparse= placement knobs,
  // horovod/tensorflow/__init__.py:43-55): 0 = auto (threshold), 1 =
  // force star, 2 = force the peer plane regardless of payload size.
  // At world size 1 every plane is the identity (there are no peers to
  // move bytes between), so flags==2 is trivially satisfied by the local
  // path rather than a silent degrade — only an UNAVAILABLE peer plane at
  // size > 1 is an error, reported by hvdcoord_submit before this runs.
  bool Submit(Request req, int flags = 0) {
    bool kind_ringable =
        (req.type == ReqType::kAllreduce ||
         req.type == ReqType::kAllgather ||
         req.type == ReqType::kAlltoall ||
         req.type == ReqType::kReducescatter ||
         (req.type == ReqType::kBroadcast && req.root_rank == rank_)) &&
        size_ > 1 && peer_listen_fd_ >= 0;
    bool ringable;
    if (flags == 1) {
      ringable = false;
    } else if (flags == 2) {
      ringable = kind_ringable;
    } else {
      ringable = kind_ringable && ring_threshold_ > 0 &&
                 static_cast<int64_t>(req.payload.size()) >= ring_threshold_;
    }
    if (ringable) {
      {
        std::lock_guard<std::mutex> l(ring_mu_);
        ring_pending_[req.name] = RingWork{std::move(req.payload),
                                           req.dtype, req.red_op,
                                           req.shape};
      }
      switch (req.type) {
        case ReqType::kAllreduce: req.type = ReqType::kAllreduceRing; break;
        case ReqType::kAllgather: req.type = ReqType::kAllgatherRing; break;
        case ReqType::kBroadcast: req.type = ReqType::kBroadcastRing; break;
        case ReqType::kAlltoall: req.type = ReqType::kAlltoallRing; break;
        case ReqType::kReducescatter:
          req.type = ReqType::kReducescatterRing;
          break;
        default: break;
      }
      req.payload.clear();
      if (!Enqueue(req)) {
        std::lock_guard<std::mutex> l(ring_mu_);
        ring_pending_.erase(req.name);
        return false;
      }
      return true;
    }
    return Enqueue(req);
  }

  // Blocks until the named op completes. Returns 0 ok, 1 connection lost,
  // 2 stall deadline exceeded (HOROVOD_STALL_TIMEOUT strict mode; 0=off —
  // then this blocks forever like the reference, which only warns),
  // 3 world aborted (a worker or the coordinator died; message in
  // abort_message()).
  int Wait(const std::string& name, Response* out) {
    std::unique_lock<std::mutex> l(mu_);
    auto ready = [&] {
      return completed_.count(name) > 0 || dead_ || aborted_;
    };
    if (stall_timeout_secs_ > 0) {
      if (!cv_.wait_for(
              l, std::chrono::duration<double>(stall_timeout_secs_),
              ready)) {
        // Abandon the op: names are auto-generated and never waited
        // again, so a late-arriving response must be dropped on receipt
        // or it would sit in completed_ forever (the documented
        // continue-after-StalledError usage would leak every payload).
        abandoned_.insert(name);
        return 2;
      }
    } else {
      cv_.wait(l, ready);
    }
    // Deliver a completed result even under abort: the response arrived
    // before the failure, so the caller's data is intact.
    if (completed_.count(name) > 0) {
      *out = std::move(completed_[name]);
      completed_.erase(name);
      return 0;
    }
    if (aborted_) return 3;
    return 1;
  }

  // Whether the world has been aborted (worker/coordinator failure); the
  // diagnostic names the dead party. Submits and waits fail fast once set.
  bool aborted() {
    std::lock_guard<std::mutex> l(mu_);
    return aborted_;
  }
  std::string abort_message() {
    std::lock_guard<std::mutex> l(mu_);
    return abort_msg_;
  }

  // Fault-injection hook (HVD_FAULT_SPEC rank=N:mute@step=S): stop
  // beating so the coordinator sees this rank go silent while the process
  // — and its TCP socket — stays alive. The only way to exercise the
  // heartbeat-timeout path deterministically (a kill also closes the
  // socket, which trips the faster disconnect path instead).
  void set_heartbeat_mute(bool m) { hb_mute_.store(m); }

  // Pending live resize, if any: returns true and fills the triple when a
  // kResizeNotice (or ack piggyback) announced one. One relaxed atomic
  // load per call — cheap enough for every step boundary.
  bool pending_resize(int32_t* target, int32_t* port, int32_t* gen) {
    int32_t t = pending_resize_target_.load();
    if (t <= 0) return false;
    if (target) *target = t;
    if (port) *port = pending_resize_port_.load();
    if (gen) *gen = pending_resize_gen_.load();
    return true;
  }

 private:
  static int64_t NowMs() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  // Mark the world dead with a diagnostic and wake every waiter. Runs on
  // the recv thread (coordinator-sent kAbort) or the heartbeat thread
  // (missed acks) — first writer wins, the message is never overwritten.
  void Abort(const std::string& msg) {
    std::lock_guard<std::mutex> l(mu_);
    if (!aborted_) {
      aborted_ = true;
      abort_msg_ = msg;
    }
    cv_.notify_all();
  }

  // Client side of the liveness plane: beat every ~timeout/4; if the
  // coordinator has not acked for a full timeout it is dead or wedged —
  // abort locally so blocked waits fail over instead of hanging (the
  // symmetric half of the coordinator's CheckHeartbeats). A C++ thread:
  // keeps beating through long Python-side pauses (GIL-free), so a slow
  // JAX compile never reads as a dead rank.
  void HeartbeatLoop() {
    int64_t interval_ms =
        static_cast<int64_t>(heartbeat_timeout_ * 1000 / 4);
    if (interval_ms < 50) interval_ms = 50;
    if (interval_ms > 2000) interval_ms = 2000;
    while (!shutdown_.load()) {
      if (!hb_mute_.load()) {
        Buf b;
        b.PutU8(static_cast<uint8_t>(MsgTag::kHeartbeat));
        b.PutI32(rank_);
        SendFrame(fd_, send_mu_, b.str());  // EOF surfaces on recv thread
        int64_t silent_ms = NowMs() - last_ack_ms_.load();
        if (silent_ms >
            static_cast<int64_t>(heartbeat_timeout_ * 1000)) {
          std::ostringstream o;
          o << "coordinator failure: no heartbeat-ack from rank 0 for "
            << silent_ms / 1000.0 << " s (> HVD_HEARTBEAT_TIMEOUT="
            << heartbeat_timeout_ << " s); coordinator process dead or "
            << "wedged — aborting this rank";
          fprintf(stderr, "hvdcoord: rank %d: %s\n", rank_,
                  o.str().c_str());
          Abort(o.str());
          return;
        }
      }
      // Sleep in short slices so Shutdown() joins promptly.
      for (int64_t slept = 0; slept < interval_ms && !shutdown_.load();
           slept += 25)
        std::this_thread::sleep_for(std::chrono::milliseconds(25));
    }
  }

  // -- ring data plane -----------------------------------------------------
  // Chunked ring allreduce (reduce-scatter + allgather) among the clients,
  // the bandwidth-optimal exchange the reference gets from MPI_Allreduce's
  // internals: each rank sends 2·(N-1)/N · bytes regardless of world size.
  // Runs on the recv thread, in coordinator response order — every rank
  // executes ring ops in the same sequence, so rings cannot interleave or
  // deadlock across ops (the reference's PerformOperation ordering).

  struct RingWork {
    std::string payload;
    DType dtype;
    RedOp red_op;
    std::vector<int64_t> shape;  // own announced shape (row size for ragged)
  };

  // Establish one full-duplex data-plane socket per needed peer, cached in
  // peer_fds_ and reused across ops (ring neighbors and mesh partners
  // share the table). Deterministic pair rule: the LOWER rank connects,
  // the higher accepts — no duplicate cross-connections. Every rank
  // executes ring/mesh ops in coordinator response order, so establishment
  // is globally ordered and cannot interleave across ops.
  bool EnsurePeerFds(const std::vector<std::string>& peers,
                     const std::vector<int>& needed) {
    std::vector<int> to_connect, to_accept;
    for (int q : needed) {
      if (q == rank_ || peer_fds_[q] >= 0) continue;
      // Dedupe: at N=2 the ring's right and left neighbor are the SAME
      // rank — one full-duplex socket serves both directions; a duplicate
      // entry would spawn two connectors and desynchronize the pair.
      auto& side = rank_ < q ? to_connect : to_accept;
      bool dup = false;
      for (int e : side) dup = dup || e == q;
      if (!dup) side.push_back(q);
    }
    if (to_connect.empty() && to_accept.empty()) return true;

    // Connect-side peers (all higher-ranked): one helper thread each, with
    // NON-BLOCKING connects under a wall-clock deadline — a blackholed
    // peer (SYN dropped, no RST) would otherwise park each blocking
    // connect on the kernel's ~2 min SYN retry schedule and blow through
    // the documented HOROVOD_RING_IO_TIMEOUT bound by orders of magnitude.
    std::vector<int> connected(to_connect.size(), -1);
    std::vector<std::thread> connectors;
    for (size_t k = 0; k < to_connect.size(); k++) {
      connectors.emplace_back([&, k] {
        const std::string& addr = peers[to_connect[k]];
        size_t c = addr.rfind(':');
        std::string ip = addr.substr(0, c);
        int pport = atoi(addr.c_str() + c + 1);
        auto cdeadline = std::chrono::steady_clock::now() +
                         std::chrono::seconds(ring_io_secs_);
        while (std::chrono::steady_clock::now() < cdeadline) {
          int s = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
          sockaddr_in a{};
          a.sin_family = AF_INET;
          a.sin_port = htons(static_cast<uint16_t>(pport));
          if (inet_pton(AF_INET, ip.c_str(), &a.sin_addr) != 1) {
            // Unresolvable peer address: retrying cannot help; fail the
            // op now with the cause on stderr instead of burning the
            // full IO timeout connecting to 0.0.0.0.
            fprintf(stderr,
                    "hvdcoord: rank %d has unparseable ring data-plane "
                    "address \"%s\" (check HOROVOD_RING_ADVERTISE_ADDR)\n",
                    to_connect[k], addr.c_str());
            ::close(s);
            return;
          }
          int rc = ::connect(s, reinterpret_cast<sockaddr*>(&a), sizeof(a));
          bool up = rc == 0;
          if (!up && errno == EINPROGRESS) {
            auto left_ms =
                std::chrono::duration_cast<std::chrono::milliseconds>(
                    cdeadline - std::chrono::steady_clock::now())
                    .count();
            pollfd pfd{s, POLLOUT, 0};
            if (left_ms > 0 &&
                ::poll(&pfd, 1, static_cast<int>(left_ms)) > 0) {
              int soerr = 0;
              socklen_t slen = sizeof(soerr);
              getsockopt(s, SOL_SOCKET, SO_ERROR, &soerr, &slen);
              up = soerr == 0;
            }
          }
          if (up) {
            // Back to blocking IO with the ring bound on both directions.
            int fl = fcntl(s, F_GETFL, 0);
            fcntl(s, F_SETFL, fl & ~O_NONBLOCK);
            int one = 1;
            setsockopt(s, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            timeval io_timeout{ring_io_secs_, 0};
            setsockopt(s, SOL_SOCKET, SO_SNDTIMEO, &io_timeout,
                       sizeof(io_timeout));
            setsockopt(s, SOL_SOCKET, SO_RCVTIMEO, &io_timeout,
                       sizeof(io_timeout));
            int32_t me = rank_;
            if (::send(s, &me, 4, MSG_NOSIGNAL) == 4) {
              connected[k] = s;  // each thread writes its own slot
              return;
            }
            ::close(s);
            return;
          }
          ::close(s);
          std::this_thread::sleep_for(std::chrono::milliseconds(50));
        }
      });
    }

    // Accept-side peers (all lower-ranked; a plan means every rank got the
    // same response, so they are coming). Stray connections to the data
    // port (port scanners, probes) must not hang or kill the rank — same
    // hardening standard as the control-plane hello: bound the identity
    // read with a recv timeout, classify by identity, and keep accepting
    // until every expected peer shows up or the deadline passes.
    size_t missing = to_accept.size();
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::seconds(ring_io_secs_);
    while (missing > 0) {
      auto left_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                         deadline - std::chrono::steady_clock::now())
                         .count();
      if (left_ms <= 0) break;
      pollfd pfd{peer_listen_fd_, POLLIN, 0};
      if (::poll(&pfd, 1, static_cast<int>(left_ms)) <= 0) break;
      int fd = ::accept(peer_listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      timeval id_timeout{/*tv_sec=*/5, /*tv_usec=*/0};
      setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &id_timeout,
                 sizeof(id_timeout));
      int32_t who = -1;
      bool expected = false;
      if (RecvAll(fd, &who, 4)) {
        for (int q : to_accept)
          expected = expected || (q == who && peer_fds_[who] < 0);
      }
      if (expected) {
        // Keep the IO bound for every future chunk send/recv: a peer
        // dying mid-op must surface as a failed step (-> TransportError),
        // not an unbounded block that also starves the control socket.
        timeval io_timeout{ring_io_secs_, 0};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &io_timeout,
                   sizeof(io_timeout));
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &io_timeout,
                   sizeof(io_timeout));
        peer_fds_[who] = fd;
        missing--;
      } else {
        fprintf(stderr,
                "hvdcoord: rejecting stray connection on peer data port "
                "(got identity %d)\n", who);
        ::close(fd);  // stray/garbled: keep accepting
      }
    }
    for (auto& t : connectors) t.join();
    for (size_t k = 0; k < to_connect.size(); k++)
      if (connected[k] >= 0) peer_fds_[to_connect[k]] = connected[k];
    bool ok = missing == 0;
    for (int q : to_connect) ok = ok && peer_fds_[q] >= 0;
    return ok;
  }

  void ClosePeerFds() {
    for (int& fd : peer_fds_)
      if (fd >= 0) { ::close(fd); fd = -1; }
  }

  // Raw fixed-size exchange with two peers: send `snd` on snd_fd while
  // receiving `rcv_n` bytes from rcv_fd (for ring ops these are the right
  // and left neighbors; for the mesh alltoall, the step's partners — the
  // two may be the same full-duplex socket at N=2). The send rides a
  // helper thread so a full TCP buffer cannot deadlock the step (everyone
  // sends and receives simultaneously). Thread spawn cost (~10 us) is
  // noise against the >=MB-scale transfers the peer plane carries; both
  // sockets have HOROVOD_RING_IO_TIMEOUT bounds so a dead peer fails the
  // step.
  bool RingStep(int snd_fd, const char* snd, size_t snd_n, int rcv_fd,
                char* rcv, size_t rcv_n) {
    std::atomic<bool> send_ok{true};
    std::thread sender([&] {
      size_t off = 0;
      while (off < snd_n) {
        ssize_t n = ::send(snd_fd, snd + off, snd_n - off, MSG_NOSIGNAL);
        if (n <= 0) { send_ok.store(false); return; }
        off += static_cast<size_t>(n);
      }
    });
    bool recv_ok = rcv_n == 0 || RecvAll(rcv_fd, rcv, rcv_n);
    sender.join();
    if (send_ok.load()) ring_bytes_sent_ += snd_n;
    return send_ok.load() && recv_ok;
  }

  // Ring-neighbor convenience wrapper (send right, receive from left).
  bool NeighborStep(const char* snd, size_t snd_n, char* rcv, size_t rcv_n) {
    int right = (rank_ + 1) % size_;
    int left = (rank_ - 1 + size_) % size_;
    return RingStep(peer_fds_[right], snd, snd_n, peer_fds_[left], rcv,
                    rcv_n);
  }

  bool EnsureRingNeighbors(const std::vector<std::string>& peers) {
    std::vector<int> needed{(rank_ + 1) % size_, (rank_ - 1 + size_) % size_};
    return EnsurePeerFds(peers, needed);
  }

  bool RunRing(const Response& plan, RingWork work, std::string* out) {
    if (!EnsureRingNeighbors(plan.ring_peers)) return false;
    const int N = size_;
    std::string& buf = work.payload;
    const size_t esz = static_cast<size_t>(DTypeSize(work.dtype));
    const size_t elems = buf.size() / esz;
    // Element-aligned chunk boundaries [off[i], off[i+1]) in bytes.
    std::vector<size_t> off(N + 1);
    for (int i = 0; i <= N; i++)
      off[i] = (elems * i / N) * esz;
    std::string incoming(off[1] - off[0] + esz, '\0');  // max chunk size
    auto chunk = [&](int i) { return &buf[0] + off[i]; };
    auto clen = [&](int i) { return off[i + 1] - off[i]; };

    // Phase 1: reduce-scatter. After step s, the chunk received at
    // (r - s - 1) holds the partial sum of s+2 ranks; after N-2 steps rank
    // r owns the fully reduced chunk (r + 1) % N.
    for (int s = 0; s <= N - 2; s++) {
      int snd = (rank_ - s + N) % N;
      int rcv = (rank_ - s - 1 + N) % N;
      if (!NeighborStep(chunk(snd), clen(snd), &incoming[0], clen(rcv)))
        return false;
      // In-place accumulate; order differs from the star's rank-order
      // reduce only in float rounding (as MPI's ring does).
      ReducePayloadRaw(work.dtype, work.red_op, chunk(rcv), incoming.data(),
                       clen(rcv));
    }
    // Phase 2: allgather of the reduced chunks around the ring.
    for (int s = 0; s <= N - 2; s++) {
      int snd = (rank_ + 1 - s + N) % N;
      int rcv = (rank_ - s + N) % N;
      if (!NeighborStep(chunk(snd), clen(snd), &incoming[0], clen(rcv)))
        return false;
      memcpy(chunk(rcv), incoming.data(), clen(rcv));
    }
    ring_ops_++;
    *out = std::move(buf);
    return true;
  }

  // Ring reducescatter: the reduce-scatter PHASE of the ring allreduce
  // alone, with chunk indices shifted by -1 so rank r ends owning its own
  // fully-reduced block r (the psum_scatter tiled semantics the star path
  // implements host-side). Blocks are exact (first dim divisible by N,
  // validated at negotiation). Per-rank traffic = (N-1)/N · payload.
  bool RunRingScatter(const Response& plan, RingWork work,
                      std::string* out) {
    if (!EnsureRingNeighbors(plan.ring_peers)) return false;
    const int N = size_;
    std::string& buf = work.payload;
    const size_t block = buf.size() / N;
    std::string incoming(block, '\0');
    for (int s = 0; s <= N - 2; s++) {
      int snd = (rank_ - s - 1 + 2 * N) % N;
      int rcv = (rank_ - s - 2 + 2 * N) % N;
      if (!NeighborStep(&buf[snd * block], block, &incoming[0], block))
        return false;
      ReducePayloadRaw(work.dtype, work.red_op, &buf[rcv * block],
                       incoming.data(), block);
    }
    out->assign(buf.data() + rank_ * block, block);
    ring_ops_++;
    return true;
  }

  // Mesh alltoall: direct pairwise block exchange over the full-duplex
  // peer-socket mesh. At step d, send block (r+d) to rank (r+d) while
  // receiving block r of rank (r-d) from (r-d) — pairwise symmetric, so
  // RingStep's concurrent send+recv cannot deadlock. Per-rank traffic =
  // (N-1)/N · payload, independent of world size (the star relays
  // N · payload through rank 0 in each direction).
  bool RunMeshAlltoall(const Response& plan, RingWork work,
                       std::string* out) {
    std::vector<int> needed;
    for (int q = 0; q < size_; q++)
      if (q != rank_) needed.push_back(q);
    if (!EnsurePeerFds(plan.ring_peers, needed)) return false;
    const size_t block = work.payload.size() / size_;
    out->assign(work.payload.size(), '\0');
    memcpy(&(*out)[0] + rank_ * block, work.payload.data() + rank_ * block,
           block);
    for (int d = 1; d < size_; d++) {
      int to = (rank_ + d) % size_;
      int from = (rank_ - d + size_) % size_;
      if (!RingStep(peer_fds_[to], work.payload.data() + to * block, block,
                    peer_fds_[from], &(*out)[0] + from * block, block))
        return false;
    }
    ring_ops_++;
    return true;
  }

  // Ring allgather: each rank's (possibly ragged) block circulates N-1
  // hops; at step s we forward the block received at step s-1 while
  // writing the incoming one straight into its slot of the final
  // rank-ordered concatenation. Per-rank traffic = output - own block.
  bool RunRingGather(const Response& plan, RingWork work,
                     std::string* out) {
    if (!EnsureRingNeighbors(plan.ring_peers)) return false;
    const int N = size_;
    int64_t row_bytes = static_cast<int64_t>(DTypeSize(work.dtype));
    for (size_t i = 1; i < work.shape.size(); i++)
      row_bytes *= work.shape[i];
    std::vector<int64_t> nb(N), off(N + 1, 0);
    for (int i = 0; i < N; i++) {
      nb[i] = plan.sizes[i] * row_bytes;
      off[i + 1] = off[i] + nb[i];
    }
    out->assign(static_cast<size_t>(off[N]), '\0');
    memcpy(&(*out)[0] + off[rank_], work.payload.data(),
           work.payload.size());
    for (int s = 0; s <= N - 2; s++) {
      int snd = (rank_ - s + N) % N;
      int rcv = (rank_ - s - 1 + N) % N;
      if (!NeighborStep(out->data() + off[snd],
                        static_cast<size_t>(nb[snd]),
                        &(*out)[0] + off[rcv], static_cast<size_t>(nb[rcv])))
        return false;
    }
    ring_ops_++;
    return true;
  }

  // Ring broadcast: chunk-pipelined CHAIN from the root around the rank
  // ring (root -> root+1 -> ... -> root-1). Middle ranks forward chunk
  // c-1 while receiving chunk c (RingStep's simultaneous send+recv), so
  // the payload streams down the chain at link bandwidth; per-link bytes
  // = payload exactly.
  bool RunRingBcast(const Response& plan, std::string root_payload,
                    std::string* out) {
    if (!EnsureRingNeighbors(plan.ring_peers)) return false;
    int root = static_cast<int>(plan.sizes.empty() ? 0 : plan.sizes[0]);
    int64_t total = DTypeSize(plan.dtype);
    for (int64_t d : plan.shape) total *= d;
    const size_t kChunk = 1 << 20;
    bool is_last = rank_ == (root - 1 + size_) % size_;
    if (rank_ == root) {
      *out = std::move(root_payload);
      for (size_t o = 0; o < static_cast<size_t>(total); o += kChunk) {
        size_t l = std::min(kChunk, static_cast<size_t>(total) - o);
        if (!NeighborStep(out->data() + o, l, nullptr, 0)) return false;
      }
    } else {
      out->assign(static_cast<size_t>(total), '\0');
      size_t po = 0, pl = 0;
      for (size_t o = 0; o < static_cast<size_t>(total); o += kChunk) {
        size_t l = std::min(kChunk, static_cast<size_t>(total) - o);
        // Forward the previous chunk while receiving this one.
        if (!NeighborStep(is_last ? nullptr : out->data() + po,
                          is_last ? 0 : pl, &(*out)[0] + o, l))
          return false;
        po = o;
        pl = l;
      }
      if (!is_last && pl > 0) {
        if (!NeighborStep(out->data() + po, pl, nullptr, 0)) return false;
      }
    }
    ring_ops_++;
    return true;
  }

  void RecvLoop() {
    while (!shutdown_.load()) {
      std::string body;
      if (!RecvFrame(fd_, &body)) break;
      Reader rd(body);
      MsgTag tag = static_cast<MsgTag>(rd.GetU8());
      if (tag == MsgTag::kHeartbeatAck) {
        last_ack_ms_.store(NowMs());
        // v7 acks carry the pending-resize triple; reading it here means
        // the training loop's step-boundary poll is one atomic load.
        if (body.size() >= 13) {
          int32_t target = rd.GetI32();
          int32_t port = rd.GetI32();
          int32_t gen = rd.GetI32();
          if (target > 0) SetPendingResize(target, port, gen);
        }
        continue;
      }
      if (tag != MsgTag::kResponse) break;
      Response resp = DecodeResponse(rd);
      if (resp.type == RespType::kShutdown) break;
      if (resp.type == RespType::kResizeNotice) {
        if (resp.sizes.size() >= 3)
          SetPendingResize(static_cast<int32_t>(resp.sizes[0]),
                           static_cast<int32_t>(resp.sizes[1]),
                           static_cast<int32_t>(resp.sizes[2]));
        continue;
      }
      if (resp.type == RespType::kAbort) {
        // World aborted (a rank died / went silent). Drop the ring
        // stashes — their plans will never arrive — and fail every
        // current and future wait with the named dead rank.
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          ring_pending_.clear();
        }
        Abort(resp.error);
        break;
      }
      if (resp.type == RespType::kResubmitStar) {
        // Mixed straddling-threshold allgather: re-announce with the
        // stashed payload over the star plane.
        RingWork work;
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          auto it = ring_pending_.find(resp.name);
          if (it == ring_pending_.end()) break;  // protocol violation
          work = std::move(it->second);
          ring_pending_.erase(it);
        }
        Request rq;
        rq.rank = rank_;
        rq.type = ReqType::kAllgather;
        rq.dtype = work.dtype;
        rq.red_op = work.red_op;
        rq.shape = work.shape;
        rq.name = resp.name;
        rq.payload = std::move(work.payload);
        if (!Enqueue(rq)) break;
        continue;
      }
      if (resp.type == RespType::kBroadcastRing) {
        std::string stash;  // only the root has one
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          auto it = ring_pending_.find(resp.name);
          if (it != ring_pending_.end()) {
            stash = std::move(it->second.payload);
            ring_pending_.erase(it);
          }
        }
        std::string result;
        if (!RunRingBcast(resp, std::move(stash), &result)) break;
        resp.type = RespType::kBroadcast;
        resp.payload = std::move(result);
        resp.sizes.clear();
      } else if (resp.type == RespType::kAllgatherRing) {
        RingWork work;
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          auto it = ring_pending_.find(resp.name);
          if (it == ring_pending_.end()) break;  // protocol violation
          work = std::move(it->second);
          ring_pending_.erase(it);
        }
        std::string gathered;
        if (!RunRingGather(resp, std::move(work), &gathered)) break;
        resp.type = RespType::kAllgather;  // sizes already negotiated
        resp.payload = std::move(gathered);
      } else if (resp.type == RespType::kAlltoallRing) {
        RingWork work;
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          auto it = ring_pending_.find(resp.name);
          if (it == ring_pending_.end()) break;  // protocol violation
          work = std::move(it->second);
          ring_pending_.erase(it);
        }
        std::string exchanged;
        if (!RunMeshAlltoall(resp, std::move(work), &exchanged)) break;
        resp.type = RespType::kAlltoall;
        resp.payload = std::move(exchanged);
      } else if (resp.type == RespType::kReducescatterRing) {
        RingWork work;
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          auto it = ring_pending_.find(resp.name);
          if (it == ring_pending_.end()) break;  // protocol violation
          work = std::move(it->second);
          ring_pending_.erase(it);
        }
        std::string scattered;
        if (!RunRingScatter(resp, std::move(work), &scattered)) break;
        resp.type = RespType::kReducescatter;
        resp.payload = std::move(scattered);
      } else if (resp.type == RespType::kAllreduceRing) {
        // NB: a ring op whose wait stall-timed-out keeps its stash here
        // until the plan (or an error) arrives — if the slow ranks do
        // announce late, the world still needs this rank's payload to
        // complete the ring (the result is then dropped via abandoned_).
        // A never-completing op retains its payload until shutdown; that
        // retention is the price of not corrupting a late completion.
        RingWork work;
        {
          std::lock_guard<std::mutex> l(ring_mu_);
          auto it = ring_pending_.find(resp.name);
          if (it == ring_pending_.end()) break;  // protocol violation
          work = std::move(it->second);
          ring_pending_.erase(it);
        }
        std::string reduced;
        if (!RunRing(resp, std::move(work), &reduced)) break;
        resp.type = RespType::kAllreduce;
        resp.payload = std::move(reduced);
      } else if (resp.type == RespType::kError) {
        // A rejected ring announcement still holds the stashed payload.
        std::lock_guard<std::mutex> l(ring_mu_);
        ring_pending_.erase(resp.name);
      }
      std::lock_guard<std::mutex> l(mu_);
      responses_received_++;
      // Late response to a wait that already timed out (strict stall
      // mode): count it completed but drop the payload — nobody will
      // ever redeem it.
      auto deliver = [&](Response&& one) {
        ops_completed_++;
        if (abandoned_.erase(one.name) > 0) return;
        completed_[one.name] = std::move(one);
      };
      if (!resp.fused_names.empty()) {
        // Fused frame: split the concatenated payload back into the
        // individual ops it answers (reference: one MPIResponse completes
        // every entry in tensor_names, mpi_ops.cc:1024-1096 memcpy-out).
        size_t off = 0;
        for (size_t i = 0; i < resp.fused_names.size(); i++) {
          Response one;
          one.type = resp.type;
          one.name = resp.fused_names[i];
          size_t n = static_cast<size_t>(resp.fused_nbytes[i]);
          one.payload = resp.payload.substr(off, n);
          off += n;
          deliver(std::move(one));
        }
      } else {
        deliver(std::move(resp));
      }
      cv_.notify_all();
    }
    // Close the peer sockets on the way out so peers blocked in a
    // ring/mesh step observe EOF immediately (fast failure cascade)
    // instead of waiting out their IO timeout.
    ClosePeerFds();
    std::lock_guard<std::mutex> l(mu_);
    dead_ = true;
    cv_.notify_all();
  }

 public:
  // Stats for fusion observability (tested by the fused-path analog of
  // mpi_ops_test.py:116-148): frames received vs ops completed — completed >
  // received proves response fusion happened.
  long long responses_received() {
    std::lock_guard<std::mutex> l(mu_);
    return responses_received_;
  }
  long long ops_completed() {
    std::lock_guard<std::mutex> l(mu_);
    return ops_completed_;
  }
  // Ring observability (the byte-accounting proof that large allreduces
  // move <= ~2x bytes per rank regardless of world size).
  long long ring_ops() { return ring_ops_.load(); }
  long long ring_bytes_sent() { return ring_bytes_sent_.load(); }

 private:
  long long responses_received_ = 0;
  long long ops_completed_ = 0;
  std::atomic<long long> ring_ops_{0};
  std::atomic<long long> ring_bytes_sent_{0};

  int32_t rank_;
  int size_;
  int fd_ = -1;
  bool connected_ = false;
  int64_t ring_threshold_ = 0;
  double stall_timeout_secs_ = 0;
  int ring_io_secs_ = 30;
  double heartbeat_timeout_ = 30.0;
  std::thread hb_thread_;
  std::atomic<bool> hb_mute_{false};
  std::atomic<int64_t> last_ack_ms_{0};
  // Pending live resize announced by the coordinator (v7). Port/gen are
  // written before target (the readiness flag), so a reader that sees the
  // target also sees its port/generation.
  std::atomic<int32_t> pending_resize_target_{0};
  std::atomic<int32_t> pending_resize_port_{0};
  std::atomic<int32_t> pending_resize_gen_{0};

  void SetPendingResize(int32_t target, int32_t port, int32_t gen) {
    pending_resize_port_.store(port);
    pending_resize_gen_.store(gen);
    pending_resize_target_.store(target);
  }
  int peer_listen_fd_ = -1;
  int peer_port_ = 0;
  // Full-duplex data-plane socket per peer rank (-1 = not established).
  // Owned by the recv thread (all ring/mesh ops run there in response
  // order); Shutdown touches it only after joining that thread.
  std::vector<int> peer_fds_;
  std::mutex ring_mu_;
  std::map<std::string, RingWork> ring_pending_;
  std::mutex send_mu_;
  std::thread recv_thread_;
  std::atomic<bool> shutdown_{false};
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, Response> completed_;
  std::set<std::string> abandoned_;  // stall-timed-out names (guarded by mu_)
  bool dead_ = false;
  bool aborted_ = false;        // guarded by mu_
  std::string abort_msg_;       // guarded by mu_
};

// ---------------------------------------------------------------------------
// Global state + C ABI (parity: horovod_tensorflow_* C functions,
// mpi_ops.cc:1516-1566; single-owner global like HorovodGlobalState).
// ---------------------------------------------------------------------------

struct Global {
  std::unique_ptr<Coordinator> coordinator;
  std::unique_ptr<Client> client;
  int rank = -1;
  int size = 0;
  std::mutex mu;
};

Global* g() {
  static Global instance;
  return &instance;
}

}  // namespace hvdcoord

extern "C" {

// Returns 0 on success; 1 coordinator bind failure; 2 connect/handshake
// failure (message in err — e.g. world-size or protocol-version mismatch
// detected by the coordinator's hello validation).
int hvdcoord_init(int rank, int size, const char* host, int port,
                  long long fusion_threshold, double stall_secs,
                  const char* timeline_path, char* err, int errlen) {
  using namespace hvdcoord;
  std::lock_guard<std::mutex> l(g()->mu);
  if (g()->client) return 0;  // idempotent (InitializeHorovodOnce parity)
  if (rank == 0) {
    g()->coordinator.reset(new Coordinator(
        size, port, fusion_threshold, stall_secs,
        timeline_path ? timeline_path : ""));
    if (!g()->coordinator->ok()) {
      if (err && errlen > 0)
        snprintf(err, errlen, "coordinator failed to bind/listen on port %d",
                 port);
      return 1;
    }
  }
  g()->client.reset(new Client(rank, size, host, port));
  if (!g()->client->connected()) {
    if (err && errlen > 0) {
      const std::string& m = g()->client->init_error();
      snprintf(err, errlen, "%s",
               m.empty() ? "could not connect to coordinator" : m.c_str());
    }
    g()->client.reset();
    g()->coordinator.reset();
    return 2;
  }
  g()->rank = rank;
  g()->size = size;
  return 0;
}

int hvdcoord_rank() { return hvdcoord::g()->client ? hvdcoord::g()->rank : -1; }
int hvdcoord_size() { return hvdcoord::g()->client ? hvdcoord::g()->size : -1; }

// Non-blocking submit (reference: ComputeAsync + EnqueueTensor*,
// mpi_ops.cc:1752-1772 — many collectives negotiate concurrently, feeding
// coordinator-side fusion). `plane` is the per-call placement override
// (the analog of the reference's device_dense=/device_sparse= knobs,
// horovod/tensorflow/__init__.py:43-55): 0 auto (HOROVOD_RING_THRESHOLD
// decides), 1 force the coordinator star, 2 force the client-to-client
// peer plane. Returns 0 ok, 2 transport failure.
int hvdcoord_submit(const char* name, int req_type, int dtype, int red_op,
                    int root_rank, int ndim, const long long* shape,
                    const void* data, long long nbytes, int plane,
                    char* err, int errlen) {
  using namespace hvdcoord;
  auto* G = g();
  if (!G->client) {
    snprintf(err, errlen, "hvdcoord not initialized");
    return 2;
  }
  if (G->client->aborted()) {
    // Fail fast: after a world abort every collective is doomed — a
    // fresh submit would announce into a dead coordinator and hang the
    // caller in wait. Surface the original failure instead.
    snprintf(err, errlen, "%s", G->client->abort_message().c_str());
    return 4;
  }
  Request req;
  req.rank = G->rank;
  req.type = static_cast<ReqType>(req_type);
  req.dtype = static_cast<DType>(dtype);
  req.red_op = static_cast<RedOp>(red_op);
  req.root_rank = root_rank;
  for (int i = 0; i < ndim; i++) req.shape.push_back(shape[i]);
  req.name = name;
  if (data && nbytes > 0)
    req.payload.assign(reinterpret_cast<const char*>(data),
                       static_cast<size_t>(nbytes));
  if (plane == 2 && G->size > 1 && !G->client->peer_plane_available()) {
    // An explicit force must not silently degrade to the star: the other
    // ranks would announce the ring variant and the world would fail with
    // a misattributed cross-rank mismatch error. Name the real cause.
    // (At size 1 every plane is the identity — no peers, nothing to
    // degrade — so the force is trivially satisfied, not an error.)
    snprintf(err, errlen,
             "plane=\"ring\" forced but the peer data plane is unavailable "
             "on rank %d (the ephemeral peer-listen socket failed to bind "
             "at init — port exhaustion?)",
             G->rank);
    return 2;
  }
  if (!G->client->Submit(std::move(req), plane)) {
    snprintf(err, errlen, "hvdcoord: send failed (coordinator down?)");
    return 2;
  }
  return 0;
}

// Block until the named op completes. Returns:
//   0 ok; fills *out (malloc'd; caller frees via hvdcoord_free), *out_nbytes,
//     and for allgather writes per-rank first dims into sizes_out[size].
//   1 coordinator-reported validation error (message in err, FailedPrecondition
//     parity, mpi_ops.cc:1141-1148); 2 transport failure; 3 stall deadline
//     exceeded (HOROVOD_STALL_TIMEOUT strict mode -> StalledError);
//   4 world aborted — a worker or the coordinator died (message names the
//     dead party -> WorkerFailureError).
int hvdcoord_wait(const char* name, void** out, long long* out_nbytes,
                  long long* sizes_out, char* err, int errlen) {
  using namespace hvdcoord;
  auto* G = g();
  if (!G->client) {
    snprintf(err, errlen, "hvdcoord not initialized");
    return 2;
  }
  Response resp;
  int wrc = G->client->Wait(name, &resp);
  if (wrc == 3) {
    snprintf(err, errlen, "%s", G->client->abort_message().c_str());
    return 4;
  }
  if (wrc == 2) {
    snprintf(err, errlen,
             "collective %s exceeded HOROVOD_STALL_TIMEOUT: one or more "
             "ranks never announced it (see the coordinator's stall "
             "warning for the ready-rank list)",
             name);
    return 3;
  }
  if (wrc != 0) {
    snprintf(err, errlen, "hvdcoord: connection lost while waiting for %s",
             name);
    return 2;
  }
  if (resp.type == RespType::kError) {
    snprintf(err, errlen, "%s", resp.error.c_str());
    return 1;
  }
  *out_nbytes = static_cast<long long>(resp.payload.size());
  *out = malloc(resp.payload.size() ? resp.payload.size() : 1);
  memcpy(*out, resp.payload.data(), resp.payload.size());
  if (sizes_out) {
    for (size_t i = 0; i < resp.sizes.size() && i < (size_t)G->size; i++)
      sizes_out[i] = resp.sizes[i];
  }
  return 0;
}

// Submit + wait (synchronous eager calls).
int hvdcoord_run(const char* name, int req_type, int dtype, int red_op,
                 int root_rank, int ndim, const long long* shape,
                 const void* data, long long nbytes, void** out,
                 long long* out_nbytes, long long* sizes_out, char* err,
                 int errlen) {
  int rc = hvdcoord_submit(name, req_type, dtype, red_op, root_rank, ndim,
                           shape, data, nbytes, /*plane=*/0, err, errlen);
  if (rc != 0) return rc;
  return hvdcoord_wait(name, out, out_nbytes, sizes_out, err, errlen);
}

// Fusion observability: response frames received vs ops completed on this
// rank's client (completed > received ⇔ some frames were fused).
long long hvdcoord_responses_received() {
  using namespace hvdcoord;
  return g()->client ? g()->client->responses_received() : -1;
}
long long hvdcoord_ops_completed() {
  using namespace hvdcoord;
  return g()->client ? g()->client->ops_completed() : -1;
}

// Ring-plane observability: ops that took the client-to-client ring, and
// the data-plane bytes this rank sent for them (2·(N-1)/N · payload per op
// — the bandwidth-optimality proof, independent of world size).
long long hvdcoord_ring_ops() {
  using namespace hvdcoord;
  return g()->client ? g()->client->ring_ops() : -1;
}
long long hvdcoord_ring_bytes_sent() {
  using namespace hvdcoord;
  return g()->client ? g()->client->ring_bytes_sent() : -1;
}

void hvdcoord_free(void* p) { free(p); }

// ---------------------------------------------------------------------------
// Deterministic fault-injection hooks (HVD_FAULT_SPEC; testing/faults.py).
// These simulate SILENT failures — the kind a kill cannot produce because
// the kernel closes a dead process's sockets (tripping the faster
// disconnect path). No-ops when the world is not initialized.
// ---------------------------------------------------------------------------

// Stop (1) / resume (0) this rank's heartbeats while keeping the process
// and socket alive: the coordinator must declare this rank dead after
// HVD_HEARTBEAT_TIMEOUT and abort the world.
void hvdcoord_mute_heartbeats(int mute) {
  using namespace hvdcoord;
  if (g()->client) g()->client->set_heartbeat_mute(mute != 0);
}

// Stop (1) / resume (0) the coordinator's heartbeat-acks (rank 0 only;
// no-op elsewhere): every client must independently detect the silent
// coordinator and abort after HVD_HEARTBEAT_TIMEOUT.
void hvdcoord_coord_mute_acks(int mute) {
  using namespace hvdcoord;
  if (g()->coordinator) g()->coordinator->set_mute_acks(mute != 0);
}

// Whether this rank's world has aborted (1) — test/observability hook.
int hvdcoord_aborted() {
  using namespace hvdcoord;
  return (g()->client && g()->client->aborted()) ? 1 : 0;
}

// Pending live resize announced over the v7 admin plane: returns 1 and
// fills {target world, new coordinator port, generation} when one is
// pending, 0 otherwise. One atomic load — called at every training step
// boundary by horovod_tpu.elastic.ResizeCoordinator.
int hvdcoord_pending_resize(int* target, int* port, int* generation) {
  using namespace hvdcoord;
  if (!g()->client) return 0;
  int32_t t = 0, p = 0, gen = 0;
  if (!g()->client->pending_resize(&t, &p, &gen)) return 0;
  if (target) *target = t;
  if (port) *port = p;
  if (generation) *generation = gen;
  return 1;
}

void hvdcoord_shutdown() {
  using namespace hvdcoord;
  std::lock_guard<std::mutex> l(g()->mu);
  if (g()->client) g()->client->Shutdown();
  g()->client.reset();
  g()->coordinator.reset();
}

}  // extern "C"
