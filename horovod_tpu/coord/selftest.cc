// Threaded self-test for the native coordination core, built standalone
// (optionally with -fsanitize=thread) — the sanitizer coverage the
// reference never had (SURVEY §5.2: its thread-safety was by construction
// only). Exercises, in ONE process, the full concurrency surface:
//
//   - a Coordinator server thread (world size N);
//   - N client "ranks", each ALSO submitting from M concurrent worker
//     threads (the reference's TF-executor-thread model);
//   - sync submits, async bursts (feeding response fusion), mixed dtypes,
//     a validation-error round, and clean shutdown.
//
// Build/run (see Makefile `selftest` / `tsan` targets):
//   g++ -std=c++14 -O2 -pthread [-fsanitize=thread] -o selftest selftest.cc
//   ./selftest
//
// The coordinator implementation is #included so the test sees the same
// code the .so ships, without exporting internal symbols.

#include <cassert>
#include <cmath>

#include "coordinator.cc"

namespace {

using hvdcoord::Client;
using hvdcoord::Coordinator;
using hvdcoord::ReqType;
using hvdcoord::RedOp;
using hvdcoord::Request;
using hvdcoord::Response;
using hvdcoord::DType;

constexpr int kPort = 29771;
constexpr int kSize = 3;
constexpr int kThreadsPerRank = 4;
constexpr int kOpsPerThread = 25;

std::string F32Payload(const std::vector<float>& v) {
  return std::string(reinterpret_cast<const char*>(v.data()),
                     v.size() * sizeof(float));
}

void RankMain(int rank, std::atomic<int>* failures) {
  Client client(rank, kSize, "127.0.0.1", kPort);
  if (!client.connected()) {
    fprintf(stderr, "rank %d: connect failed: %s\n", rank,
            client.init_error().c_str());
    failures->fetch_add(1);
    return;
  }

  // Concurrent submitters (the ComputeAsync model).
  std::vector<std::thread> workers;
  for (int t = 0; t < kThreadsPerRank; t++) {
    workers.emplace_back([&, t] {
      for (int i = 0; i < kOpsPerThread; i++) {
        Request req;
        req.rank = rank;
        req.type = ReqType::kAllreduce;
        req.dtype = DType::kF32;
        req.red_op = RedOp::kSum;
        req.shape = {4};
        req.name = "t" + std::to_string(t) + "." + std::to_string(i);
        req.payload = F32Payload({1.f * rank, 2.f, 3.f, float(i)});
        if (!client.Enqueue(req)) {
          failures->fetch_add(1);
          return;
        }
        Response resp;
        if (client.Wait(req.name, &resp) != 0 ||
            resp.type != hvdcoord::RespType::kAllreduce) {
          failures->fetch_add(1);
          return;
        }
        const float* out =
            reinterpret_cast<const float*>(resp.payload.data());
        float expect0 = 0.f;
        for (int r = 0; r < kSize; r++) expect0 += 1.f * r;
        if (std::fabs(out[0] - expect0) > 1e-6 ||
            std::fabs(out[1] - 2.f * kSize) > 1e-6) {
          failures->fetch_add(1);
          return;
        }
      }
    });
  }
  for (auto& w : workers) w.join();

  // Async burst from one thread: submit all, wait all (fusion path).
  std::vector<std::string> names;
  for (int i = 0; i < 16; i++) {
    Request req;
    req.rank = rank;
    req.type = ReqType::kAllreduce;
    req.dtype = DType::kF32;
    req.shape = {8};
    req.name = "burst." + std::to_string(i);
    req.payload = F32Payload(std::vector<float>(8, float(i)));
    if (!client.Enqueue(req)) failures->fetch_add(1);
    names.push_back(req.name);
  }
  for (auto it = names.rbegin(); it != names.rend(); ++it) {  // reverse
    Response resp;
    if (client.Wait(*it, &resp) != 0) failures->fetch_add(1);
  }

  // A cross-rank validation error must surface as kError on every rank.
  {
    Request req;
    req.rank = rank;
    req.type = ReqType::kAllreduce;
    req.dtype = (rank == 0) ? DType::kF32 : DType::kF64;
    req.shape = {2};
    req.name = "bad.dtype";
    req.payload = std::string((rank == 0 ? 2 : 2) *
                              (rank == 0 ? 4 : 8), '\0');
    client.Enqueue(req);
    Response resp;
    if (client.Wait(req.name, &resp) != 0 ||
        resp.type != hvdcoord::RespType::kError ||
        resp.error.find("Mismatched data types") == std::string::npos) {
      failures->fetch_add(1);
    }
  }

  // Ring round: a payload over HOROVOD_RING_THRESHOLD (set tiny in main)
  // takes the client-to-client ring data plane — exercises the peer
  // connect/accept handshake, the per-step sender threads, and the
  // in-place chunk reduction under TSan.
  for (int round = 0; round < 3; round++) {
    std::vector<float> v(1000);
    for (int i = 0; i < 1000; i++) v[i] = float(rank) + float(i);
    Request req;
    req.rank = rank;
    req.type = ReqType::kAllreduce;
    req.dtype = DType::kF32;
    req.red_op = RedOp::kSum;
    req.shape = {1000};
    std::string name = "ring.big." + std::to_string(round);
    req.name = name;
    req.payload = F32Payload(v);
    if (!client.Submit(std::move(req))) failures->fetch_add(1);
    Response resp;
    if (client.Wait(name, &resp) != 0 ||
        resp.type != hvdcoord::RespType::kAllreduce ||
        resp.payload.size() != 4000) {
      failures->fetch_add(1);
    } else {
      const float* out =
          reinterpret_cast<const float*>(resp.payload.data());
      float rsum = 0.f;
      for (int r = 0; r < kSize; r++) rsum += float(r);
      for (int i : {0, 333, 334, 666, 667, 999}) {
        if (std::fabs(out[i] - (rsum + kSize * float(i))) > 1e-3) {
          failures->fetch_add(1);
          break;
        }
      }
    }
  }
  // Ring allgather round: RAGGED first dims (rank r contributes r+1
  // rows of 4 floats) circulate the ring; result must be the rank-order
  // concatenation.
  {
    int rows = (rank + 1) * 4;  // 64..192 B blocks: all above threshold
    std::vector<float> v(rows * 4);
    for (int i = 0; i < rows * 4; i++) v[i] = rank * 1000.f + i;
    Request req;
    req.rank = rank;
    req.type = ReqType::kAllgather;
    req.dtype = DType::kF32;
    req.shape = {rows, 4};
    req.name = "ring.gather";
    req.payload = F32Payload(v);
    if (!client.Submit(std::move(req))) failures->fetch_add(1);
    Response resp;
    if (client.Wait("ring.gather", &resp) != 0 ||
        resp.type != hvdcoord::RespType::kAllgather) {
      failures->fetch_add(1);
    } else {
      size_t total_elems = 0;
      for (int r2 = 0; r2 < kSize; r2++) total_elems += (r2 + 1) * 16;
      if (resp.payload.size() != total_elems * 4) {
        failures->fetch_add(1);
      } else {
        const float* out =
            reinterpret_cast<const float*>(resp.payload.data());
        size_t offset = 0;
        bool ok = true;
        for (int r2 = 0; r2 < kSize; r2++) {
          for (int i = 0; i < (r2 + 1) * 16; i++)
            ok = ok &&
                 std::fabs(out[offset + i] - (r2 * 1000.f + i)) < 1e-6;
          offset += (r2 + 1) * 16;
        }
        if (!ok) failures->fetch_add(1);
      }
    }
  }
  // Large STAR round (plane forced): a 256 KiB payload through the
  // coordinator's host reduction exercises ReduceAllStriped across
  // stripe boundaries (set HOROVOD_COORD_REDUCE_THREADS>1 + TSan to
  // race-check the striped path; 1-core hosts run it serial).
  {
    const int n = 65536;
    std::vector<float> v(n);
    for (int i = 0; i < n; i++) v[i] = float(rank + 1) * float(i % 97);
    Request req;
    req.rank = rank;
    req.type = ReqType::kAllreduce;
    req.dtype = DType::kF32;
    req.red_op = RedOp::kSum;
    req.shape = {n};
    req.name = "star.big";
    req.payload = F32Payload(v);
    if (!client.Submit(std::move(req), /*flags=*/1)) failures->fetch_add(1);
    Response resp;
    if (client.Wait("star.big", &resp) != 0 ||
        resp.payload.size() != size_t(n) * 4) {
      failures->fetch_add(1);
    } else {
      const float* out =
          reinterpret_cast<const float*>(resp.payload.data());
      float scale = 0.f;
      for (int r = 0; r < kSize; r++) scale += float(r + 1);
      for (int i : {0, 1, 21845, 21846, 43690, 43691, 65535}) {
        if (std::fabs(out[i] - scale * float(i % 97)) > 1e-2) {
          failures->fetch_add(1);
          break;
        }
      }
    }
  }
  if (client.ring_ops() != 4) failures->fetch_add(1);
  // Bandwidth optimality: each ring allreduce moves 2*(N-1)/N * payload
  // per rank (up to one element of chunk-remainder skew per send); the
  // gather round sends exactly its two forwarded blocks.
  long long expect = 3LL * 2 * (kSize - 1) * 4000 / kSize +
                     64LL * (rank + 1) +
                     64LL * (((rank - 1 + kSize) % kSize) + 1);
  long long sent = client.ring_bytes_sent();
  if (sent < expect - 64 || sent > expect + 64) {
    fprintf(stderr, "rank %d: ring bytes %lld !~ %lld\n", rank, sent,
            expect);
    failures->fetch_add(1);
  }

  client.Shutdown();
}

}  // namespace

int main() {
  setenv("HOROVOD_RING_THRESHOLD", "64", 1);  // ring the 4 KB round
  std::atomic<int> failures{0};
  Coordinator coordinator(kSize, kPort, 64 << 20, 60.0, "");
  if (!coordinator.ok()) {
    fprintf(stderr, "coordinator bind failed\n");
    return 2;
  }
  std::vector<std::thread> ranks;
  for (int r = 0; r < kSize; r++)
    ranks.emplace_back(RankMain, r, &failures);
  for (auto& t : ranks) t.join();
  if (failures.load() != 0) {
    fprintf(stderr, "SELFTEST FAILED: %d failures\n", failures.load());
    return 1;
  }
  printf("hvdcoord selftest OK (%d ranks x %d threads x %d ops + burst + "
         "error round + ring rounds)\n",
         kSize, kThreadsPerRank, kOpsPerThread);
  return 0;
}
