"""Host coordination client — the multi-process eager control/data plane.

This is the TPU-native analog of the reference's background-thread MPI
negotiation (``BackgroundThreadLoop``, ``mpi_ops.cc:1248-1512``): name-keyed
Request/Response messages to a rank-0 coordinator over DCN/TCP, cross-rank
validation with the reference's error taxonomy, stall detection, and host-side
execution of eager op-at-a-time collectives.

Implemented in ``horovod_tpu/coord/`` (C++ core + this Python binding).
"""

from __future__ import annotations


class CoordClient:
    """Placeholder until the native coordination core lands.

    Compiled collectives (``shard_map`` over the global mesh) already span
    processes via XLA — only the *eager* op-at-a-time API needs this plane.
    ``init(coordinator=False)`` disables it explicitly.
    """

    @classmethod
    def from_env(cls, rank: int, size: int, timeline=None) -> "CoordClient":
        raise NotImplementedError(
            "the multi-process eager coordination plane is not built yet; "
            "compiled collectives (shard_map over the world mesh) already "
            "span processes — pass init(coordinator=False) to proceed "
            "without eager op-at-a-time collectives")
