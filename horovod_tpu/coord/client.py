"""Host coordination client — Python binding over the native core.

This is the TPU-native analog of the reference's background-thread MPI
negotiation (``BackgroundThreadLoop``, ``mpi_ops.cc:1248-1512``): name-keyed
Request/Response messages to a rank-0 coordinator over DCN/TCP, cross-rank
validation with the reference's error taxonomy (``ConstructMPIResponse``,
``mpi_ops.cc:266-474``), stall detection, tensor-fusion response batching and
host-side execution of eager op-at-a-time collectives. The native core lives
in ``coordinator.cc`` (built lazily into ``libhvdcoord.so``); this module is
the ctypes binding (parity: ``mpi_ops.py:68-124`` loads the native lib via
ctypes with a thin wrapper).

Only the *eager* op-at-a-time API (metrics, epoch broadcast, init-time weight
sync) uses this plane. Compiled collectives (``shard_map`` over the global
mesh) span processes via XLA itself.
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import subprocess
from typing import NamedTuple, Optional

import numpy as np

from ..exceptions import (FailedPreconditionError, StalledError,
                          TransportError, WorkerFailureError)
from ..testing import faults as _faults
from ..utils import config as _config


class PendingResize(NamedTuple):
    """A live resize announced by the coordinator (v7 admin plane)."""

    target_world: int   # new world size the job must quiesce into
    coord_port: int     # coordinator port reserved for the NEW world
    generation: int     # monotonically increasing resize counter


# ---------------------------------------------------------------------------
# Admin RPC (v7) — pure-socket client, deliberately ctypes-free so the
# supervising tpurun (which must not load jax OR build the native core) and
# one-line operator invocations can speak it. The wire format mirrors
# coordinator.cc: 8-byte native-order length prefix, then
# {u8 kResizeRequest, i32 target}; reply {u8 kResizeReply, u8 ok, str msg,
# i32 world, i32 pending_target, i32 new_port, i32 generation} where str is
# {i64 len, bytes}.
# ---------------------------------------------------------------------------

_MSG_RESIZE_REQUEST = 7
_MSG_RESIZE_REPLY = 8


def _admin_rpc(addr: str, target: int, timeout: float) -> dict:
    import time as _time
    host, _, port_s = addr.partition(":")
    port = int(port_s) if port_s else 29521
    # The timeout is a WALL-CLOCK budget for the whole exchange, not a
    # per-recv bound — a foreign process that re-bound the polled port
    # must not be able to park the supervisor by dripping one byte per
    # second inside a per-recv window.
    deadline = _time.monotonic() + timeout

    def _recv_exact(s, n, what):
        buf = b""
        while len(buf) < n:
            left = deadline - _time.monotonic()
            if left <= 0:
                raise TransportError(
                    f"admin exchange with {addr} exceeded its {timeout}s "
                    f"budget while reading the {what}")
            s.settimeout(min(left, timeout))
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise TransportError(
                    f"coordinator at {addr} closed the admin connection "
                    f"while sending the {what}")
            buf += chunk
        return buf

    with socket.create_connection((host or "127.0.0.1", port),
                                  timeout=timeout) as s:
        body = struct.pack("<Bi", _MSG_RESIZE_REQUEST, int(target))
        s.sendall(struct.pack("<Q", len(body)) + body)
        (length,) = struct.unpack("<Q", _recv_exact(s, 8, "length prefix"))
        if length > 4096:
            # Mirror the server's admin frame cap.
            raise TransportError(
                f"oversized admin reply ({length} bytes) from {addr} — "
                f"not a horovod_tpu coordinator?")
        reply = _recv_exact(s, length, "reply frame")
    # Parse defensively: the reply may come from a foreign process that
    # re-bound the port, or be truncated — surface the documented
    # TransportError, never a bare struct.error or garbage field values.
    try:
        tag, ok = struct.unpack_from("<BB", reply, 0)
        if tag != _MSG_RESIZE_REPLY:
            raise TransportError(
                f"unexpected admin reply tag {tag} from {addr} (mixed "
                f"horovod_tpu builds? the admin plane is protocol v7+)")
        (msg_len,) = struct.unpack_from("<q", reply, 2)
        off = 10
        if msg_len < 0 or off + msg_len + 16 > len(reply):
            raise TransportError(
                f"malformed admin reply from {addr} (message length "
                f"{msg_len} does not fit the {len(reply)}-byte frame)")
        msg = reply[off:off + msg_len].decode(errors="replace")
        off += msg_len
        world, pending, new_port, generation = struct.unpack_from(
            "<iiii", reply, off)
    except struct.error as e:
        raise TransportError(
            f"truncated admin reply from coordinator at {addr}: {e}"
        ) from None
    return {"ok": bool(ok), "message": msg, "world": world,
            "pending_target": pending, "coord_port": new_port,
            "generation": generation}


def resize_status(addr: str, *, timeout: float = 5.0,
                  supervisor: bool = False) -> dict:
    """Query the coordinator's world size and pending resize (if any).

    Returns ``{"world": N, "pending_target": K-or-0, "coord_port": P,
    "generation": G, ...}``. Raises :class:`TransportError`/``OSError``
    when the coordinator is unreachable (callers that poll — tpurun's
    supervision loop — treat that as "not ready, retry").

    ``supervisor=True`` marks the query as the SUPERVISING launcher's
    poll: it releases the coordinator's teardown-handoff linger (the
    pending-resize triple has reached the party that spawns grow ranks).
    Operator/observability queries must leave it False."""
    return _admin_rpc(addr, -1 if supervisor else 0, timeout)


def request_resize(addr: str, target_world: int, *,
                   timeout: float = 10.0) -> dict:
    """Ask the running world at ``addr`` to resize itself to
    ``target_world`` ranks — the operator/admin ingress of the live-resize
    plane (``docs/fault_tolerance.md``). Idempotent for the same target;
    raises :class:`TransportError` when the coordinator refuses (resize to
    a different size already pending, target == current size, ...).

    One-liner for operators::

        python -c "from horovod_tpu.coord.client import request_resize; \\
                   print(request_resize('127.0.0.1:29521', 2))"
    """
    if int(target_world) < 1:
        raise ValueError(
            f"resize target must be >= 1 rank, got {target_world}")
    out = _admin_rpc(addr, int(target_world), timeout)
    if not out["ok"]:
        raise TransportError(
            f"coordinator at {addr} refused resize to {target_world}: "
            f"{out['message']}")
    return out

_REQ_TYPES = {"allreduce": 0, "allgather": 1, "broadcast": 2,
              "alltoall": 3, "reducescatter": 4}

# numpy dtype -> wire enum (coordinator.cc DType; the reference's nine dtypes
# of mpi_message.h:26-36 plus bfloat16).
_DTYPES = {
    "uint8": 0, "int8": 1, "uint16": 2, "int16": 3, "int32": 4,
    "int64": 5, "float32": 6, "float64": 7, "bool": 8, "bfloat16": 9,
}


def _build_and_load() -> ctypes.CDLL:
    here = os.path.dirname(os.path.abspath(__file__))
    so = os.path.join(here, "libhvdcoord.so")
    src = os.path.join(here, "coordinator.cc")
    if not os.path.exists(so) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(so)):
        # Concurrently launched ranks all reach this on a fresh checkout;
        # serialize the build with an exclusive lock so nobody dlopens a
        # half-written .so.
        import fcntl
        with open(os.path.join(here, ".build.lock"), "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                if not os.path.exists(so) or (
                        os.path.exists(src)
                        and os.path.getmtime(src) > os.path.getmtime(so)):
                    subprocess.run(["make", "-C", here], check=True,
                                   capture_output=True, text=True)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
    lib = ctypes.CDLL(so)
    lib.hvdcoord_init.restype = ctypes.c_int
    lib.hvdcoord_init.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_longlong, ctypes.c_double, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_int]
    lib.hvdcoord_submit.restype = ctypes.c_int
    lib.hvdcoord_submit.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int]
    lib.hvdcoord_wait.restype = ctypes.c_int
    lib.hvdcoord_wait.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_char_p, ctypes.c_int]
    lib.hvdcoord_free.argtypes = [ctypes.c_void_p]
    lib.hvdcoord_shutdown.restype = None
    lib.hvdcoord_responses_received.restype = ctypes.c_longlong
    lib.hvdcoord_responses_received.argtypes = []
    lib.hvdcoord_ops_completed.restype = ctypes.c_longlong
    lib.hvdcoord_ops_completed.argtypes = []
    lib.hvdcoord_ring_ops.restype = ctypes.c_longlong
    lib.hvdcoord_ring_ops.argtypes = []
    lib.hvdcoord_ring_bytes_sent.restype = ctypes.c_longlong
    lib.hvdcoord_ring_bytes_sent.argtypes = []
    # Liveness-plane fault-injection/observability hooks (v6).
    lib.hvdcoord_mute_heartbeats.restype = None
    lib.hvdcoord_mute_heartbeats.argtypes = [ctypes.c_int]
    lib.hvdcoord_coord_mute_acks.restype = None
    lib.hvdcoord_coord_mute_acks.argtypes = [ctypes.c_int]
    lib.hvdcoord_aborted.restype = ctypes.c_int
    lib.hvdcoord_aborted.argtypes = []
    # Live-resize plane (v7).
    lib.hvdcoord_pending_resize.restype = ctypes.c_int
    lib.hvdcoord_pending_resize.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    return lib


class CoordClient:
    """Per-process handle on the coordination plane."""

    def __init__(self, rank: int, size: int, host: str, port: int,
                 timeline=None):
        self._lib = _build_and_load()
        self.rank = rank
        self.size = size
        tl_path = _config.timeline_path() if rank == 0 else None
        err = ctypes.create_string_buffer(1024)
        rc = self._lib.hvdcoord_init(
            rank, size, host.encode(), port,
            _config.fusion_threshold_bytes(),
            _config.stall_warning_secs(),
            tl_path.encode() if tl_path else None, err, len(err))
        if rc != 0:
            detail = err.value.decode() or f"rc={rc}"
            raise TransportError(
                f"coordination plane init failed (rank {rank}, "
                f"{host}:{port}): {detail}")
        # Names currently announced-but-unwaited by THIS rank. The
        # coordinator drops duplicate same-rank announcements of an
        # in-flight name (Ingest), so a second submit under the same name
        # would wait forever; fail fast here instead.
        self._inflight: set = set()
        # Names whose wait raised StalledError: still half-announced at
        # the coordinator, permanently unusable for resubmission.
        self._stalled: set = set()
        # The coordinator (not Python) writes the timeline in coord mode.
        self.timeline = None

    @classmethod
    def from_env(cls, rank: int, size: int, timeline=None) -> "CoordClient":
        addr = _config.coordinator_address()
        if addr is None:
            raise TransportError(
                "multi-process world without HVD_COORD_ADDR; launch via "
                "tpurun or set HVD_COORD_ADDR=host:port")
        host, _, port_s = addr.partition(":")
        try:
            port = int(port_s) if port_s else 29521
        except ValueError:
            raise ValueError(
                f"malformed HVD_COORD_ADDR {addr!r}: the port part "
                f"{port_s!r} is not an integer (expected host:port, e.g. "
                f"10.0.0.1:29521)") from None
        if not 1 <= port <= 65535:
            raise ValueError(
                f"malformed HVD_COORD_ADDR {addr!r}: port {port} outside "
                f"1-65535")
        return cls(rank, size, host or "127.0.0.1", port,
                   timeline=timeline)

    # -- eager collectives -------------------------------------------------
    def collective(self, kind: str, x, name: str, *, op=None, root_rank=0,
                   plane: str = "auto"):
        """Run one named eager collective through the host plane.

        Semantics parity: eager ``hvd.allreduce/allgather/broadcast(value)``
        (``horovod/keras/__init__.py:90-144``); errors surface as
        FailedPreconditionError (``mpi_ops.cc:1141-1148``).
        """
        return self.wait(self.submit(kind, x, name, op=op,
                                     root_rank=root_rank, plane=plane))

    def submit(self, kind: str, x, name: str, *, op=None,
               root_rank=0, plane: str = "auto") -> "CoordHandle":
        """Non-blocking announce+send (the reference's ``ComputeAsync`` +
        ``EnqueueTensor*`` model, ``mpi_ops.cc:1752-1772``): many submits can
        be in flight at once, which is what feeds coordinator-side response
        fusion. Complete with :meth:`wait`.

        ``plane`` is the per-call placement override, the analog of the
        reference's per-call ``device_dense=``/``device_sparse=`` knobs
        (``horovod/tensorflow/__init__.py:43-55``): ``"auto"`` lets
        ``HOROVOD_RING_THRESHOLD`` elect, ``"star"`` forces the coordinator
        star, ``"ring"`` forces the client-to-client peer plane (must agree
        across ranks; a non-root broadcast always announces star — the root
        alone elects the plane)."""
        from ..ops.collectives import Op

        arr = np.asarray(x)
        average = False
        red_op = 0
        if kind in ("allreduce", "reducescatter"):
            resolved = op if op is not None else Op.SUM
            average = resolved is Op.AVERAGE
            red_op = {Op.SUM: 0, Op.AVERAGE: 0, Op.MIN: 1, Op.MAX: 2,
                      Op.PRODUCT: 3}[resolved]
        dtype_name = arr.dtype.name
        if dtype_name not in _DTYPES:
            raise TypeError(f"unsupported dtype {dtype_name} for eager "
                            f"coordination-plane collective")

        if name in self._stalled:
            # The earlier collective under this name timed out
            # (HOROVOD_STALL_TIMEOUT) but is STILL half-announced at the
            # coordinator; re-announcing would be silently dropped as a
            # duplicate and could pair this step's payload on other ranks
            # with our stale one. Fail fast with the reason.
            raise ValueError(
                f"tensor name {name!r} previously raised StalledError and "
                f"is still pending at the coordinator; a stalled "
                f"collective cannot be retried under the same name — use "
                f"a fresh name (name=None auto-names)")
        if name in self._inflight:
            raise ValueError(
                f"tensor name {name!r} is already in flight on rank "
                f"{self.rank}; synchronize() the first handle before "
                f"reusing the name (or pass name=None for auto-naming)")

        planes = {"auto": 0, "star": 1, "ring": 2}
        if plane not in planes:
            raise ValueError(f"plane must be one of {sorted(planes)}, "
                             f"got {plane!r}")

        # Deterministic fault injection (HVD_FAULT_SPEC coord:delay_ms=N):
        # no-op unless the spec targets the coordination plane.
        _faults.coord_delay()

        send_payload = not (kind == "broadcast" and self.rank != root_rank)
        data = np.ascontiguousarray(arr) if send_payload else None

        shape = (ctypes.c_longlong * max(arr.ndim, 1))(*arr.shape)
        err = ctypes.create_string_buffer(4096)
        rc = self._lib.hvdcoord_submit(
            name.encode(), _REQ_TYPES[kind], _DTYPES[dtype_name], red_op,
            root_rank, arr.ndim, shape,
            data.ctypes.data if data is not None else None,
            data.nbytes if data is not None else 0, planes[plane],
            err, len(err))
        if rc == 4:
            # World already aborted (a rank or the coordinator died):
            # fail fast with the original diagnosis instead of feeding a
            # dead coordinator and hanging in wait.
            raise WorkerFailureError(self._abort_record(err.value.decode()))
        if rc != 0:
            raise TransportError(err.value.decode())
        self._inflight.add(name)
        return CoordHandle(self, kind, name, tuple(arr.shape), arr.dtype,
                           average)

    def wait(self, handle: "CoordHandle"):
        """Block until ``handle``'s collective completes; returns the result
        (out-of-order safe — any in-flight handle may be waited first)."""
        import jax.numpy as jnp

        if handle._result is not None:
            return handle._result
        out = ctypes.c_void_p()
        out_nbytes = ctypes.c_longlong()
        sizes = (ctypes.c_longlong * self.size)()
        err = ctypes.create_string_buffer(4096)
        try:
            rc = self._lib.hvdcoord_wait(
                handle.name.encode(), ctypes.byref(out),
                ctypes.byref(out_nbytes), sizes, err, len(err))
        finally:
            self._inflight.discard(handle.name)
        if rc == 1:
            raise FailedPreconditionError(err.value.decode())
        if rc == 3:
            # HOROVOD_STALL_TIMEOUT strict mode (the reference only warns,
            # mpi_ops.cc:1153-1196; the hard deadline is a TPU-era extra).
            self._stalled.add(handle.name)
            raise StalledError(err.value.decode())
        if rc == 4:
            # World abort: a rank died / went silent (or the coordinator
            # did). The message names the dead party; the collective can
            # never complete — recovery is a world restart
            # (tpurun --restarts + horovod_tpu.elastic).
            raise WorkerFailureError(self._abort_record(err.value.decode()))
        if rc != 0:
            raise TransportError(err.value.decode())

        raw = ctypes.string_at(out.value, out_nbytes.value)
        self._lib.hvdcoord_free(out)
        result = np.frombuffer(raw, dtype=handle.dtype)

        kind, shape = handle.kind, handle.shape
        if kind == "allreduce":
            result = result.reshape(shape)
            if handle.average:
                # True division; integers promote to float exactly as the
                # compiled plane's lax.pmean does (jnp.asarray then applies
                # the session's x64 policy, so both planes agree bit-for-bit
                # on dtype).
                result = result / self.size
        elif kind == "allgather":
            total_rows = int(sum(sizes[i] for i in range(self.size)))
            result = result.reshape((total_rows,) + tuple(shape[1:]))
        elif kind == "alltoall":
            result = result.reshape(shape)
        elif kind == "reducescatter":
            result = result.reshape((shape[0] // self.size,)
                                    + tuple(shape[1:]))
            if handle.average:
                result = result / self.size
        else:  # broadcast
            result = result.reshape(shape)
        handle._result = jnp.asarray(result)
        return handle._result

    # -- fusion observability (fused-path test support, the analog of the
    # reference's deliberately-fused mpi_ops_test.py:116-148) ---------------
    def responses_received(self) -> int:
        return int(self._lib.hvdcoord_responses_received())

    def ops_completed(self) -> int:
        return int(self._lib.hvdcoord_ops_completed())

    # -- ring-plane observability (large allreduces ride a client-to-client
    # chunked ring, 2·(N-1)/N bytes/rank — the byte-accounting test's
    # evidence; threshold: HOROVOD_RING_THRESHOLD) ------------------------
    def ring_ops(self) -> int:
        return int(self._lib.hvdcoord_ring_ops())

    def ring_bytes_sent(self) -> int:
        return int(self._lib.hvdcoord_ring_bytes_sent())

    # -- liveness plane (fault injection + observability) -----------------
    def aborted(self) -> bool:
        """Whether the world has aborted (a rank or the coordinator died)."""
        return bool(self._lib.hvdcoord_aborted())

    def _abort_record(self, msg: str) -> str:
        """Leave this rank's post-mortem the moment a world ABORT
        surfaces: one ``abort`` flight-recorder event plus a dump of the
        ring (``hvd_flightrec.rank{N}.json``, :mod:`horovod_tpu.obs.
        flightrec`) — every SURVIVING rank of a dead world records the
        diagnosis (the message names the dead party) and its own last
        completed step, so an operator reads files, not scrollback.
        Returns ``msg`` unchanged so the raise sites stay one-liners;
        repeated aborts just overwrite the dump (last record wins)."""
        try:
            from ..obs import flightrec
            flightrec.record("abort", rank=self.rank, error=msg)
            flightrec.dump(reason=f"coordinator abort: {msg}",
                           rank=self.rank)
        except Exception:  # noqa: BLE001 — never mask the abort itself
            pass
        return msg

    def mute_heartbeats(self, mute: bool = True) -> None:
        """Fault hook: stop this rank's heartbeats while the process (and
        its socket) stays alive — the coordinator must detect the silence
        after ``HVD_HEARTBEAT_TIMEOUT`` and abort the world."""
        self._lib.hvdcoord_mute_heartbeats(1 if mute else 0)

    def mute_coordinator_acks(self, mute: bool = True) -> None:
        """Fault hook (rank 0 only): stop the coordinator's heartbeat-acks
        so every client independently detects a dead coordinator."""
        self._lib.hvdcoord_coord_mute_acks(1 if mute else 0)

    def pending_resize(self) -> Optional["PendingResize"]:
        """The live resize announced by the coordinator, if one is pending
        (v7 admin plane): ``(target_world, coord_port, generation)``, or
        ``None``. One atomic load — cheap enough to poll at every training
        step boundary (the quiesce ingress of
        :class:`horovod_tpu.elastic.ResizeCoordinator`)."""
        t = ctypes.c_int(0)
        p = ctypes.c_int(0)
        gen = ctypes.c_int(0)
        if not self._lib.hvdcoord_pending_resize(
                ctypes.byref(t), ctypes.byref(p), ctypes.byref(gen)):
            return None
        return PendingResize(target_world=int(t.value),
                             coord_port=int(p.value),
                             generation=int(gen.value))

    def shutdown(self):
        self._lib.hvdcoord_shutdown()


class CoordHandle:
    """In-flight eager collective (async API, reference ``ComputeAsync``
    callback model). Obtain via :meth:`CoordClient.submit`; redeem with
    :meth:`CoordClient.wait` (or ``horovod_tpu.synchronize``)."""

    def __init__(self, client: CoordClient, kind: str, name: str,
                 shape: tuple, dtype, average: bool):
        self.client = client
        self.kind = kind
        self.name = name
        self.shape = shape
        self.dtype = dtype
        self.average = average
        self._result = None
