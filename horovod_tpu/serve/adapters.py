"""Multi-tenant adapter lifecycle: a fixed-capacity stacked device table.

The model layer (:mod:`horovod_tpu.parallel.lora`) makes tenant identity
*data* — a per-slot ``adapter_idx`` gathering rows of stacked
``[capacity, ...]`` LoRA tables inside the one compiled decode program.
This module owns everything around that table the compiled program must
never see change shape:

* **Fixed capacity.** The table is allocated ONCE at
  ``[capacity, ...]`` — capacity is the only compile-relevant number.
  Loading tenant #37 into a free row is a data update; it never
  recompiles anything (the compile-cache pin tests/test_adapters.py
  holds).
* **Hot-load at a step boundary, never mid-step.** A load stages the
  adapter host-side, then swaps the row in by building a NEW table tree
  (``leaf.at[row].set(...)`` — jax arrays are immutable). The engine
  loop reads :meth:`AdapterRegistry.table` afresh at every
  prefill/decode invocation, i.e. at a decode-step boundary; a step
  already in flight keeps the OLD buffers, so a swap can never tear a
  step, and the next step sees the whole new row or none of it.
* **Evict refuses while referenced** — the
  :class:`~horovod_tpu.parallel.kv_blocks.BlockManager` refcount
  discipline. Every admitted request retains its adapter's row
  (submit-time, released when the stream finishes or fails), so a row a
  live stream gathers from can never be freed and overwritten under it.
  ``evict`` of a referenced adapter raises instead; drain the tenant
  first.
* **Per-tenant admission quotas.** ``quota(name)`` caps a tenant's
  in-flight streams (queued + decoding); the engine rejects over-quota
  submits with the ``tenant_quota`` reason, split from
  ``slots_full``/``blocks_exhausted`` exactly as PR 11 split those —
  an operator must see WHICH resource a tenant exhausted. ``"base"``
  (no adapter) is a quotable tenant too.
* **Per-tenant scheduling policy.** ``weight(name)`` /
  ``priority(name)`` / ``slo_ttft_ms(name)`` carry the fair-scheduling
  plane's knobs (:mod:`horovod_tpu.serve.sched`): the DRR share, the
  strict priority class (preemption-grade), and the TTFT target the
  ``hvd_tenant_slo_*`` burn series measure against. All follow the
  quota discipline — settable for ``"base"`` too, registry values
  override ``GenerationConfig`` defaults, and changes apply at the
  next admission (policy is data, never a compile key).

Weights come from anywhere that yields the
``parallel.lora.init_adapter`` tree shape — typically
``parallel.checkpoint.restore_adapter`` (manifest-CRC-verified), so a
rotted fine-tune fails its load loudly and the base model keeps serving.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.lora import (LoraConfig, check_adapter,
                             check_adapter_name, empty_adapter_table)
from ..parallel.transformer import TransformerConfig


class AdapterRegistry:
    """Name → table-row bookkeeping over one stacked LoRA device table.

    Args:
      model_cfg: the base :class:`~horovod_tpu.parallel.transformer.
        TransformerConfig` the adapters fine-tune.
      lora: the :class:`~horovod_tpu.parallel.lora.LoraConfig` every
        loaded adapter must match (rank/alpha/targets are table shape).
      capacity: table rows — the max adapters resident at once. Compile
        surface: pick for the tenant working set, not the tenant count
        (cold tenants hot-load on demand).

    Thread-safe; the swap itself runs under the lock (adapter rows are
    tiny — microseconds of dispatch).
    """

    def __init__(self, model_cfg: TransformerConfig, lora: LoraConfig,
                 capacity: int = 8):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._model_cfg = model_cfg
        self._lora = lora
        self._capacity = int(capacity)
        self._table = empty_adapter_table(model_cfg, lora, capacity)
        self._lock = threading.Lock()
        self._names: Dict[str, int] = {}
        self._ref = np.zeros(self._capacity, np.int64)
        self._free: List[int] = list(range(self._capacity - 1, -1, -1))
        self._quotas: Dict[str, Optional[int]] = {}
        # Fair-scheduling policy (serve/sched.py): DRR weights, strict
        # priority classes, and per-tenant TTFT SLO targets. Absent =
        # the engine's GenerationConfig default (weight 1.0, priority
        # 0, no SLO target).
        self._weights: Dict[str, float] = {}
        self._priorities: Dict[str, int] = {}
        self._slo_ttft: Dict[str, float] = {}
        # Monotone per-name load generation: bumped on EVERY load (fresh
        # and hot-reload) and never reset by evict — the engine salts
        # its prefix-reuse registry keys with (name, generation), so a
        # new adapter loaded under a recycled name can never hit KV
        # prefixes its predecessor wrote.
        self._gens: Dict[str, int] = {}
        self._loads_total = 0
        self._evictions_total = 0
        # Fired (outside the lock) after an evict commits: the owning
        # engine folds the tenant's metric state so tenant churn cannot
        # grow per-tenant recorders/series without bound.
        self._evict_listeners: List[Callable[[str], None]] = []

    # -- properties --------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def lora(self) -> LoraConfig:
        return self._lora

    @property
    def model_cfg(self) -> TransformerConfig:
        return self._model_cfg

    def table(self) -> Any:
        """The current stacked device table (an immutable tree — pass it
        straight into the compiled prefill/decode; a concurrent load
        publishes a NEW tree, it never mutates this one)."""
        with self._lock:
            return self._table

    def resident(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(sorted(self._names))

    def index_of(self, name: str) -> Optional[int]:
        with self._lock:
            return self._names.get(name)

    # -- load / evict ------------------------------------------------------

    def load(self, name: str, adapter: Any,
             quota: Optional[int] = None,
             weight: Optional[float] = None,
             priority: Optional[int] = None,
             slo_ttft_ms: Optional[float] = None) -> int:
        """Stage ``adapter`` and swap it into a table row; returns the
        row index. Re-loading a resident name hot-reloads its weights in
        place — refused (``RuntimeError``) while any live stream
        references the row, for the same reason evict refuses: a
        mid-stream weight change would fork the tenant's stream. A full
        table raises ``ValueError`` naming the capacity.
        ``quota``/``weight``/``priority``/``slo_ttft_ms`` set the
        tenant's admission and scheduling policy in the same call
        (``None`` leaves each unset — see the ``set_*`` methods)."""
        check_adapter_name(name)
        if weight is not None and weight <= 0:
            raise ValueError(
                f"scheduling weight must be > 0 or None, got {weight}")
        if slo_ttft_ms is not None and slo_ttft_ms <= 0:
            raise ValueError(
                f"slo_ttft_ms must be > 0 or None, got {slo_ttft_ms}")
        check_adapter(adapter, self._model_cfg, self._lora)
        staged = jax.tree_util.tree_map(np.asarray, adapter)
        with self._lock:
            row = self._names.get(name)
            if row is not None:
                if self._ref[row] > 0:
                    raise RuntimeError(
                        f"adapter {name!r} is referenced by "
                        f"{int(self._ref[row])} live stream(s) — a "
                        f"hot-reload would change tokens mid-stream; "
                        f"drain the tenant first")
            else:
                if not self._free:
                    raise ValueError(
                        f"adapter table full ({self._capacity} rows, "
                        f"resident: {sorted(self._names)}) — evict an "
                        f"idle adapter or raise capacity")
                row = self._free.pop()
            self._table = jax.tree_util.tree_map(
                lambda t, a: t.at[row].set(jnp.asarray(a, t.dtype)),
                self._table, staged)
            self._names[name] = row
            self._gens[name] = self._gens.get(name, 0) + 1
            if quota is not None:
                self._quotas[name] = int(quota)
            if weight is not None:
                self._weights[name] = float(weight)
            if priority is not None:
                self._priorities[name] = int(priority)
            if slo_ttft_ms is not None:
                self._slo_ttft[name] = float(slo_ttft_ms)
            self._loads_total += 1
            return row

    def evict(self, name: str) -> None:
        """Free ``name``'s row for a future load. Refuses
        (``RuntimeError``) while any live stream references the row —
        the BlockManager discipline: a row is reusable only at
        refcount 0. The row's bytes are left in place; nothing gathers
        from an unnamed row, and the next load overwrites it."""
        with self._lock:
            row = self._names.get(name)
            if row is None:
                raise ValueError(
                    f"no adapter {name!r} resident "
                    f"(resident: {sorted(self._names)})")
            if self._ref[row] > 0:
                raise RuntimeError(
                    f"adapter {name!r} is referenced by "
                    f"{int(self._ref[row])} live stream(s) — refusing to "
                    f"evict; drain the tenant first")
            del self._names[name]
            self._quotas.pop(name, None)
            self._weights.pop(name, None)
            self._priorities.pop(name, None)
            self._slo_ttft.pop(name, None)
            self._free.append(row)
            self._evictions_total += 1
            listeners = list(self._evict_listeners)
        for fn in listeners:
            try:
                fn(name)
            except Exception:  # noqa: BLE001 — cleanup must not fail evict
                pass

    def add_evict_listener(self, fn: Callable[[str], None]) -> None:
        """Register a post-evict callback (called with the evicted name,
        outside the registry lock) — the engine's metric-folding hook."""
        with self._lock:
            self._evict_listeners.append(fn)

    def remove_evict_listener(self, fn: Callable[[str], None]) -> None:
        """Unhook a listener (idempotent) — engines unhook at shutdown
        so a registry SHARED across replicas does not accumulate
        callbacks bound to retired engines' metrics."""
        with self._lock:
            try:
                self._evict_listeners.remove(fn)
            except ValueError:
                pass

    # -- stream references -------------------------------------------------

    def retain(self, name: str) -> int:
        """One more live-stream reference on ``name``'s row (called at
        admission); returns the row index the stream's ``adapter_idx``
        uses for its whole lifetime."""
        with self._lock:
            row = self._names.get(name)
            if row is None:
                raise ValueError(
                    f"adapter {name!r} is not resident (resident: "
                    f"{sorted(self._names)}) — load() it first")
            self._ref[row] += 1
            return row

    def release(self, name: str) -> None:
        """Drop one stream reference (stream finished or failed)."""
        with self._lock:
            row = self._names.get(name)
            if row is None or self._ref[row] <= 0:
                raise RuntimeError(
                    f"release of unretained adapter {name!r}")
            self._ref[row] -= 1

    def refcount(self, name: str) -> int:
        with self._lock:
            row = self._names.get(name)
            return int(self._ref[row]) if row is not None else 0

    def generation(self, name: str) -> int:
        """How many times ``name`` has been loaded (any weights) —
        stable for a stream's lifetime once its row is retained (a
        reload is refused while referenced), which is what makes it a
        sound prefix-reuse salt component."""
        with self._lock:
            if name not in self._names:
                raise ValueError(
                    f"adapter {name!r} is not resident (resident: "
                    f"{sorted(self._names)})")
            return self._gens[name]

    # -- quotas ------------------------------------------------------------

    def quota(self, tenant: str) -> Optional[int]:
        """Max in-flight streams for ``tenant`` (``None`` = unlimited).
        ``"base"`` is a valid tenant — base traffic can be capped too."""
        with self._lock:
            return self._quotas.get(tenant)

    def set_quota(self, tenant: str, quota: Optional[int]) -> None:
        if quota is not None and quota < 1:
            raise ValueError(f"quota must be >= 1 or None, got {quota}")
        with self._lock:
            if quota is None:
                self._quotas.pop(tenant, None)
            else:
                self._quotas[tenant] = int(quota)

    # -- scheduling policy ---------------------------------------------------

    def weight(self, tenant: str) -> Optional[float]:
        """DRR scheduling weight for ``tenant`` (``None`` = the engine
        default, 1.0). ``"base"`` is schedulable like any adapter."""
        with self._lock:
            return self._weights.get(tenant)

    def set_weight(self, tenant: str, weight: Optional[float]) -> None:
        """Applied at the next admission pick — no restart, no
        recompile (the scheduler reads weights per pick)."""
        if weight is not None and weight <= 0:
            raise ValueError(
                f"scheduling weight must be > 0 or None, got {weight}")
        with self._lock:
            if weight is None:
                self._weights.pop(tenant, None)
            else:
                self._weights[tenant] = float(weight)

    def priority(self, tenant: str) -> Optional[int]:
        """Strict priority class for ``tenant`` (``None`` = the engine
        default, 0; higher admits first and may preempt lower)."""
        with self._lock:
            return self._priorities.get(tenant)

    def set_priority(self, tenant: str, priority: Optional[int]) -> None:
        with self._lock:
            if priority is None:
                self._priorities.pop(tenant, None)
            else:
                self._priorities[tenant] = int(priority)

    def slo_ttft_ms(self, tenant: str) -> Optional[float]:
        """TTFT SLO target for ``tenant`` in ms (``None`` = no target —
        the ``hvd_tenant_slo_*`` series stay silent for it)."""
        with self._lock:
            return self._slo_ttft.get(tenant)

    def set_slo_ttft_ms(self, tenant: str,
                        slo_ttft_ms: Optional[float]) -> None:
        if slo_ttft_ms is not None and slo_ttft_ms <= 0:
            raise ValueError(
                f"slo_ttft_ms must be > 0 or None, got {slo_ttft_ms}")
        with self._lock:
            if slo_ttft_ms is None:
                self._slo_ttft.pop(tenant, None)
            else:
                self._slo_ttft[tenant] = float(slo_ttft_ms)

    # -- gauges ------------------------------------------------------------

    def gauges(self) -> Dict:
        """The ``/stats`` adapter-table block: plain json-ready values."""
        with self._lock:
            return {
                "capacity": self._capacity,
                "resident": len(self._names),
                "free_rows": len(self._free),
                "names": sorted(self._names),
                "refcounts": {n: int(self._ref[i])
                              for n, i in sorted(self._names.items())},
                "quotas": dict(sorted(self._quotas.items())),
                "weights": dict(sorted(self._weights.items())),
                "priorities": dict(sorted(self._priorities.items())),
                "slo_ttft_ms": dict(sorted(self._slo_ttft.items())),
                "loads_total": self._loads_total,
                "evictions_total": self._evictions_total,
            }
