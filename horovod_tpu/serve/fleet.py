"""The serving fleet's control loop: queue-depth autoscaling over
:class:`~.router.FleetRouter` membership.

This is the serving-plane twin of the PR-9 live-resize ingress, and it
deliberately reuses that machinery's *shape* rather than inventing a new
control discipline:

* **Hysteresis** — a watermark must be breached on ``breach_up`` /
  ``breach_down`` CONSECUTIVE polls before anything moves (one noisy
  sample never scales a fleet), and the high/low watermarks are kept
  apart so load sitting between them is a stable fixed point: no
  grow/shrink oscillation across a single threshold.
* **One pending change at a time** — while any replica is ``warming``
  (a grow in flight) or ``draining`` (a shrink in flight) the loop
  observes but does not decide, exactly like the coordinator's "one
  pending resize" rule: two in-flight membership changes would make the
  pressure signal unattributable.
* **Cooldown** — after a committed change the loop holds for
  ``cooldown_s`` so the new membership's effect on queue depth is
  actually measured before the next decision.
* **Min/max caps** — the serving analog of ``-np``/``--max-np``.

The *signal* is the PR-12 telemetry the replicas already export: queue
depth per ready replica (``hvd_queue_depth`` + ``hvd_active_slots`` —
:meth:`~.router.ReplicaHandle.load`) as the primary watermark, and the
fleet's interval-mean TTFT differenced from the
``hvd_generate_ttft_seconds`` histogram (exactly what a scraper's
``rate(sum)/rate(count)`` computes) as the secondary grow trigger — a
fleet can be latency-sick before its queues are deep.

Scale-down goes through :meth:`~.router.FleetRouter.remove_replica`,
i.e. drain-on-evict: the retiring replica finishes every admitted
stream before leaving. Scale-up is hitless: the new replica reads
``warming`` and takes no traffic until its compiles finish.

Multi-process replica liveness rides the EXISTING ``coord/`` heartbeat
plane (:func:`heartbeat_liveness`) — the fleet never grows a second
liveness protocol. Thread replicas ride the engine's own in-process
probe (:meth:`~.generate.GenerationEngine.loop_alive`), which reads
dead on loop-thread death AND on a wedged loop (work pending, no
completed iteration inside the stall window). Subprocess replicas
(:class:`~.proc_replica.ProcReplicaClient`) answer the SAME
``loop_alive`` probe, so the handle plumbing is unchanged: a dead pid
(``proc.poll()``) reads dead within one membership poll — no heartbeat
wait — and an unreachable-but-running child is declared dead on the
two-strike ``/healthz`` rule (one strike once a transport timeout on
the stats surface marked it suspect).

The failover interplay (ISSUE 15): every :meth:`poll_once` starts with
:meth:`~.router.FleetRouter.poll`, whose eviction of a liveness-dead
replica now STRANDS-AND-RESUMES — the router re-dispatches the dead
replica's tracked streams to surviving ready replicas and replays them
bit-identically (the dead member costs capacity, never a client
stream). Two control loops then cooperate without coordination: the
router's own lazy sweep thread delivers the death verdict even on a
static fleet with no autoscaler, while the autoscaler's below-min
refill (the liveness promise above) restores the lost capacity on its
next tick. Both paths are idempotent — a double poll evicts once,
and an already-finished stream ignores its death verdict.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from .router import FleetRouter


def heartbeat_liveness(client) -> Callable[[], bool]:
    """Replica liveness from the existing coordinator heartbeat plane.

    A multi-process serving fleet forms a coord world (one rank per
    replica process); the PR-1 liveness plane already detects a silent
    member after ``HVD_HEARTBEAT_TIMEOUT`` and ABORTs the world with the
    dead party named — there is nothing for the router to poll that the
    heartbeats do not already know. This adapter turns that verdict into
    the ``ReplicaHandle(liveness=)`` callable: alive until the world
    aborted. (The abort record — ``CoordClient._abort_record`` /
    the flight-recorder dump — names WHICH replica died; the supervising
    ``tpurun --restarts`` relaunches the fleet world per the PR-1
    contract, while the router stops dispatching the moment the verdict
    flips.)

    ``client`` is anything with the :meth:`~horovod_tpu.coord.client.
    CoordClient.aborted` surface.
    """

    def alive() -> bool:
        try:
            return not client.aborted()
        except Exception:  # noqa: BLE001 — an unreachable plane is "gone"
            return False

    return alive


class FleetAutoscaler:
    """Closed-loop replica-count controller for a :class:`FleetRouter`.

    Args:
      router: the fleet to scale; must have been built with a
        ``factory=`` (growth needs to mint replicas).
      min_replicas / max_replicas: membership caps (warming counts
        toward the cap — a grow in flight is a replica).
      high_watermark: grow when queued-work-per-ready-replica exceeds
        this for ``breach_up`` consecutive polls.
      low_watermark: shrink when it stays below this for
        ``breach_down`` consecutive polls. Keep ``low < high`` — the
        band between them is the stable region (enforced).
      ttft_high_ms: optional secondary grow trigger — the fleet's
        interval-mean TTFT (histogram delta between polls) above this
        counts as a high breach even with shallow queues.
      breach_up / breach_down: consecutive-poll hysteresis counts.
      cooldown_s: minimum seconds between committed membership changes.
      interval_s: poll period of :meth:`start`'s background thread.
      pressure_fn: test/override hook — zero-arg callable replacing the
        default queue-depth-per-ready-replica signal.
      clock: time source (injectable for tests; ``time.monotonic``).

    The decision core is :meth:`poll_once` — one observation + at most
    one membership change — so tests drive the loop deterministically
    without threads or sleeps; :meth:`start` just calls it on a timer.
    """

    def __init__(self, router: FleetRouter, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 high_watermark: float = 8.0, low_watermark: float = 1.0,
                 ttft_high_ms: Optional[float] = None,
                 breach_up: int = 2, breach_down: int = 2,
                 cooldown_s: float = 5.0, interval_s: float = 1.0,
                 pressure_fn: Optional[Callable[[], float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1 (a fleet of zero serves "
                f"nothing), got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= min_replicas "
                f"({min_replicas})")
        if not low_watermark < high_watermark:
            raise ValueError(
                f"low_watermark ({low_watermark}) must be < "
                f"high_watermark ({high_watermark}) — the band between "
                f"them is what prevents grow/shrink oscillation")
        if breach_up < 1 or breach_down < 1:
            raise ValueError("breach counts must be >= 1")
        if getattr(router, "_factory", None) is None:
            # Fail fast: without a factory every grow (and the below-min
            # refill) would raise per-tick inside the loop forever — a
            # misconfiguration only discoverable by reading logs.
            raise ValueError(
                "FleetAutoscaler needs a router built with factory= — "
                "it cannot grow a fleet it was never taught to build "
                "replicas for")
        self._router = router
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.high = high_watermark
        self.low = low_watermark
        self.ttft_high_ms = ttft_high_ms
        self.breach_up = breach_up
        self.breach_down = breach_down
        self.cooldown_s = cooldown_s
        self.interval_s = interval_s
        self._pressure_fn = pressure_fn
        self._clock = clock
        self._up = 0
        self._down = 0
        self._last_change: Optional[float] = None
        self._prev_ttft = router.ttft_totals()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals -----------------------------------------------------------

    def pressure(self) -> float:
        """Queued-plus-executing work per READY replica — the primary
        watermark signal (``pressure_fn`` overrides)."""
        if self._pressure_fn is not None:
            return float(self._pressure_fn())
        loads = [h.load() for h in self._router.replicas()
                 if h.state() == "ready"]
        if not loads:
            return 0.0
        return sum(loads) / len(loads)

    def _ttft_breach(self) -> bool:
        if self.ttft_high_ms is None:
            return False
        s, n = self._router.ttft_totals()
        ps, pn = self._prev_ttft
        self._prev_ttft = (s, n)
        if n <= pn:
            return False
        mean_ms = (s - ps) / (n - pn) * 1e3
        return mean_ms > self.ttft_high_ms

    # -- the control loop --------------------------------------------------

    def poll_once(self) -> Optional[str]:
        """One control tick: sweep membership (liveness evictions), read
        the signals, and commit at most one scale action. Returns
        ``"grow"`` / ``"shrink"`` when a change was committed, None
        otherwise."""
        counts = self._router.poll()
        live = counts["ready"] + counts["warming"] + counts["draining"]
        pending = counts["warming"] > 0 or counts["draining"] > 0
        # A fleet evicted below its floor (dead replicas) is refilled
        # regardless of pressure — min_replicas is a liveness promise.
        if not pending and live < self.min_replicas:
            self._commit("grow")
            return "grow"
        p = self.pressure()
        ttft_hot = self._ttft_breach()   # every poll: keeps the TTFT
        if pending:                      # delta window one-poll wide
            # One membership change at a time (the PR-9 rule): while a
            # change is in flight the loop only observes. Breaches are
            # NOT counted here — _commit zeroed the counters, so the
            # first decision about the settled fleet is built from
            # breach_up/_down fresh polls of the membership that would
            # actually be scaled (a warmup longer than the cooldown
            # would otherwise cascade a second grow off measurements of
            # the fleet it replaced).
            return None
        if p > self.high or ttft_hot:
            self._up += 1
            self._down = 0
        elif p < self.low:
            self._down += 1
            self._up = 0
        else:
            # The stable band: decay both counters — breaches must be
            # CONSECUTIVE (the hysteresis contract).
            self._up = 0
            self._down = 0
        now = self._clock()
        if (self._last_change is not None
                and now - self._last_change < self.cooldown_s):
            return None
        if self._up >= self.breach_up and live < self.max_replicas:
            self._commit("grow")
            return "grow"
        if self._down >= self.breach_down and live > self.min_replicas:
            self._commit("shrink")
            return "shrink"
        return None

    def _commit(self, direction: str) -> None:
        if direction == "grow":
            self._router.add_replica()
        else:
            self._router.remove_replica()
        self._router._metrics.on_scale(direction)
        self._last_change = self._clock()
        self._up = 0
        self._down = 0

    # -- thread lifecycle --------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        self._thread = threading.Thread(target=self._run,
                                        name="hvd-fleet-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — a bad tick must not kill
                import logging      # the loop; the next tick retries
                logging.getLogger("horovod_tpu.serve.fleet").exception(
                    "autoscaler tick failed")

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
