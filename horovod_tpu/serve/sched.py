"""Tenant-weighted fair admission scheduling (host-side, data-only).

FIFO admission is the noisy-neighbor failure mode: one chatty tenant
under its stream quota can fill the held line and the decode slots, and
every other tenant's TTFT degrades behind it. The fix lives entirely on
the host side of the decode-step boundary — WHICH held request is
admitted into a free slot is already data (a slot index and a block
table row), so fairness costs zero compiled programs (the compile-cache
pin ``tests/test_sched.py`` holds).

:class:`FairScheduler` implements weighted deficit round-robin (DRR)
over tenants, with strict priority classes above it:

* **Priority first.** Only the highest priority class with a pending
  request is eligible in any pick — priorities are for preemption-grade
  separation (interactive vs batch), not proportional sharing.
* **Weighted DRR within a class.** Every pending tenant accrues
  ``weight`` deficit per refill round; a pick costs 1. Over a saturated
  window tenants receive admission slots proportional to their weights
  regardless of how deep any one tenant's backlog is.
* **Per-tenant FIFO.** Within one tenant, requests are admitted in
  arrival order — fairness reorders *across* tenants only, so a
  single-tenant engine degenerates to exactly the FIFO admission order
  (the digest drills in ci.sh are pinned on this).
* **No banking.** A tenant's deficit is reset when it has nothing
  pending (standard DRR anti-burst rule): an idle tenant cannot save up
  credit and then monopolize the admission line.

Determinism: ties break on (deficit, tenant name), and the scheduler
holds no clock and no RNG — the same (held line, weights, priorities)
always picks the same request, which is what lets the starvation drill
pin completions rather than bound them statistically.
"""

from __future__ import annotations

from typing import Callable, Dict, FrozenSet, Optional, Sequence

__all__ = ["FairScheduler"]


class FairScheduler:
    """Pick which held request is admitted next, fairly across tenants.

    Args:
      weight_of: tenant name -> scheduling weight (> 0). Consulted at
        every pick, so weight changes (registry ``set_weight``) apply
        from the next admission without any engine restart.
      priority_of: tenant name -> priority class (int, higher wins;
        0 = default). Strictly above the weighted sharing: a pending
        higher class always admits before any lower class.

    Engine-loop-only: the single admitting thread owns the deficit
    state, so there is no lock (same discipline as the block manager's
    allocate/lookup/register flow).
    """

    def __init__(self, weight_of: Callable[[str], float],
                 priority_of: Optional[Callable[[str], int]] = None):
        self._weight_of = weight_of
        self._priority_of = priority_of or (lambda _t: 0)
        self._deficit: Dict[str, float] = {}

    def _weight(self, tenant: str) -> float:
        w = float(self._weight_of(tenant))
        if w <= 0:
            raise ValueError(
                f"tenant {tenant!r} has non-positive scheduling weight "
                f"{w} — weights must be > 0 (use priorities, not zero "
                f"weights, to de-class a tenant)")
        return w

    def pick(self, held: Sequence, *,
             blocked: FrozenSet[str] = frozenset()) -> Optional[int]:
        """Index into ``held`` of the next request to admit, or None
        when every pending tenant is in ``blocked`` (or ``held`` is
        empty). ``blocked`` carries the tenants whose head request is
        starved on a resource only THEY exhausted (a per-tenant block
        budget) — the whole point of per-tenant starvation is that it
        must not hold any other tenant's line.

        Each ``held`` element needs ``.tenant``; FIFO within a tenant
        is preserved by only ever considering a tenant's FIRST held
        request.
        """
        pending: Dict[str, int] = {}
        for i, req in enumerate(held):
            t = req.tenant
            if t in blocked or t in pending:
                continue
            pending[t] = i
        if not pending:
            return None
        top = max(self._priority_of(t) for t in pending)
        eligible = {t: i for t, i in pending.items()
                    if self._priority_of(t) == top}
        # DRR reset: tenants with nothing pending (in this class) drop
        # their deficit — no banking across idle gaps. Blocked tenants
        # KEEP theirs: a budget-starved tenant is waiting, not idle,
        # and must not lose its turn for being throttled.
        live = set(eligible) | set(blocked)
        for t in list(self._deficit):
            if t not in live:
                del self._deficit[t]
        while True:
            ready = [t for t in eligible if self._deficit.get(t, 0.0) >= 1]
            if ready:
                # Deterministic: largest deficit first, name breaks ties.
                t = max(ready, key=lambda n: (self._deficit[n], n))
                self._deficit[t] -= 1.0
                return eligible[t]
            for t in eligible:
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + self._weight(t))

    def forget(self, tenant: str) -> None:
        """Drop ``tenant``'s deficit (its adapter was evicted) so tenant
        churn cannot grow the deficit map without bound."""
        self._deficit.pop(tenant, None)
