"""Speculative decoding: draft k tokens host-side, verify in one forward.

The decode loop's ceiling is one compiled program dispatch per emitted
token. Speculative decoding (Leviathan et al. 2023; Chen et al. 2023)
raises it: a cheap *drafter* proposes up to ``k`` continuation tokens,
the model scores all ``k+1`` positions in ONE ``verify_step`` forward,
and the engine accepts the agreeing prefix plus one bonus token — so a
step emits between 1 and ``k+1`` tokens with zero quality change.

This module is the host half of the subsystem:

- :class:`DraftProposer` — the drafter contract. The default
  :class:`NgramProposer` is *self-speculative* (prompt-lookup): it scans
  the request's own prompt + emitted tokens for a previous occurrence of
  the current suffix n-gram and proposes what followed it. No second
  model, no new weights. A small-draft-model proposer is the documented
  stretch: implement ``propose`` over a distilled model behind this same
  interface and pass it via ``SpecConfig(drafter=...)``.
- :func:`accept_greedy` / :func:`accept_sampled` — the acceptance rules.
  Greedy acceptance is exactly the one-token stream (each emitted token
  is the argmax the sequential decode would have produced — the engine's
  verify logits are bitwise identical to ``decode_step``'s, so greedy
  speculated streams are digest-identical to non-speculated ones).
  Sampled acceptance is the standard rejection rule specialised to a
  point-mass draft distribution: accept draft token ``d`` with
  probability ``p_target(d)``; on rejection sample from the residual
  (``p_target`` with ``d`` zeroed, renormalised). The marginal over
  emitted tokens is exactly ``p_target`` — speculation never changes
  the sampling distribution (tests/test_spec.py chi-square-pins it).
  All randomness comes from the per-request seeded Generator, so seeded
  speculated streams stay run-to-run deterministic and replay
  bit-identically through a fleet failover.
- :class:`SpecConfig` — the engine knob: ``GenerationEngine(spec=
  SpecConfig(k=4))``. JSON round-trips via ``to_spec``/``from_spec`` so
  subprocess replicas re-derive the same speculation plane.

The model half — ``verify_step`` / ``paged_verify_step`` — lives in
:mod:`horovod_tpu.parallel.transformer` / ``.kv_blocks``.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Protocol, Sequence, Tuple, runtime_checkable

import numpy as np

__all__ = ["DraftProposer", "NgramProposer", "SpecConfig",
           "accept_greedy", "accept_sampled"]

_EMPTY = np.empty((0,), np.int64)


@runtime_checkable
class DraftProposer(Protocol):
    """Host-side drafter: propose up to ``k`` continuation tokens.

    ``context`` is the request's full token history (prompt + every
    emitted token, most recent last); the proposal continues it.
    Returning fewer than ``k`` tokens (or none) is always legal — the
    engine pads the verify batch and a slot with no proposal simply
    takes its normal one-token step, so a drafter can never stall a
    stream. Proposals are hints, not promises: a wrong draft costs only
    the wasted verify rows.
    """

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        ...


@dataclasses.dataclass
class NgramProposer:
    """Prompt-lookup / n-gram self-speculative drafter.

    Finds the most recent earlier occurrence of the context's trailing
    n-gram (trying window sizes ``max_ngram`` down to ``min_ngram``) and
    proposes the tokens that followed it. Pure host-side numpy over a
    few hundred tokens — effectively free next to a forward pass. It
    shines exactly where decoding is slowest to watch: repetitive
    continuations (code, templated text, self-repeating outputs), where
    the acceptance rate approaches 1 and a step emits ``k+1`` tokens.
    """
    max_ngram: int = 3
    min_ngram: int = 1

    def __post_init__(self):
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min={self.min_ngram} max={self.max_ngram}")

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        ctx = np.asarray(context).ravel()
        n = int(ctx.size)
        if n < 2 or k <= 0:
            return _EMPTY
        for g in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            pat = ctx[n - g:]
            # Candidate starts strictly before the suffix itself; walk
            # right-to-left so the MOST RECENT occurrence wins (recent
            # repetition is the best predictor of the next tokens).
            cand = np.flatnonzero(ctx[:n - g] == pat[0])
            for i in cand[::-1]:
                if np.array_equal(ctx[i:i + g], pat):
                    j = int(i) + g
                    return ctx[j:min(j + k, n)].astype(np.int64)
        return _EMPTY


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculation knob for :class:`~.generate.GenerationEngine`.

    Args:
      k: max draft tokens per step; the verify program scores ``k+1``
        positions (compile surface: exactly ONE extra executable,
        keyed ``("verify", k+1)`` — pinned in tests/test_spec.py).
      max_ngram/min_ngram: the default :class:`NgramProposer`'s window.
      drafter: override the drafter entirely (any
        :class:`DraftProposer`). Custom drafters are engine-local and
        not JSON-serialisable into a subprocess replica spec.
    """
    k: int = 4
    max_ngram: int = 3
    min_ngram: int = 1
    drafter: Optional[DraftProposer] = None

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min={self.min_ngram} max={self.max_ngram}")

    def make_drafter(self) -> DraftProposer:
        if self.drafter is not None:
            return self.drafter
        return NgramProposer(max_ngram=self.max_ngram,
                             min_ngram=self.min_ngram)

    def to_spec(self) -> dict:
        """JSON form for the subprocess replica spec (``"spec"`` entry)."""
        if self.drafter is not None:
            raise ValueError(
                "custom drafters are not serialisable into a subprocess "
                "replica spec; use the built-in n-gram drafter knobs")
        return {"k": self.k, "max_ngram": self.max_ngram,
                "min_ngram": self.min_ngram}

    @staticmethod
    def from_spec(d: dict) -> "SpecConfig":
        return SpecConfig(k=int(d.get("k", 4)),
                          max_ngram=int(d.get("max_ngram", 3)),
                          min_ngram=int(d.get("min_ngram", 1)))


def accept_greedy(rows: np.ndarray,
                  draft: Sequence[int]) -> Tuple[List[int], int]:
    """Greedy acceptance over verify logits.

    ``rows`` is ``[W, vocab]`` (row ``j`` = next-token logits after
    consuming the last token plus ``draft[:j]``); ``draft`` holds up to
    ``W - 1`` proposed tokens. Emits the argmax chain: row ``j``'s
    argmax, continuing while it equals ``draft[j]`` (so the next row's
    context is real), plus one bonus token from the row after the last
    match. Exactly the tokens sequential greedy decode would emit —
    never more rows than the context justifies.

    Returns ``(tokens, hits)`` where ``hits`` counts draft-accepted
    tokens (the accepted prefix; ``len(tokens) == hits + 1``).
    """
    out: List[int] = []
    hits = 0
    for j, d in enumerate(draft):
        e = int(np.argmax(rows[j]))
        out.append(e)
        if e != int(d):
            return out, hits
        hits += 1
    out.append(int(np.argmax(rows[len(draft)])))
    return out, hits


def accept_sampled(rows: np.ndarray, draft: Sequence[int], probs_fn,
                   rng: np.random.Generator) -> Tuple[List[int], int]:
    """Rejection-rule acceptance for seeded sampling (point-mass draft).

    ``probs_fn(logits_row) -> [vocab] float64 probs`` is the request's
    temperature/top-k transform — the TARGET distribution sequential
    decode would sample from. Per draft token ``d``: accept with
    probability ``p(d)`` (one uniform draw); on rejection emit a draw
    from the residual ``p`` with ``d`` zeroed, renormalised, and stop.
    After a fully-accepted draft, emit one bonus draw from the last
    row. Marginally each emitted token ~ ``p`` exactly (chi-square
    pinned), and the draw sequence is a pure function of the seeded
    ``rng`` — deterministic re-runs and bit-identical failover replay.

    Returns ``(tokens, hits)`` as in :func:`accept_greedy`.
    """
    out: List[int] = []
    hits = 0
    for j, d in enumerate(draft):
        d = int(d)
        p = probs_fn(rows[j])
        if rng.random() < p[d]:
            out.append(d)
            hits += 1
            continue
        q = p.copy()
        q[d] = 0.0
        tot = q.sum()
        if tot <= 0.0:
            # Target IS the point mass on d; the accept draw can only
            # have failed on an fp edge — the draft token is the whole
            # distribution, emit it.
            out.append(d)
            hits += 1
            continue
        out.append(int(rng.choice(q.size, p=q / tot)))
        return out, hits
    p = probs_fn(rows[len(draft)])
    out.append(int(rng.choice(p.size, p=p)))
    return out, hits
