"""The inference engine: bucketed batches over a warm per-bucket jit cache.

Composition of the pieces the training side already built (PAPER.md's
design re-used, zero new model code):

* **Params over the mesh** — restored from a ``save_checkpoint`` pytree
  (:func:`horovod_tpu.parallel.checkpoint.restore_for_inference`) and
  laid out by ``NamedSharding`` over a ``parallel.mesh`` mesh, so a model
  too big for one chip serves across the slice exactly as it trained.
* **Per-bucket compile cache** — requests are coalesced into power-of-two
  buckets (:mod:`.batcher`); each bucket is one AOT-compiled executable
  (``jax.jit(...).lower(...).compile()``), built either lazily on first
  hit or all at once by :meth:`Engine.warmup` so no user request ever
  pays a compile.
* **Backpressure** — bounded admission queue
  (:class:`~horovod_tpu.exceptions.ServerOverloadedError` at the door),
  per-request deadlines dropped at dequeue
  (:class:`~horovod_tpu.exceptions.DeadlineExceededError` through the
  future), graceful drain on shutdown.
* **Observability** — :class:`~.metrics.ServeMetrics` snapshot plus the
  serving phases ``QUEUE → PAD → XLA_EXECUTE → RESPOND`` on the existing
  :class:`~horovod_tpu.utils.timeline.Timeline` Chrome trace (tensor row
  ``serve``, op kind ``INFERENCE``).

The dispatch loop is one background thread: with a single accelerator
program per batch there is nothing to overlap host-side, and one
consumer keeps the batcher's FIFO semantics trivially correct.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..exceptions import (DeadlineExceededError, ServerClosedError,
                          ServerOverloadedError)
from .batcher import (Request, RequestQueue, bucket_for, bucket_sizes,
                      pad_rows)
from .metrics import ServeMetrics

# Serving phases on the timeline, in emission order. QUEUE is the wait
# assembling the batch (flush policy), PAD the host-side bucket/stack,
# XLA_EXECUTE the device program incl. transfer, RESPOND future delivery.
SERVE_PHASES = ("QUEUE", "PAD", "XLA_EXECUTE", "RESPOND")
_TIMELINE_ROW = "serve"


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (all host-side; buckets are the only compile surface).

    ``max_batch`` must be a power of two — it is both the top bucket and
    the flush threshold. ``batch_timeout_ms`` bounds the head-of-line
    wait: an arriving trickle ships after at most that delay.
    ``default_deadline_ms`` (None = no deadline) applies to requests that
    don't carry their own.
    """

    max_batch: int = 32
    batch_timeout_ms: float = 5.0
    max_queue: int = 256
    default_deadline_ms: Optional[float] = None
    # Debug/test hook: keep the padded batch each request executed in on
    # its Request (``future.request.executed_batch``) so callers can
    # reproduce the exact program input (the bit-identity contract
    # tests/test_serve.py pins). Off by default — it pins up to a full
    # [max_batch, *item] array per RETAINED future, real memory at
    # production shapes.
    record_executed_batch: bool = False


class ReadinessMixin:
    """The /healthz readiness contract shared by every serving engine
    (:class:`Engine` and :class:`~.generate.GenerationEngine`): a triple
    ``(ready, status, queue_depth)`` — ``(False, "warming", ...)`` until
    :meth:`warmup` completes (a cold engine answers, but every first
    bucket hit pays a compile — a load balancer must not route to it),
    ``(False, "draining", ...)`` once shutdown began, ``(True, "ok", ...)``
    otherwise. Hosts provide ``_warmed``/``_closed`` flags and a
    ``_queue`` with ``__len__``."""

    _warmed = False
    _closed = False

    def health(self) -> Tuple[bool, str, int]:
        if self._closed:
            return False, "draining", len(self._queue)
        if not self._warmed:
            return False, "warming", len(self._queue)
        return True, "ok", len(self._queue)

    def load(self) -> int:
        """Dispatch pressure for a fleet router: queued requests plus
        rows currently mid-execution (:meth:`_active_rows`) — the same
        numbers this engine's ``/metrics`` exports as
        ``hvd_queue_depth`` and ``hvd_active_slots``, so least-depth
        routing and the operator's dashboard read one signal."""
        return len(self._queue) + self._active_rows()

    def _active_rows(self) -> int:
        """Rows mid-execution. 0 for the single-shot engine (a batch is
        in flight for milliseconds); the generation engine overrides
        with its live decode slots — a stream occupies its slot for its
        whole lifetime, which is real dispatch pressure."""
        return 0


class Engine(ReadinessMixin):
    """In-process dynamic-batching inference server.

    Args:
      apply_fn: ``apply_fn(variables, batch) -> outputs`` — typically
        ``lambda v, x: model.apply(v, x, train=False)``. Outputs may be
        any pytree of arrays with a leading batch axis.
      variables: the model variable dict (``{"params": ..., [
        "batch_stats": ...]}``), e.g. from ``restore_for_inference``.
        Pre-sharded ``jax.Array`` leaves are served as laid out; host
        arrays are fine for single-host serving.
      item_shape / item_dtype: shape/dtype of ONE example (no batch
        axis). Fixed per engine — one engine serves one signature, the
        bucketed compile cache depends on it.
      config: :class:`ServeConfig`.
      timeline: a :class:`horovod_tpu.utils.timeline.Timeline` to receive
        the serving phases; defaults to the runtime's timeline when
        ``horovod_tpu.init()`` ran with ``HOROVOD_TIMELINE`` set.
    """

    def __init__(self, apply_fn: Callable, variables: Any,
                 item_shape: Tuple[int, ...], item_dtype: Any = np.float32,
                 config: ServeConfig = ServeConfig(),
                 timeline: Optional[Any] = None):
        self._apply = apply_fn
        self._variables = variables
        self._item_shape = tuple(item_shape)
        self._item_dtype = np.dtype(item_dtype)
        self._cfg = config
        self._buckets = bucket_sizes(config.max_batch)
        self._queue = RequestQueue(config.max_queue)
        self._metrics = ServeMetrics()
        self._compiled: Dict[int, Any] = {}
        self._compile_lock = threading.Lock()
        # Bucket ids mirrored under their own micro-lock so stats() never
        # shares the compile critical section — a lazy compile of a big
        # model holds _compile_lock for seconds-to-minutes, exactly when
        # an operator polls /stats.
        self._compiled_ids: set = set()
        self._stats_lock = threading.Lock()
        if timeline is None:
            from .. import runtime
            if runtime.is_initialized():
                timeline = runtime.world().timeline
        self._timeline = timeline
        self._closed = False
        # Readiness for load balancers (/healthz): a cold engine serves
        # lazily but pays compiles under traffic — routable means warmup()
        # completed AND shutdown hasn't begun.
        self._warmed = False
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="hvd-serve-dispatch",
                                        daemon=True)
        self._thread.start()

    # -- compile cache -----------------------------------------------------

    def _compile(self, bucket: int):
        """AOT-compile the ``bucket``-sized executable (idempotent)."""
        exe = self._compiled.get(bucket)
        if exe is not None:
            return exe
        with self._compile_lock:
            exe = self._compiled.get(bucket)
            if exe is None:
                x = jax.ShapeDtypeStruct((bucket,) + self._item_shape,
                                         self._item_dtype)
                exe = (jax.jit(self._apply)
                       .lower(self._variables, x).compile())
                self._compiled[bucket] = exe
                with self._stats_lock:
                    self._compiled_ids.add(bucket)
        return exe

    def warmup(self) -> Tuple[int, ...]:
        """Pre-compile AND pre-execute every bucket before traffic.

        The execution pass matters as much as the compile: it faults in
        the executable, touches the transfer path, and validates that
        outputs really carry a leading batch axis — all failures you want
        at deploy time, not under load. Returns the bucket sizes warmed.
        """
        for b in self._buckets:
            exe = self._compile(b)
            x = np.zeros((b,) + self._item_shape, self._item_dtype)
            out = exe(self._variables, x)
            jax.tree_util.tree_map(
                lambda a: jax.block_until_ready(a), out)
            for leaf in jax.tree_util.tree_leaves(out):
                if not getattr(leaf, "shape", (0,))[:1] == (b,):
                    raise ValueError(
                        f"apply_fn output leaf shape {leaf.shape} has no "
                        f"leading batch axis of {b}; the engine cannot "
                        f"split it back into per-request rows")
        self._warmed = True
        return self._buckets

    # -- client API --------------------------------------------------------

    def submit(self, inputs: Any,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one example; returns a future resolving to its output
        row (a numpy pytree). Raises :class:`ServerOverloadedError` when
        the queue is full, :class:`ServerClosedError` after shutdown.

        The resolved future additionally exposes ``request.bucket`` via
        the :class:`Request` stored on ``future.request`` — clients and
        tests can reconstruct the exact executed batch shape.
        """
        x = np.asarray(inputs, self._item_dtype)
        if x.shape != self._item_shape:
            raise ValueError(
                f"request shape {x.shape} != engine item shape "
                f"{self._item_shape} (one example per request)")
        if deadline_ms is None:
            deadline_ms = self._cfg.default_deadline_ms
        now = time.monotonic()
        req = Request(inputs=x, future=Future(), enqueued_at=now,
                      deadline_at=(None if deadline_ms is None
                                   else now + deadline_ms / 1e3))
        req.future.request = req
        try:
            depth = self._queue.put(req)   # raises Closed
        except ServerOverloadedError as e:
            self._metrics.on_overload()
            # Backoff hint for the 503 (satellite of the failover
            # plane): time until the full queue drains at the measured
            # service rate — proportional backoff beats a fixed retry.
            e.retry_after_ms = self._metrics.retry_after_ms(
                len(self._queue))
            raise
        self._metrics.on_submit(depth)
        return req.future

    def infer(self, inputs: Any, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None) -> Any:
        """Synchronous :meth:`submit` (+ ``future.result(timeout)``)."""
        return self.submit(inputs, deadline_ms).result(timeout)

    def stats(self) -> Dict:
        """The ``/stats`` snapshot."""
        snap = self._metrics.snapshot()
        snap["buckets"] = list(self._buckets)
        with self._stats_lock:     # lazy _compile inserts race a poll
            snap["buckets_compiled"] = sorted(self._compiled_ids)
        snap["max_queue"] = self._cfg.max_queue
        snap["batch_timeout_ms"] = self._cfg.batch_timeout_ms
        return snap

    def prom_collect(self):
        """This engine's ``(meta, samples)`` in Prometheus terms —
        everything :meth:`stats` knows plus the latency histograms,
        labeled ``engine="predict"`` (see
        :func:`~horovod_tpu.serve.metrics.collect_stats`)."""
        from .metrics import collect_stats
        return collect_stats(self.stats(), self._metrics.registry,
                             engine="predict")

    def prom_metrics(self) -> str:
        """Prometheus text exposition of :meth:`prom_collect` (the
        ``/metrics`` body when this engine serves alone)."""
        from ..obs.registry import render
        return render(*self.prom_collect())

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the engine. ``drain=True`` serves everything already
        queued first; ``drain=False`` fails pending futures with
        :class:`ServerClosedError`. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if drain:
            self._queue.close()
        else:
            self._fail_pending()
        self._thread.join(timeout)
        # A drain that outlasts the join timeout (a wedged device batch)
        # must not leave clients blocked forever in future.result():
        # fail whatever is STILL queued. In-flight requests stay with
        # the stuck dispatcher — if it ever finishes, their done-state
        # guards keep it from crashing on a resolved future.
        if self._thread.is_alive():
            self._fail_pending()

    def _fail_pending(self) -> None:
        cancelled = 0
        for req in self._queue.drain_pending():
            # A client may have cancel()ed a queued future already —
            # set_exception on a done future raises InvalidStateError
            # and would abandon every later pending future.
            if not req.future.done():
                req.future.set_exception(ServerClosedError(
                    "server shut down before execution"))
                cancelled += 1
        if cancelled:
            self._metrics.on_shutdown_cancel(cancelled)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- dispatch ----------------------------------------------------------

    @staticmethod
    def _phase(tl, name: str):
        """A serving phase as a scoped timeline activity (no-op without a
        timeline). Straight-line phases use this; the QUEUE phase stays
        hand-bracketed in the loop below because its op must be ABANDONED
        (abort), not ended, when the queue closes mid-wait."""
        if tl is None:
            return contextlib.nullcontext()
        return tl.activity(_TIMELINE_ROW, name)

    def _dispatch_loop(self):
        tl = self._timeline
        while True:
            if tl:
                tl.start(_TIMELINE_ROW, "INFERENCE")
                tl.activity_start(_TIMELINE_ROW, "QUEUE")
            batch = self._queue.take_batch(self._cfg.max_batch,
                                           self._cfg.batch_timeout_ms)
            if tl:
                tl.activity_end(_TIMELINE_ROW)
            if not batch:               # closed + drained
                if tl:
                    tl.abort(_TIMELINE_ROW)
                return
            try:
                self._run_batch(batch, tl)
            except Exception as e:  # noqa: BLE001 — deliver, don't die
                if tl:
                    tl.abort(_TIMELINE_ROW, error=repr(e))
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)

    def _run_batch(self, batch, tl):
        now = time.monotonic()
        live = []
        for req in batch:
            # Claiming the future here (PENDING -> RUNNING) also fences
            # client-side Future.cancel(): a future cancelled while
            # queued returns False and is dropped — otherwise the later
            # set_result would raise InvalidStateError and poison every
            # other request in the batch.
            if not req.future.set_running_or_notify_cancel():
                continue
            if req.expired(now):
                self._metrics.on_deadline_expired(
                    (now - req.enqueued_at) * 1e3)
                req.future.set_exception(DeadlineExceededError(
                    f"deadline expired after "
                    f"{(now - req.enqueued_at) * 1e3:.1f} ms in queue"))
            else:
                live.append(req)
        if not live:
            if tl:
                tl.end(_TIMELINE_ROW)
            return
        with self._phase(tl, "PAD"):
            bucket = bucket_for(len(live), self._buckets)
            padded = pad_rows([r.inputs for r in live], bucket)
            for i, req in enumerate(live):
                req.bucket, req.row = bucket, i
                if self._cfg.record_executed_batch:
                    req.executed_batch = padded
        with self._phase(tl, "XLA_EXECUTE"):
            t0 = time.monotonic()
            exe = self._compile(bucket)
            out = exe(self._variables, padded)
            out_np = jax.tree_util.tree_map(np.asarray, out)  # blocks
            exec_ms = (time.monotonic() - t0) * 1e3
        with self._phase(tl, "RESPOND"):
            done = time.monotonic()
            self._metrics.on_batch(bucket, len(live), exec_ms,
                                   len(self._queue))
            for i, req in enumerate(live):
                row = jax.tree_util.tree_map(lambda a, i=i: a[i], out_np)
                req.future.set_result(row)
                self._metrics.on_response(
                    (done - req.enqueued_at) * 1e3,
                    (t0 - req.enqueued_at) * 1e3)
        if tl:
            first = jax.tree_util.tree_leaves(out_np)[0]
            tl.end(_TIMELINE_ROW, output=first)
