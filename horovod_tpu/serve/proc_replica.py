"""Out-of-process fleet replicas: a subprocess worker + its HTTP client.

Every fleet replica before this module was a thread inside one Python
process, so the fault-tolerance plane (drain-on-evict, liveness
verdicts, deterministic stream failover) had never actually crossed a
process boundary — a real replica death is a SIGKILL'd process, not a
flipped flag. This module closes that gap with two halves:

* **The worker** (``python -m horovod_tpu.serve.proc_replica --spec
  <json>``): builds a :class:`~.generate.GenerationEngine` from a
  JSON-able spec (model dims + param seed + generation knobs — params
  are re-derived from the seed, so a child holds BIT-IDENTICAL weights
  to any sibling built from the same spec), mounts the existing
  :class:`~.server.HttpServer` (``/generate`` / ``/stats`` /
  ``/healthz`` / ``/metrics``), and reports readiness to its parent
  through a ready file. Lifecycle is parent-driven over the child's
  stdin: a ``{"shutdown": {"drain": ...}}`` line drains or aborts;
  stdin EOF (the parent died or closed the pipe) aborts — plus a
  belt-and-braces ``getppid()`` watchdog — so a child can never orphan.

* **The client** (:class:`ProcReplicaClient`): duck-types the engine
  surface :class:`~.router.ReplicaHandle` already consumes (``submit``
  / ``generate`` / ``stats`` / ``health`` / ``prom_collect`` /
  ``warmup`` / ``shutdown(drain=)`` / ``loop_alive``) over HTTP with
  explicit connect/read timeouts and bounded retry-with-backoff on
  transient transport errors. The hard rule: a transport failure on
  ``submit`` maps to the RETRYABLE-OVERLOAD path
  (:class:`~..exceptions.ServerOverloadedError`), never a silent loss —
  the router's dispatch walk then tries another door. A stream is only
  recorded as admitted once the child's 200 arrives (the server holds
  headers until the first event, so queue-death surfaces as a status
  code, not a broken stream).

Because the client implements ``loop_alive``, the router's existing
liveness plumbing works unchanged: process-exit detection
(``proc.poll()``) declares a dead pid dead within ONE membership poll —
no heartbeat wait — and a ``/healthz`` probe with a two-strike
tolerance (one strike once :meth:`ProcReplicaClient.mark_suspect` has
fired) catches the hung-but-alive child. Stream failover needs no new
code either: the PR-15 replay envelope (tokens + seed + absolute
deadline) was always process-shippable; the pump just relays the
replacement child's HTTP stream instead of a thread's queue.

The child's samples are deliberately NOT relayed through the router's
``/metrics`` render (``prom_collect`` returns an empty set): relaying
would serialize N child HTTP scrapes into every router scrape and
double-publish the same series to a scraper that also walks the
``/healthz`` ``replica_metrics`` advertisement — the federation path
:class:`~horovod_tpu.obs.summary.FleetPoller` uses (one scrape per
endpoint per poll).

When to prefer threads: subprocess replicas cost a full interpreter +
jax import + compile per member and an HTTP round trip per dispatch —
the right trade when replica isolation matters (a crash must not take
the fleet) or ahead of multi-host serving, the wrong one for packing
maximum replicas of a tiny model into one host's memory.
"""

from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..exceptions import (DeadlineExceededError, PreemptedError,
                          ReplicaTimeoutError, ServerClosedError,
                          ServerOverloadedError, WorkerFailureError)
from .generate import GenerationHandle

_DEFAULT = object()     # mirrors generate.submit's eos_id sentinel


class _ClientCfg:
    """The slice of :class:`~.generate.GenerationConfig` the router
    reads off an engine object (``_track`` resolves the default
    deadline through ``engine._cfg.default_deadline_ms``)."""

    def __init__(self, default_deadline_ms: Optional[float] = None):
        self.default_deadline_ms = default_deadline_ms


class ProcReplicaClient:
    """HTTP client for one subprocess replica, shaped like an engine.

    ``proc`` is the child's ``subprocess.Popen`` (None in tests that
    fake the server side — every proc-dependent path then degrades to
    HTTP-only semantics). ``port`` may be unknown at construction: the
    worker binds an ephemeral port and publishes it through
    ``ready_file``; until that lands the replica reads ``warming`` and
    takes no traffic.

    Transport contract (the tentpole's hard rule): ``submit`` maps
    EVERY transport failure — connect refusal, connect timeout, a
    mid-body disconnect before the response status line — to
    :class:`ServerOverloadedError` with a ``retry_after_ms`` hint,
    after a bounded retry-with-backoff on errors raised while the
    request was still being sent (nothing admitted yet, so a retry
    cannot double-submit). An error AFTER the request was fully sent is
    not client-retried (the child may already hold the stream; a blind
    retry would double-execute) but still maps to the overload path:
    the router re-dispatches, the orphaned child stream — if any —
    burns slots, never client-visible state. No stream is recorded as
    admitted until the 200 status line arrives.
    """

    def __init__(self, name: str, proc: Optional[subprocess.Popen] = None,
                 *, host: str = "127.0.0.1", port: Optional[int] = None,
                 ready_file: Optional[str] = None,
                 connect_timeout_s: float = 2.0,
                 read_timeout_s: float = 120.0,
                 probe_timeout_s: float = 1.0,
                 submit_retries: int = 2,
                 backoff_s: float = 0.05,
                 backoff_cap_s: float = 0.5,
                 ready_timeout_s: float = 180.0,
                 heartbeat_timeout_s: float = 5.0,
                 default_deadline_ms: Optional[float] = None):
        self.name = name
        self.serve_name = name          # router re-stamps on _attach
        self._proc = proc
        self._host = host
        self._port = port
        self._ready_file = ready_file
        self._connect_timeout = connect_timeout_s
        self._read_timeout = read_timeout_s
        self._probe_timeout = probe_timeout_s
        self._submit_retries = max(0, int(submit_retries))
        self._backoff = backoff_s
        self._backoff_cap = backoff_cap_s
        self._ready_timeout = ready_timeout_s
        self._hb_file = (ready_file + ".hb") if ready_file else None
        self._hb_timeout = heartbeat_timeout_s
        self._cfg = _ClientCfg(default_deadline_ms)
        self._closed = False            # router reads this as "draining"
        self._suspect = False
        self._miss_streak = 0
        self._inflight: set = set()
        self._inflight_lock = threading.Lock()
        self._last_stats: Dict[str, Any] = {}

    # -- process / readiness plumbing ---------------------------------------

    @property
    def pid(self) -> Optional[int]:
        return None if self._proc is None else self._proc.pid

    def _ensure_port(self) -> bool:
        """Resolve the child's ephemeral port from the ready file (one
        successful read sticks). False while the child is still
        booting."""
        if self._port is not None:
            return True
        if self._ready_file is None:
            return False
        try:
            with open(self._ready_file) as f:
                info = json.load(f)
            self._port = int(info["port"])
            self._host = info.get("host", self._host)
        except (OSError, ValueError, KeyError):
            return False
        return True

    def metrics_endpoint(self) -> Optional[str]:
        """``"host:port"`` of the child's own ``/metrics`` — what the
        router advertises in ``/healthz`` ``replica_metrics`` for
        scrapers to walk (the federation path; see module docstring)."""
        if not self._ensure_port():
            return None
        return f"{self._host}:{self._port}"

    # -- HTTP plumbing ------------------------------------------------------

    def _get_json(self, path: str, timeout: float) -> Dict[str, Any]:
        """One GET round trip, JSON-decoded whatever the status code
        (``/healthz`` answers 503 with a meaningful body). Raises
        :class:`ReplicaTimeoutError` on a transport TIMEOUT (the
        hung-child signal ``ReplicaHandle.load`` keys eviction on),
        plain ``OSError``/``HTTPException`` on other transport
        failures."""
        if not self._ensure_port():
            raise RuntimeError(f"replica {self.name} not ready yet")
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
        except TimeoutError as e:
            raise ReplicaTimeoutError(
                f"replica {self.name} ({self._host}:{self._port}) timed "
                f"out after {timeout}s on GET {path}") from e
        finally:
            conn.close()
        return json.loads(body.decode("utf-8", "replace") or "{}")

    # -- engine surface: health / load / stats ------------------------------

    def health(self) -> Tuple[bool, str, int]:
        """The child's ``/healthz`` verdict. Never raises — the router
        walks ``state()`` over the whole membership, and one unreachable
        child must not 500 the fleet's ``/stats``; unreachable reads as
        not-ready (the liveness plane owns the dead verdict)."""
        if not self._ensure_port():
            return False, "booting", 0
        try:
            body = self._get_json("/healthz", self._probe_timeout)
        except Exception:  # noqa: BLE001 — unreachable = not ready
            return False, "unreachable", 0
        status = str(body.get("status", "unreachable"))
        return status == "ok", status, int(body.get("queue_depth", 0))

    def load(self) -> int:
        """Dispatch pressure (queued + executing rows) from the child's
        ``/stats``. A transport timeout raises
        :class:`ReplicaTimeoutError` so the handle can key the
        suspect-and-check eviction path; any other failure propagates
        and reads as the busy sentinel."""
        snap = self._get_json("/stats", self._probe_timeout)
        self._last_stats = snap
        return int(snap.get("queue_depth", 0)) \
            + int(snap.get("active_slots", 0))

    def stats(self) -> Dict[str, Any]:
        """The child's full ``/stats`` snapshot — or the LAST-KNOWN one
        when the child no longer answers: the router folds a retiring
        replica's final totals into its monotone baselines, and a child
        that exited after a clean drain should contribute what it last
        reported, not zeros."""
        try:
            snap = self._get_json("/stats", max(self._probe_timeout, 5.0))
        except Exception:  # noqa: BLE001 — dead child keeps what it had
            return dict(self._last_stats)
        self._last_stats = snap
        return snap

    def adapter_names(self) -> Optional[Tuple[str, ...]]:
        """Resident adapter names from the child's ``/stats``
        ``adapter_table`` block — the surface the router's
        adapter-affinity dispatch reads (``None`` = the child hosts no
        registry and can never take adapter traffic). Served from the
        stats cache (``load()`` refreshes it every dispatch walk); one
        fresh fetch when nothing is cached yet."""
        snap = self._last_stats
        if not snap:
            snap = self.stats()
        table = snap.get("adapter_table")
        if not isinstance(table, dict):
            return None
        return tuple(table.get("names") or ())

    def adapters_resident(self) -> Optional[int]:
        names = self.adapter_names()
        return None if names is None else len(names)

    def prefix_digests(self) -> Tuple[str, ...]:
        """Registered-prefix route digests from the child's ``/stats``
        — the surface the router's prefix-affine dispatch reads (empty
        = nothing registered, never affine). Served from the stats
        cache (``load()`` refreshes it every dispatch walk); one fresh
        fetch when nothing is cached yet."""
        snap = self._last_stats
        if not snap:
            snap = self.stats()
        digests = snap.get("prefix_digests")
        if not isinstance(digests, (list, tuple)):
            return ()
        return tuple(str(d) for d in digests)

    @property
    def route_block_size(self) -> Optional[int]:
        """The child's KV block size (the digest granularity), from the
        same cached ``/stats`` snapshot ``prefix_digests`` reads."""
        bs = self._last_stats.get("block_size")
        return bs if isinstance(bs, int) and bs > 0 else None

    def slo_burn(self, tenant: str) -> float:
        """The child's current SLO burn fraction for ``tenant`` (0.0
        when unknown) — the router's SLO-aware dispatch signal, read
        from the stats cache (``load()`` refreshes it every dispatch
        walk, so the signal is at most one walk stale; a fresh HTTP
        fetch per sort key would multiply the dispatch round trips by
        the fleet size)."""
        t = (self._last_stats.get("tenants") or {}).get(tenant)
        if not isinstance(t, dict):
            return 0.0
        burn = t.get("slo_burn")
        return float(burn) if isinstance(burn, (int, float)) else 0.0

    def _active_rows(self) -> int:
        """Best-effort active-slot count for the router's fleet peak
        sampling — read from the stats cache (a fresh HTTP fetch per
        dispatch-time peak sample would double the dispatch round
        trips)."""
        return int(self._last_stats.get("active_slots", 0))

    def prom_collect(self):
        """Empty on purpose — a subprocess replica's samples are scraped
        at ITS advertised ``/metrics`` endpoint, never relayed through
        the router render (see module docstring: federation, not
        proxying)."""
        return {}, []

    def prom_metrics(self) -> str:
        return ""

    # -- liveness -----------------------------------------------------------

    def mark_suspect(self) -> None:
        """Satellite rule: a transport timeout on the stats surface
        tightens the next liveness probe to one strike — a hung child
        must be evicted within one poll, not routed around forever."""
        self._suspect = True

    def loop_alive(self, stall_timeout_s: float = 60.0) -> bool:
        """The liveness verdict ``ReplicaHandle.alive()`` consumes:
        process-exit detection first (a dead pid reads dead IMMEDIATELY
        — within one membership poll, no heartbeat wait), then a
        ``/healthz`` reachability probe with a two-strike tolerance so
        one dropped packet is not an eviction (one strike once
        :meth:`mark_suspect` fired). A child still booting (no port
        yet) is warming, not dead."""
        del stall_timeout_s     # the child's own loop_alive covers stall
        if self._proc is not None and self._proc.poll() is not None:
            return False
        if not self._ensure_port():
            return True         # booting: add_replica's warmup gates traffic
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=self._probe_timeout)
        try:
            conn.request("GET", "/healthz")
            conn.getresponse().read()
        except Exception:  # noqa: BLE001 — any transport failure = strike
            self._miss_streak += 1
            return not (self._suspect or self._miss_streak >= 2)
        finally:
            conn.close()
        self._miss_streak = 0
        self._suspect = False
        return True

    def _heartbeat_stale(self) -> bool:
        """True once the worker's heartbeat file has gone silent past
        the timeout. A missing file reads FRESH, not stale — the child
        may still be booting (warmup gates traffic either way), and an
        operator pointing at a worker predating the heartbeat plane
        must not have every replica read dead."""
        if self._hb_file is None:
            return False
        try:
            age = time.time() - os.path.getmtime(self._hb_file)
        except OSError:
            return False
        return age > self._hb_timeout

    def aborted(self) -> bool:
        """The ``CoordClient.aborted`` surface, so a subprocess replica
        wires onto the existing :func:`~.fleet.heartbeat_liveness` hook
        unchanged: gone once the child process exited, its heartbeat
        file went stale, or the ``/healthz`` probe's two-strike verdict
        fired (the probe still runs — the heartbeat catches a SIGSTOPed
        or wedged-before-accept child the HTTP path answers for)."""
        if self._proc is not None and self._proc.poll() is not None:
            return True
        if self._heartbeat_stale():
            return True
        return not self.loop_alive()

    # -- engine surface: submit / generate ----------------------------------

    def submit(self, tokens: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               sampling: Any = None,
               eos_id: Any = _DEFAULT,
               deadline_ms: Optional[float] = None,
               adapter: Optional[str] = None) -> GenerationHandle:
        """POST the request to the child's ``/generate`` (streaming) and
        return a local :class:`GenerationHandle` relaying the chunked
        token lines. Blocks until the response STATUS LINE — the server
        holds headers until the first event, so admission verdicts
        (overload 503 / closed 503 / deadline 504 / malformed 400)
        surface here as the same synchronous exceptions a thread engine
        raises, and no stream is recorded as admitted on any earlier
        failure."""
        if self._closed:
            raise ServerClosedError(
                f"replica {self.name} client is shut down")
        if not self._ensure_port():
            err = ServerOverloadedError(
                f"replica {self.name} is still booting — retry after "
                f"backoff")
            err.retry_after_ms = 500.0
            raise err
        body = {"tokens": [int(t) for t in tokens], "stream": True}
        if max_new_tokens is not None:
            body["max_new_tokens"] = int(max_new_tokens)
        if sampling is not None:
            body["temperature"] = float(sampling.temperature)
            body["top_k"] = int(sampling.top_k)
            body["seed"] = int(sampling.seed)
        if eos_id is not _DEFAULT:
            body["eos"] = eos_id
        if deadline_ms is not None:
            body["deadline_ms"] = float(deadline_ms)
        if adapter is not None:
            body["adapter"] = adapter
        payload = json.dumps(body).encode()
        conn, resp = self._post_generate(payload)
        if resp.status != 200:
            try:
                err_body = json.loads(
                    resp.read().decode("utf-8", "replace") or "{}")
            except ValueError:
                err_body = {}
            finally:
                conn.close()
            self._raise_status(resp.status, err_body)
        handle = GenerationHandle()
        with self._inflight_lock:
            self._inflight.add(handle)
        threading.Thread(target=self._relay, args=(conn, resp, handle),
                         name=f"hvd-proc-relay-{self.name}",
                         daemon=True).start()
        return handle

    def _post_generate(self, payload: bytes):
        """The transport half of :meth:`submit`: bounded
        retry-with-backoff on errors raised while SENDING (nothing
        admitted — retry is safe), one shot on the response wait (the
        child may hold the stream — double-submit is the router's call,
        via the overload path)."""
        delay = self._backoff
        last: Optional[BaseException] = None
        for attempt in range(self._submit_retries + 1):
            conn = http.client.HTTPConnection(self._host, self._port,
                                              timeout=self._connect_timeout)
            try:
                conn.request("POST", "/generate", payload,
                             {"Content-Type": "application/json"})
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                last = e
                if attempt < self._submit_retries:
                    time.sleep(min(delay, self._backoff_cap))
                    delay *= 2
                    continue
                raise self._overload_from(
                    e, f"transport error sending submit after "
                       f"{attempt + 1} attempt(s)") from e
            try:
                # Headers arrive with the child's FIRST event; give the
                # wait the stream read timeout, not the connect timeout.
                if conn.sock is not None:
                    conn.sock.settimeout(self._read_timeout)
                return conn, conn.getresponse()
            except (OSError, http.client.HTTPException) as e:
                # Request fully sent: the child may have admitted the
                # stream. NOT client-retried (a blind retry could
                # double-submit); the overload mapping hands the verdict
                # to the router's dispatch walk.
                conn.close()
                raise self._overload_from(
                    e, "connection lost awaiting the submit verdict "
                       "(request was sent — the child may hold an "
                       "orphaned stream)") from e
        raise self._overload_from(last, "submit transport failed")

    def _overload_from(self, cause: Optional[BaseException],
                       what: str) -> ServerOverloadedError:
        err = ServerOverloadedError(
            f"replica {self.name} ({self._host}:{self._port}): {what} "
            f"({cause!r}) — mapped to the retryable-overload path, never "
            f"a silent loss")
        err.retry_after_ms = max(100.0, self._backoff * 1e3)
        return err

    def _raise_status(self, status: int, body: Dict[str, Any]) -> None:
        msg = str(body.get("error", f"HTTP {status}"))
        if status == 503:
            if body.get("retryable", True):
                # (A PreemptedError repr can land here too — preempted
                # past the budget before the FIRST token. At submit time
                # that is retryable overload: the dispatch walk tries the
                # next door. Only the mid-stream error line keeps the
                # typed verdict, via _wire_error.)
                err = ServerOverloadedError(msg)
                ra = body.get("retry_after_ms")
                if isinstance(ra, (int, float)):
                    err.retry_after_ms = float(ra)
                raise err
            raise ServerClosedError(msg)
        if status == 504:
            raise DeadlineExceededError(msg)
        if status == 400:
            raise ValueError(msg)
        raise WorkerFailureError(
            f"replica {self.name}: HTTP {status}: {msg}")

    def _relay(self, conn, resp, handle: GenerationHandle) -> None:
        """Reader thread: chunked JSON lines → handle events. A
        transport death mid-stream fails the handle with
        :class:`WorkerFailureError` — exactly the verdict the router's
        pump converts into a failover; a DEADLINE error line stays a
        deadline (the stream's own verdict, never failed over)."""
        try:
            for raw in iter(resp.readline, b""):
                line = raw.strip()
                if not line:
                    continue
                ev = json.loads(line)
                if ev.get("done"):
                    if "error" in ev:
                        handle._fail(self._wire_error(str(ev["error"])))
                    else:
                        handle._finish(
                            {k: v for k, v in ev.items() if k != "done"})
                    return
                if "token" in ev:
                    handle._emit(int(ev["token"]))
            handle._fail(WorkerFailureError(
                f"replica {self.name} closed the stream before the done "
                f"line"))
        except (OSError, http.client.HTTPException, ValueError) as e:
            handle._fail(WorkerFailureError(
                f"replica {self.name} connection lost mid-stream: {e!r}"))
        finally:
            conn.close()
            with self._inflight_lock:
                self._inflight.discard(handle)

    def _wire_error(self, text: str) -> Exception:
        if text.startswith("DeadlineExceededError"):
            return DeadlineExceededError(text)
        if text.startswith("PreemptedError"):
            # Keep the preemption verdict typed across the wire: the
            # router fails it over like any strand, but a FLEET-level
            # exhaustion must still report terminal reason
            # "preempted_exhausted" (priority congestion), not replica
            # death.
            return PreemptedError(f"replica {self.name}: {text}")
        return WorkerFailureError(f"replica {self.name}: {text}")

    def generate(self, tokens, timeout: Optional[float] = None, **kw):
        """Synchronous convenience (submit + result), mirroring the
        engine surface."""
        return self.submit(tokens, **kw).result(timeout)

    # -- lifecycle ----------------------------------------------------------

    def warmup(self) -> Tuple[str, ...]:
        """Block until the child reports ready (the worker warms its
        engine BEFORE publishing the ready file, so "ready" means
        compiled). Raises :class:`WorkerFailureError` on child exit or
        timeout — ``add_replica``'s warm thread then marks the handle
        dead, same as a failed thread-replica warmup."""
        deadline = time.monotonic() + self._ready_timeout
        while time.monotonic() < deadline:
            if self._proc is not None and self._proc.poll() is not None:
                raise WorkerFailureError(
                    f"replica {self.name} worker exited rc="
                    f"{self._proc.returncode} before reporting ready")
            if self._ensure_port():
                ready, _, _ = self.health()
                if ready:
                    return ("proc-ready",)
            time.sleep(0.05)
        raise WorkerFailureError(
            f"replica {self.name} worker not ready after "
            f"{self._ready_timeout}s")

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the child. ``drain=True`` asks the worker to finish its
        admitted streams first and WAITS for the streams this client is
        still relaying (the router's drain-on-evict contract crosses
        the process boundary); ``drain=False`` aborts, escalating
        SIGTERM → SIGKILL if the control message does not land.
        Idempotent, and safe on an already-dead child."""
        self._closed = True
        deadline = time.monotonic() + max(0.1, timeout)
        if self._proc is not None and self._proc.poll() is None:
            try:
                msg = json.dumps({"shutdown": {
                    "drain": bool(drain), "timeout": float(timeout)}})
                self._proc.stdin.write(msg.encode() + b"\n")
                self._proc.stdin.flush()
                self._proc.stdin.close()
            except (OSError, ValueError, AttributeError):
                pass
        if drain:
            # The child finishes the streams; this side must keep
            # relaying them — return only once every in-flight handle
            # has its terminal event (or the drain window closes).
            while time.monotonic() < deadline:
                with self._inflight_lock:
                    if not self._inflight:
                        break
                time.sleep(0.02)
            self.stats()    # final totals for the router's retire fold
        if self._proc is None:
            return
        try:
            self._proc.wait(max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            self._proc.terminate()
            try:
                self._proc.wait(2.0)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(5.0)


# -- spawning ---------------------------------------------------------------


def spawn_replica_factory(spec: Dict[str, Any], *,
                          host: str = "127.0.0.1",
                          python: Optional[str] = None,
                          run_dir: Optional[str] = None,
                          ready_timeout_s: float = 180.0,
                          client_kwargs: Optional[Dict[str, Any]] = None):
    """Build a ``factory(name) -> ProcReplicaClient`` for
    ``FleetRouter(factory=...)`` — the process factory that makes
    spawn/warm/drain/evict, the autoscaler, and the resize ingress work
    unchanged over subprocess replicas.

    ``spec`` is the JSON-able engine description the worker rebuilds
    from (see :func:`worker_main`): ``model`` (TransformerConfig kwargs,
    dtypes as strings), ``seed`` (param init — same seed + dims ⇒
    bit-identical weights in every child), ``generation``
    (GenerationConfig kwargs), optional ``warmup`` (default True),
    optional ``spec`` (speculative decoding — SpecConfig kwargs) and
    ``adapters`` (seeded LoRA tenants + quotas; see
    :func:`_build_adapters` — trees are re-derived from seeds in the
    child, never shipped as bytes).
    Each spawned child inherits the parent environment — fault specs
    (``HVD_FAULT_SPEC``) reach the child loop — and gets a PER-REPLICA
    flight-recorder dump dir (``$HVD_FLIGHTREC_DIR/<name>``) so two
    children's rank-0 post-mortems never collide."""
    base = dict(spec)
    kw = dict(client_kwargs or {})

    def factory(name: str) -> ProcReplicaClient:
        rd = run_dir or tempfile.mkdtemp(prefix="hvd-proc-")
        os.makedirs(rd, exist_ok=True)
        spec_path = os.path.join(rd, f"{name}.spec.json")
        ready_path = os.path.join(rd, f"{name}.ready.json")
        child_spec = dict(base)
        child_spec["name"] = name
        child_spec.setdefault("host", host)
        with open(spec_path, "w") as f:
            json.dump(child_spec, f)
        cmd = [python or sys.executable, "-m",
               "horovod_tpu.serve.proc_replica",
               "--spec", spec_path, "--ready-file", ready_path,
               "--parent-pid", str(os.getpid())]
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE)
        client = ProcReplicaClient(
            name, proc, host=child_spec["host"], ready_file=ready_path,
            ready_timeout_s=ready_timeout_s,
            default_deadline_ms=(child_spec.get("generation")
                                 or {}).get("default_deadline_ms"), **kw)
        factory.clients[name] = client
        return client

    # Liveness wiring for FleetRouter(liveness_factory=...): each
    # spawned client implements the CoordClient ``aborted`` surface
    # (pid + heartbeat file + /healthz two-strike), so the existing
    # heartbeat_liveness adapter consumes it unchanged. Names this
    # factory never minted (thread replicas attached by hand) get None
    # — the handle falls back to its default in-process probe.
    factory.clients = {}

    def liveness_factory(name: str):
        client = factory.clients.get(name)
        if client is None:
            return None
        from .fleet import heartbeat_liveness
        return heartbeat_liveness(client)

    factory.liveness_factory = liveness_factory
    return factory


# -- the worker entrypoint --------------------------------------------------


def _arm_parent_watchdog(parent_pid: int, engine_ref: list,
                         poll_s: float = 1.0) -> None:
    """Children must not orphan: if the parent dies (even SIGKILL — the
    stdin-EOF path can't fire when the pipe fd leaked or stdin was
    replaced), this reparents to init and ``getppid()`` changes; abort
    the engine and exit. ``engine_ref`` is a one-slot list filled once
    the engine exists."""
    def _watch():
        while True:
            if os.getppid() != parent_pid:
                eng = engine_ref[0] if engine_ref else None
                if eng is not None:
                    try:
                        eng.shutdown(drain=False, timeout=2.0)
                    except Exception:  # noqa: BLE001 — exiting anyway
                        pass
                os._exit(3)
            time.sleep(poll_s)
    threading.Thread(target=_watch, daemon=True,
                     name="hvd-proc-parent-watchdog").start()


def _arm_heartbeat(hb_file: str, period_s: float = 1.0) -> None:
    """The worker's liveness beat: rewrite ``hb_file`` every
    ``period_s`` (atomic tmp + replace — the parent keys staleness on
    the file's mtime, so a torn write must be impossible). A SIGKILLed
    or SIGSTOPed worker stops beating and the parent's
    :meth:`ProcReplicaClient.aborted` verdict flips within the
    heartbeat timeout — the same silence-means-dead contract the coord
    plane's heartbeats keep."""
    def _beat():
        while True:
            try:
                tmp = hb_file + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"ts": time.time(), "pid": os.getpid()}, f)
                os.replace(tmp, hb_file)
            except OSError:
                pass        # a full disk must not kill the worker
            time.sleep(period_s)
    threading.Thread(target=_beat, daemon=True,
                     name="hvd-proc-heartbeat").start()


def _resolve_dtype(jnp, name):
    table = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
             "float16": jnp.float16}
    if name not in table:
        raise ValueError(
            f"spec dtype must be one of {sorted(table)}, got {name!r}")
    return table[name]


def _build_adapters(mcfg, ad: Optional[Dict[str, Any]]):
    """The worker's adapter plane from the spec's JSON ``"adapters"``
    block: ``{"rank", "alpha", "capacity", "entries": [{"name", "seed",
    "b_scale", "quota", "weight", "priority", "slo_ttft_ms"}, ...]}``.
    Trees are re-derived from per-entry seeds
    (``init_adapter(PRNGKey(seed), ...)``), not shipped as bytes —
    the same trick the base params use, so a replacement child after a
    SIGKILL holds bit-identical tables and per-tenant failover replay
    stays digest-exact. Per entry, all optional: ``quota`` caps that
    tenant's in-flight streams, ``weight``/``priority`` set its fair-
    scheduling class, ``slo_ttft_ms`` its TTFT SLO target. The
    no-adapter tenant takes the same knobs spelled ``"base_quota"``,
    ``"base_weight"``, ``"base_priority"``, ``"base_slo_ttft_ms"``."""
    if not ad:
        return None
    import jax

    from ..parallel.lora import LoraConfig, init_adapter
    from .adapters import AdapterRegistry

    entries = list(ad.get("entries") or [])
    if not entries:
        return None
    lora = LoraConfig(rank=int(ad.get("rank", 4)),
                      alpha=float(ad.get("alpha", 8.0)))
    reg = AdapterRegistry(mcfg, lora,
                          capacity=int(ad.get("capacity", len(entries))))
    for e in sorted(entries, key=lambda x: str(x.get("name"))):
        tree = init_adapter(jax.random.PRNGKey(int(e["seed"])), mcfg,
                            lora, b_scale=float(e.get("b_scale", 0.0)))
        q = e.get("quota")
        name = str(e["name"])
        reg.load(name, tree, quota=int(q) if q is not None else None)
        _apply_policy(reg, name, e.get("weight"), e.get("priority"),
                      e.get("slo_ttft_ms"))
    bq = ad.get("base_quota")
    if bq is not None:
        reg.set_quota("base", int(bq))
    _apply_policy(reg, "base", ad.get("base_weight"),
                  ad.get("base_priority"), ad.get("base_slo_ttft_ms"))
    return reg


def _apply_policy(reg, tenant: str, weight, priority, slo_ttft_ms) -> None:
    """Stamp one tenant's optional scheduling policy onto the registry
    (absent keys leave the engine defaults: weight 1.0, priority 0, no
    SLO)."""
    if weight is not None:
        reg.set_weight(tenant, float(weight))
    if priority is not None:
        reg.set_priority(tenant, int(priority))
    if slo_ttft_ms is not None:
        reg.set_slo_ttft_ms(tenant, float(slo_ttft_ms))


def worker_main(argv: Optional[List[str]] = None) -> int:
    """The replica worker: spec → engine → warmup → HttpServer → ready
    file, then block on the stdin control channel until the parent says
    shutdown (or disappears)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m horovod_tpu.serve.proc_replica",
        description="Out-of-process serving replica worker")
    ap.add_argument("--spec", required=True,
                    help="path to the JSON engine spec")
    ap.add_argument("--ready-file", required=True,
                    help="path the worker writes its readiness/port to")
    ap.add_argument("--parent-pid", type=int, default=0,
                    help="parent pid for the orphan watchdog (0 = off)")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    name = spec.get("name", "proc")
    # Per-replica flight-recorder dir: every child dumps as rank 0, so
    # siblings sharing the parent's dump dir would overwrite each
    # other's post-mortems.
    base_dir = os.environ.get("HVD_FLIGHTREC_DIR")
    if base_dir:
        child_dir = os.path.join(base_dir, name)
        os.makedirs(child_dir, exist_ok=True)
        os.environ["HVD_FLIGHTREC_DIR"] = child_dir
    engine_ref: list = []
    if args.parent_pid:
        _arm_parent_watchdog(args.parent_pid, engine_ref)

    # Heavy imports AFTER the watchdog is armed: a parent that dies
    # during the child's jax import must still reap it.
    import jax
    import jax.numpy as jnp

    from ..parallel.transformer import TransformerConfig, init_params
    from .generate import GenerationConfig, GenerationEngine
    from .server import HttpServer
    from .spec import SpecConfig

    model_kw = dict(spec.get("model") or {})
    for key in ("dtype", "unembed_dtype"):
        if isinstance(model_kw.get(key), str):
            model_kw[key] = _resolve_dtype(jnp, model_kw[key])
    mcfg = TransformerConfig(**model_kw)
    params = init_params(jax.random.PRNGKey(int(spec.get("seed", 0))), mcfg)
    gcfg = GenerationConfig(**(spec.get("generation") or {}))
    # Optional planes, both JSON-derived so every sibling child is
    # bit-identical: "spec" → speculative decoding (SpecConfig kwargs),
    # "adapters" → LoRA tenants re-derived from per-entry seeds (same
    # seed + dims ⇒ the same adapter bytes in every child, exactly like
    # the base params — so per-tenant stream digests stay comparable
    # across thread and subprocess topologies).
    spec_cfg = (SpecConfig.from_spec(spec["spec"])
                if spec.get("spec") else None)
    registry = _build_adapters(mcfg, spec.get("adapters"))
    eng = GenerationEngine(params, mcfg, gcfg, adapters=registry,
                           spec=spec_cfg)
    eng.serve_name = name       # fault clauses + flightrec key on it
    engine_ref.append(eng)
    if spec.get("warmup", True):
        eng.warmup()
    srv = HttpServer(generate=eng, host=spec.get("host", "127.0.0.1"),
                     port=int(spec.get("port", 0)))
    srv.start()
    ready = {"ready": True, "pid": os.getpid(),
             "host": srv.host, "port": srv.port, "name": name}
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ready, f)
    os.replace(tmp, args.ready_file)    # atomic: no torn ready read
    _arm_heartbeat(args.ready_file + ".hb")
    print(f"[proc_replica] {name}: ready on {srv.host}:{srv.port} "
          f"(pid {os.getpid()})", flush=True)

    closed = False
    try:
        for raw in sys.stdin.buffer:
            try:
                msg = json.loads(raw)
            except ValueError:
                continue
            sd = msg.get("shutdown")
            if sd is not None:
                eng.shutdown(drain=bool(sd.get("drain", True)),
                             timeout=float(sd.get("timeout", 30.0)))
                closed = True
                break
    except KeyboardInterrupt:
        pass
    finally:
        if not closed:
            # stdin EOF: the parent died or dropped the pipe — abort,
            # never orphan (mirrors the watchdog verdict).
            eng.shutdown(drain=False, timeout=5.0)
        # Let in-flight handler threads flush their final chunks before
        # the listener goes away.
        time.sleep(0.2)
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(worker_main())
