"""Replica membership and admission routing for a serving fleet.

One :class:`~.generate.GenerationEngine` (or single-shot
:class:`~.engine.Engine`) is a *replica*: one decode batch over its own
slots and KV block pool. The ROADMAP's "millions of users" traffic does
not fit one replica, and simply running N engines behind N ports pushes
the load-balancing problem onto every client. :class:`FleetRouter` is
the missing layer: ONE front door that owns admission for the whole
fleet and fans requests out to N replicas.

Design rules, each load-bearing:

* **The router is the single admission point, not a second buffer.**
  Every replica already owns a bounded admission queue with
  overload-at-the-door semantics (PR 2); parking requests in a router
  queue in front of those would strand them when their eventual replica
  dies and would hide queue pressure from the autoscaler. Admission
  happens once, at :meth:`FleetRouter.submit`: pick the least-loaded
  READY replica, hand the request to its queue, and fail over to the
  next replica if that door is shut. The fleet rejects only when EVERY
  ready replica rejected — one saturated replica never bounces traffic
  the rest could serve.
* **Least-queue-depth dispatch reads the metrics the replicas already
  export.** :meth:`ReadinessMixin.load` is the same number `/metrics`
  publishes as ``hvd_queue_depth`` (+ active decode rows); no parallel
  bookkeeping that could drift from what the operator's dashboard says.
* **Readiness is the PR-4 ``/healthz`` contract, per replica.** A
  ``warming`` replica (engine built, ``warmup()`` still compiling)
  takes NO traffic — routing to it would make a user pay the compile. A
  ``draining`` replica takes no NEW traffic but finishes every stream
  already admitted — scale-down may never lose an admitted stream
  (the bit-identity drill in tests/test_fleet.py and the ci.sh
  autoscaler leg pin exactly this).
* **Liveness is the existing ``coord/`` heartbeat plane, not a second
  protocol.** Thread replicas are in-process: their loop thread is the
  ground truth. Multi-process replicas form a coordinator world whose
  heartbeat timeouts (PR 1) already detect silence; a
  :class:`ReplicaHandle` wires ``liveness=`` to that plane
  (:func:`~.fleet.heartbeat_liveness`) and the router EVICTS on its
  verdict — it never grows its own poller.

The router duck-types the engine surface (``submit`` / ``generate`` /
``infer`` / ``stats`` / ``health`` / ``prom_collect`` / ``warmup`` /
``shutdown``), so :class:`~.server.HttpServer` mounts a fleet exactly
where it mounted one engine: ``POST /generate`` routes through the
router, ``GET /metrics`` merges every replica's samples (each carrying
a ``replica=`` label) with the fleet series into ONE valid exposition,
``GET /healthz`` reports fleet readiness (>= 1 ready replica).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..exceptions import ServerClosedError, ServerOverloadedError
from .metrics import FleetMetrics

_log = logging.getLogger("horovod_tpu.serve.fleet")

# Replica states, in dispatch-priority order of meaning:
#   warming  — engine exists, warmup() not finished: takes NO traffic
#   ready    — routable
#   draining — scale-down in progress: finishes admitted streams, no new
#   dead     — liveness said gone (heartbeat abort / loop thread died)
REPLICA_STATES = ("ready", "warming", "draining", "dead")


class ReplicaHandle:
    """One fleet member: a name, an engine, and the membership verdicts
    the router needs (state, load, liveness).

    ``liveness`` is an optional zero-arg callable returning False once
    the replica's backing process is gone — for multi-process replicas
    this is the coord heartbeat plane
    (:func:`~.fleet.heartbeat_liveness`); thread replicas default to
    their engine loop thread's aliveness. The handle never invents its
    own poller.
    """

    def __init__(self, name: str, engine: Any,
                 liveness: Optional[Callable[[], bool]] = None):
        self.name = name
        self.engine = engine
        self._liveness = liveness
        self._draining = False
        self._dead = False
        self._drain_thread: Optional[threading.Thread] = None

    def alive(self) -> bool:
        if self._dead:
            return False
        if self._liveness is not None:
            try:
                return bool(self._liveness())
            except Exception:  # noqa: BLE001 — a broken probe is "gone"
                return False
        # Thread replicas: the engine loop thread is the ground truth —
        # it only exits on drain-complete or abort, both terminal.
        thread = getattr(self.engine, "_thread", None)
        if thread is not None and not thread.is_alive() \
                and not getattr(self.engine, "_closed", False):
            return False
        return True

    def state(self) -> str:
        if not self.alive():
            return "dead"
        if self._draining or getattr(self.engine, "_closed", False):
            return "draining"
        ready, _, _ = self.engine.health()
        return "ready" if ready else "warming"

    def load(self) -> int:
        """Dispatch pressure: queued + executing rows — the same number
        this replica's ``/metrics`` exports (``hvd_queue_depth`` +
        ``hvd_active_slots``)."""
        try:
            return int(self.engine.load())
        except Exception:  # noqa: BLE001 — a dying replica reads as busy
            return 1 << 30


class FleetRouter:
    """Admission router + replica membership for N serving engines.

    Args:
      engines: pre-built engines to wrap (replica names ``r0..rN-1``).
      factory: ``factory(name) -> engine`` for membership changes —
        required by :meth:`add_replica` (and therefore by the
        :class:`~.fleet.FleetAutoscaler`).
      initial: replicas to build from ``factory`` at construction.
      liveness_factory: optional ``liveness_factory(name) -> callable``
        wiring each new replica's liveness to the coord heartbeat plane
        (multi-process fleets); thread replicas leave it None.
      drain_timeout: seconds a drain-on-evict waits for the replica to
        finish its admitted streams before the handle is force-reaped.
      adapter_source: optional ``adapter_source(name) -> adapter tree``
        backing the adapter-affine dispatch's lazy-load path: a request
        whose adapter is resident on NO ready replica is dispatched
        least-load and the adapter hot-loaded there first (typically a
        closure over ``parallel.checkpoint.restore_adapter`` — the
        manifest-CRC walk then guards every lazy load). Without it, a
        non-resident adapter is a ``ValueError`` naming the remedy.
    """

    def __init__(self, engines: Optional[List[Any]] = None, *,
                 factory: Optional[Callable[[str], Any]] = None,
                 initial: int = 0,
                 liveness_factory: Optional[Callable] = None,
                 drain_timeout: float = 60.0,
                 adapter_source: Optional[Callable[[str], Any]] = None):
        self._factory = factory
        self._liveness_factory = liveness_factory
        self._drain_timeout = drain_timeout
        self._adapter_source = adapter_source
        self._lock = threading.Lock()
        self._metrics = FleetMetrics()
        self._replicas: List[ReplicaHandle] = []
        self._seq = 0
        self._closed = False
        self._t0 = time.monotonic()
        # Final counter totals of replicas that LEFT the membership:
        # the fleet aggregates in stats() add these baselines so
        # cumulative fields (requests_total, tokens_generated_total,
        # prefix hits, rejections) never go BACKWARDS across a shrink —
        # the same monotonicity rule FleetMetrics.forget_replica keeps
        # for the dispatch counter.
        self._retired_totals: Dict[str, float] = {}
        self._retired_gen_totals: Dict[str, float] = {}
        self._retired_tenant_totals: Dict[str, Dict[str, float]] = {}
        # Fleet-wide concurrency high-water, sampled at dispatch and
        # stats boundaries. Summing per-replica peaks would add maxima
        # that never coincided (and the sum would DROP when a replica
        # retires) — a "peak" must be monotone and fleet-coincident.
        self._peak_active = 0
        for eng in engines or []:
            self._attach(eng)
        for _ in range(initial):
            if factory is None:
                raise ValueError(
                    "FleetRouter(initial=N) needs a factory= to build "
                    "replicas from")
            name = self._next_name()
            self._attach(factory(name), name=name)
        self._refresh_gauges()

    # -- membership --------------------------------------------------------

    def _next_name(self) -> str:
        name = f"r{self._seq}"
        self._seq += 1
        return name

    def _attach(self, engine: Any, name: Optional[str] = None
                ) -> ReplicaHandle:
        with self._lock:
            if name is None:
                name = self._next_name()
            liveness = (self._liveness_factory(name)
                        if self._liveness_factory else None)
            handle = ReplicaHandle(name, engine, liveness=liveness)
            self._replicas.append(handle)
        return handle

    def replicas(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._replicas)

    def counts(self) -> Dict[str, int]:
        """Membership by state (``{"ready": ..., "warming": ...,
        "draining": ..., "dead": ...}``)."""
        out = {s: 0 for s in REPLICA_STATES}
        for h in self.replicas():
            out[h.state()] += 1
        return out

    def add_replica(self, warm: bool = True) -> ReplicaHandle:
        """Grow the fleet by one replica. The engine is built
        synchronously (cheap — allocations, no compiles); ``warmup()``
        runs on a background thread, during which the replica reads
        ``warming`` and takes no traffic. Scale-up is therefore
        hitless: current replicas keep serving while the newcomer
        compiles."""
        if self._closed:
            raise ServerClosedError("fleet router is shut down")
        if self._factory is None:
            raise RuntimeError(
                "add_replica needs FleetRouter(factory=...) — the router "
                "cannot build engines it was never taught to build")
        with self._lock:
            name = self._next_name()
        handle = self._attach(self._factory(name), name=name)

        def _warm():
            try:
                handle.engine.warmup()
            except Exception as e:  # noqa: BLE001 — a failed warm = dead
                _log.warning("replica %s failed warmup: %r", handle.name, e)
                handle._dead = True
            self._refresh_gauges()

        if warm:
            t = threading.Thread(target=_warm,
                                 name=f"hvd-fleet-warm-{name}", daemon=True)
            t.start()
        self._refresh_gauges()
        return handle

    def remove_replica(self, name: Optional[str] = None) -> ReplicaHandle:
        """Shrink the fleet by one replica, drain-on-evict: the replica
        stops taking NEW traffic immediately, finishes every stream it
        already admitted (the engine's ``shutdown(drain=True)``
        contract), and only then leaves the membership — no admitted
        stream is ever lost on scale-down. Returns the draining handle
        (``handle._drain_thread.join()`` to wait)."""
        with self._lock:
            candidates = [h for h in self._replicas if not h._draining]
            if name is not None:
                candidates = [h for h in candidates if h.name == name]
            if not candidates:
                raise ValueError(
                    f"no evictable replica"
                    f"{' named ' + name if name else ''} "
                    f"(states: {[ (h.name, h.state()) for h in self._replicas ]})")
            # Prefer a READY replica with the least to drain; fall back
            # to whatever is left (a warming replica drains instantly).
            ready = [h for h in candidates if h.state() == "ready"]
            pool = ready or candidates
            handle = min(pool, key=lambda h: h.load())
            handle._draining = True

        def _drain():
            try:
                handle.engine.shutdown(drain=True,
                                       timeout=self._drain_timeout)
            except Exception as e:  # noqa: BLE001
                _log.warning("replica %s drain raised: %r", handle.name, e)
            self._retire(handle)
            self._refresh_gauges()

        t = threading.Thread(target=_drain,
                             name=f"hvd-fleet-drain-{handle.name}",
                             daemon=True)
        handle._drain_thread = t
        t.start()
        self._refresh_gauges()
        return handle

    def poll(self) -> Dict[str, int]:
        """One membership sweep (the autoscaler calls this every tick):
        evict replicas whose liveness verdict says gone — a dead replica
        cannot drain, so its streams fail fast instead of hanging their
        clients — and refresh the ``hvd_fleet_replicas`` gauges.
        Returns :meth:`counts` after the sweep."""
        for h in self.replicas():
            if h.state() == "dead":
                self._evict_dead(h)
        self._refresh_gauges()
        return self.counts()

    def _evict_dead(self, handle: ReplicaHandle) -> None:
        _log.warning("replica %s is dead (liveness verdict) — evicting "
                     "without drain", handle.name)
        handle._dead = True
        self._retire(handle)

        def _reap():
            try:
                handle.engine.shutdown(drain=False, timeout=5.0)
            except Exception:  # noqa: BLE001
                pass

        threading.Thread(target=_reap, name=f"hvd-fleet-reap-{handle.name}",
                         daemon=True).start()

    def _retire(self, handle: ReplicaHandle) -> None:
        """Remove ``handle`` from membership, folding its final counter
        totals into the retired baselines so the fleet aggregates stay
        monotone (best-effort for a dead replica whose stats raise).
        Exactly-once: the fold happens only on the call that wins the
        membership removal — a drain completing while a liveness
        verdict evicts the same replica must not double-count its
        history."""
        snap: Dict[str, Any] = {}
        try:
            snap = handle.engine.stats()
        except Exception:  # noqa: BLE001 — a dead replica keeps what it had
            pass
        with self._lock:
            if handle not in self._replicas:
                return
            self._replicas.remove(handle)
            for key in self._COUNTER_KEYS:
                v = snap.get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._retired_totals[key] = (
                        self._retired_totals.get(key, 0) + v)
            for key in self._GEN_SUM_KEYS:
                v = (snap.get("generation") or {}).get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._retired_gen_totals[key] = (
                        self._retired_gen_totals.get(key, 0) + v)
            for tenant, tv in (snap.get("tenants") or {}).items():
                base = self._retired_tenant_totals.setdefault(tenant, {})
                for key in self._TENANT_SUM_KEYS:
                    v = tv.get(key)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        base[key] = base.get(key, 0) + v
        self._metrics.forget_replica(handle.name)

    def _note_peak(self) -> None:
        """Sample the fleet's CURRENT total active streams into the
        high-water mark (called at dispatch and stats boundaries —
        approximate between samples; per-replica exact peaks stay in
        the nested snapshots)."""
        active = 0
        for h in self.replicas():
            try:
                active += h.engine._active_rows()
            except Exception:  # noqa: BLE001 — a dying replica counts 0
                pass
        with self._lock:
            # Compare+assign under the lock: two dispatch threads racing
            # the check-then-set could otherwise publish the SMALLER
            # sample last and regress the high-water.
            if active > self._peak_active:
                self._peak_active = active

    def adapters_resident(self) -> Optional[int]:
        """DISTINCT adapters resident across live replicas (the
        ``/healthz`` and fleet-line number), or None when no replica
        carries a registry (an adapter-free fleet)."""
        names: set = set()
        any_registry = False
        for h in self.replicas():
            fn = getattr(h.engine, "adapter_names", None)
            if not callable(fn):
                continue
            try:
                res = fn()
            except Exception:  # noqa: BLE001 — a dying replica counts 0
                continue
            if res is not None:
                any_registry = True
                names.update(res)
        return len(names) if any_registry else None

    def _refresh_gauges(self) -> None:
        self._metrics.set_replicas(self.counts())
        self._metrics.set_adapters_resident(self.adapters_resident())

    # -- dispatch ----------------------------------------------------------

    @staticmethod
    def _resident_names(handle: ReplicaHandle) -> Tuple[str, ...]:
        """A replica's resident adapters (empty for engines without a
        registry — they can never serve an adapter request)."""
        fn = getattr(handle.engine, "adapter_names", None)
        if not callable(fn):
            return ()
        try:
            return tuple(fn() or ())
        except Exception:  # noqa: BLE001 — a dying replica reads empty
            return ()

    def _lazy_load(self, handle: ReplicaHandle, adapter: str) -> None:
        """The affinity-miss path: fetch the adapter from
        ``adapter_source`` and hot-load it into ``handle`` before the
        dispatch. Raises ``ValueError`` when this replica cannot take it
        (no source, no registry, table full) — the dispatch loop then
        fails over."""
        if adapter in self._resident_names(handle):
            return      # a concurrent submit already loaded it here
        if self._adapter_source is None:
            raise ValueError(
                f"adapter {adapter!r} is not resident on any ready "
                f"replica and the router has no adapter_source= to "
                f"lazy-load it from — load it on a replica or pass "
                f"adapter_source=")
        load = getattr(handle.engine, "load_adapter", None)
        if not callable(load):
            raise ValueError(
                f"replica {handle.name} cannot host adapters "
                f"(engine has no load_adapter)")
        # Propagate the tenant's quota from a replica that already hosts
        # it: a lazy load must not mint a quota-free copy of the adapter
        # (one saturated replica would otherwise let the tenant run
        # unlimited streams through every replica it seeds).
        quota = None
        for other in self.replicas():
            reg = getattr(other.engine, "adapters", None)
            if reg is None:
                continue
            try:
                if adapter in (reg.resident() or ()):
                    quota = reg.quota(adapter)
                    if quota is not None:
                        break
            except Exception:  # noqa: BLE001 — a dying replica has no say
                continue
        try:
            load(adapter, self._adapter_source(adapter), quota=quota)
        except RuntimeError:
            # Raced a concurrent submit that loaded the same adapter
            # (and already has a live stream refcounting its row, so the
            # registry refused our redundant reload): it IS resident —
            # the dispatch can proceed.
            if adapter not in self._resident_names(handle):
                raise

    def submit(self, *args, **kwargs):
        """Admit one request to the fleet: least-loaded READY replica
        first, failing over across the ready set. A request carrying
        ``adapter=`` dispatches adapter-AFFINE: ready replicas that
        already have the adapter resident come first (least-load
        tiebreak unchanged — their KV/compile state is equally warm, so
        load still orders within the resident set), the rest fall back
        to least-load + lazy hot-load via ``adapter_source``. Raises
        :class:`ServerOverloadedError` only when EVERY ready replica
        rejected (or none is ready yet — a warming fleet is a retryable
        condition), :class:`ServerClosedError` once the router (or the
        whole membership) is shut down, ``ValueError`` when an adapter
        is resident nowhere and cannot be lazy-loaded. Returns whatever
        the replica's ``submit`` returns (a
        :class:`~.generate.GenerationHandle` for generation fleets, a
        ``Future`` for single-shot fleets)."""
        if self._closed:
            raise ServerClosedError("fleet router is shut down")
        adapter = kwargs.get("adapter")
        snapshot = self.replicas()
        ready = [h for h in snapshot if h.state() == "ready"]
        resident: Dict[str, bool] = {}
        if adapter is not None:
            resident = {h.name: adapter in self._resident_names(h)
                        for h in ready}
            ready.sort(key=lambda h: (not resident[h.name], h.load()))
        else:
            ready.sort(key=lambda h: h.load())
        if not ready:
            warming = sum(1 for h in snapshot if h.state() == "warming")
            if warming:
                raise ServerOverloadedError(
                    f"no ready replicas yet ({warming} warming) — retry "
                    f"after backoff")
            if self._factory is not None:
                # An open router with a factory is one autoscaler tick
                # away from a below-min refill — a terminal "closed"
                # here would tell well-behaved clients to stop retrying
                # a fleet about to heal.
                raise ServerOverloadedError(
                    "no live replicas right now (the fleet can refill) "
                    "— retry after backoff")
            raise ServerClosedError(
                "fleet has no live replicas (all drained or dead)")
        last: Optional[BaseException] = None
        hosting_error: Optional[ValueError] = None
        saw_backpressure = False
        lazy_loaded = False
        for h in ready:
            if adapter is not None and not resident.get(h.name):
                if lazy_loaded:
                    # At most ONE lazy load per dispatch: a burst that
                    # overloads the freshly-loaded replica must read as
                    # retryable overload, not replicate the adapter into
                    # every table on the failover walk (rows are never
                    # auto-evicted — proliferation would turn transient
                    # backpressure into permanently full tables). Spread
                    # stays demand-driven: each retry may seed one more
                    # replica while the resident set stays saturated.
                    continue
                try:
                    self._lazy_load(h, adapter)
                    lazy_loaded = True
                except ValueError as e:
                    # This replica can't take the adapter (no source /
                    # no registry / table full): fail over.
                    last = hosting_error = e
                    continue
            try:
                out = h.engine.submit(*args, **kwargs)
            except ServerOverloadedError as e:
                last = e
                saw_backpressure = True
                continue
            except ServerClosedError as e:
                # Raced a drain decision between the snapshot and the
                # submit: that replica's door is shut, not the fleet's.
                last = e
                saw_backpressure = True
                continue
            except ValueError as e:
                if adapter is None:
                    raise
                # An adapter submit can lose an evict race: the adapter
                # was resident when this loop snapshotted residency, and
                # gone by the time submit retained it. Other replicas may
                # still host it — fail over instead of erroring the
                # request terminally. (A genuinely malformed request
                # raises the same ValueError on EVERY replica with no
                # backpressure seen, and surfaces below unchanged.)
                last = hosting_error = e
                continue
            self._metrics.on_dispatch(h.name)
            if adapter is not None:
                self._metrics.on_adapter_dispatch(
                    "affine" if resident.get(h.name) else "miss")
            self._note_peak()
            return out
        if adapter is not None and hosting_error is not None \
                and not saw_backpressure:
            # EVERY ready replica failed to even HOST the adapter — a
            # config problem, not backpressure; retrying would never
            # help. (If any hosting-capable replica merely rejected on
            # load, the condition IS retryable — fall through to the
            # overload below.)
            raise hosting_error
        raise ServerOverloadedError(
            f"all {len(ready)} ready replicas rejected the request "
            f"(last: {last}) — grow the fleet or shed load")

    def generate(self, tokens, timeout: Optional[float] = None, **kw):
        """Synchronous generation through the fleet (submit + result)."""
        return self.submit(tokens, **kw).result(timeout)

    def infer(self, inputs, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None):
        """Synchronous single-shot inference through a fleet of
        :class:`~.engine.Engine` replicas (the ``/predict`` path)."""
        return self.submit(inputs, deadline_ms=deadline_ms).result(timeout)

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> Tuple[str, ...]:
        """Warm every current replica (sequentially — deploy-time code;
        mid-run growth warms on its own thread via
        :meth:`add_replica`). Returns the replica names warmed."""
        warmed = []
        for h in self.replicas():
            if h.state() == "warming":
                h.engine.warmup()
            warmed.append(h.name)
        self._refresh_gauges()
        return tuple(warmed)

    def shutdown(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the fleet. ``drain=True`` finishes every admitted stream
        on every replica (drained concurrently) first. Idempotent."""
        if self._closed:
            return
        self._closed = True
        handles = self.replicas()
        threads = []
        for h in handles:
            t = threading.Thread(
                target=lambda h=h: h.engine.shutdown(drain=drain,
                                                     timeout=timeout),
                name=f"hvd-fleet-stop-{h.name}", daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout)
        for h in handles:
            if h._drain_thread is not None:
                h._drain_thread.join(timeout)
        self._refresh_gauges()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- health / stats / metrics ------------------------------------------

    def health(self) -> Tuple[bool, str, int]:
        """Fleet-level ``/healthz``: ready iff >= 1 replica is ready.
        Status mirrors the per-engine vocabulary (``ok`` / ``warming`` /
        ``draining``) so load balancers need no new parser; the per-state
        breakdown lives in :meth:`fleet_health`."""
        c = self.counts()
        # Exclude dead replicas from the depth sum: their load() reads
        # as the 1<<30 dispatch-ordering sentinel, which would turn the
        # /healthz queue_depth into a nonsense spike until the next
        # membership sweep evicts them.
        depth = sum(h.load() for h in self.replicas()
                    if h.state() != "dead")
        if self._closed:
            return False, "draining", depth
        if c["ready"] >= 1:
            return True, "ok", depth
        if c["warming"] >= 1:
            return False, "warming", depth
        return False, "draining", depth

    def fleet_health(self) -> Dict[str, int]:
        """Membership breakdown for the ``/healthz`` body."""
        return self.counts()

    def ttft_totals(self) -> Tuple[float, int]:
        """Fleet-cumulative ``(ttft_seconds_sum, count)`` summed from
        each replica's ``hvd_generate_ttft_seconds`` histogram — the
        rate()-able pair the autoscaler differences between polls."""
        s, n = 0.0, 0
        for h in self.replicas():
            m = getattr(h.engine, "_metrics", None)
            if m is None or not hasattr(m, "ttft_totals"):
                continue
            ds, dn = m.ttft_totals()
            s += ds
            n += dn
        return s, n

    # /stats keys summed across replicas (the fleet-aggregate view the
    # bench and dashboards read; per-replica truth nests under
    # "replicas"). Percentile fields cannot be summed and are omitted —
    # scrape the histograms for fleet quantiles. _COUNTER_KEYS are the
    # CUMULATIVE subset: a retiring replica's final values fold into the
    # retired baseline so they never go backwards across a shrink;
    # gauges (queue depth, slots) reflect live membership only.
    _COUNTER_KEYS = ("requests_total", "responses_total",
                     "rejected_overload", "rejected_slots_full",
                     "rejected_blocks_exhausted", "rejected_tenant_quota",
                     "expired_deadline",
                     "cancelled_shutdown", "batches_total",
                     "batch_rows_total", "batch_live_rows_total")
    # (peak_active_slots is NOT summed: the fleet peak is the router's
    # own sampled high-water — see _note_peak.)
    _GAUGE_KEYS = ("queue_depth", "active_slots", "max_slots")
    _SUM_KEYS = _COUNTER_KEYS + _GAUGE_KEYS
    _GEN_SUM_KEYS = ("generations_total", "tokens_generated_total",
                     "prefix_hits_total", "prefix_misses_total",
                     "prefix_hit_blocks_total", "prefix_lookup_blocks_total")
    # Per-tenant counters summed across replicas (+ retired baselines —
    # same monotonicity rule); tenant percentile fields cannot be summed
    # and stay in the nested per-replica snapshots (scrape the
    # hvd_tenant_* histograms for fleet-wide tenant quantiles).
    _TENANT_SUM_KEYS = ("generations_total", "tokens_generated_total")

    def stats(self) -> Dict:
        """The fleet ``/stats`` snapshot: aggregate counters at the top
        (same key names as one engine, so existing consumers keep
        reading), per-replica snapshots under ``"replicas"``, and the
        fleet plane (membership, dispatch, scale events) under
        ``"fleet"``."""
        self._note_peak()
        per: Dict[str, Dict] = {}
        states: Dict[str, str] = {}
        for h in self.replicas():
            try:
                per[h.name] = h.engine.stats()
            except Exception as e:  # noqa: BLE001 — a dying replica's
                per[h.name] = {"error": repr(e)}   # stats must not 500 /stats
            states[h.name] = h.state()
        snap: Dict[str, Any] = {
            "uptime_seconds": time.monotonic() - self._t0,
            "kv_layout": None,
            "max_len": None,
        }
        with self._lock:
            retired = dict(self._retired_totals)
            retired_gen = dict(self._retired_gen_totals)
            retired_tenants = {t: dict(v) for t, v in
                               self._retired_tenant_totals.items()}
        for key in self._SUM_KEYS:
            vals = [p.get(key) for p in per.values()
                    if isinstance(p.get(key), (int, float))]
            snap[key] = sum(vals) + retired.get(key, 0) if (
                vals or key in retired) else 0
        gen: Dict[str, Any] = {}
        for key in self._GEN_SUM_KEYS:
            vals = [p.get("generation", {}).get(key) for p in per.values()
                    if isinstance(p.get("generation", {}).get(key),
                                  (int, float))]
            gen[key] = sum(vals) + retired_gen.get(key, 0)
        snap["generation"] = gen
        snap["peak_active_slots"] = self._peak_active
        rows, live = snap.get("batch_rows_total", 0), snap.get(
            "batch_live_rows_total", 0)
        snap["batch_fill_ratio"] = (live / rows) if rows else None
        for p in per.values():
            if snap["kv_layout"] is None and "kv_layout" in p:
                snap["kv_layout"] = p["kv_layout"]
            if "max_len" in p:
                snap["max_len"] = max(snap["max_len"] or 0, p["max_len"])
        blocks = [p["blocks"] for p in per.values() if "blocks" in p]
        if blocks and len(blocks) == len(per):
            snap["blocks"] = {k: sum(b.get(k, 0) for b in blocks)
                              for k in blocks[0]}
            sizes = {p.get("block_size") for p in per.values()}
            if len(sizes) == 1:
                snap["block_size"] = sizes.pop()
        hits, misses = gen.get("prefix_hits_total", 0), gen.get(
            "prefix_misses_total", 0)
        snap["prefix_hit_rate"] = (hits / (hits + misses)
                                   if hits + misses else None)
        # Per-tenant counter aggregates (multi-tenant adapters): summed
        # across live replicas plus retired baselines, keyed exactly as
        # one engine's snapshot keys them.
        tenants: Dict[str, Dict[str, float]] = {
            t: dict(v) for t, v in retired_tenants.items()}
        for p in per.values():
            for name, tv in (p.get("tenants") or {}).items():
                agg = tenants.setdefault(name, {})
                for key in self._TENANT_SUM_KEYS:
                    v = tv.get(key)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        agg[key] = agg.get(key, 0) + v
        if tenants:
            snap["tenants"] = tenants
        k = self.adapters_resident()
        if k is not None:
            snap["adapters_resident"] = k
        snap["replicas"] = per
        adapter_dispatch = self._metrics.adapter_dispatch_counts()
        snap["fleet"] = {
            "replicas": len(per),
            "states": states,
            **{f"n_{s}": n for s, n in self.counts().items()},
            "dispatch_total": self._metrics.dispatch_counts(),
            "scale_events": self._metrics.scale_counts(),
            **({"adapter_dispatch": adapter_dispatch}
               if adapter_dispatch else {}),
        }
        return snap

    def prom_collect(self):
        """The fleet's ``(meta, samples)``: every replica's samples with
        a ``replica=`` label added, merged with the fleet-plane series
        (``hvd_fleet_replicas{state=}``,
        ``hvd_fleet_dispatch_total{replica=}``,
        ``hvd_fleet_scale_events_total{direction=}``) — ONE render is
        one valid exposition (grouped by metric name, single ``# TYPE``
        each; the reason this cannot be string concatenation)."""
        self._refresh_gauges()
        meta: Dict = {}
        samples: List = []
        for h in self.replicas():
            try:
                m, s = h.engine.prom_collect()
            except Exception:  # noqa: BLE001 — scrape what still answers
                continue
            meta.update(m)
            samples.extend((name, {**labels, "replica": h.name}, v)
                           for name, labels, v in s)
        m, s = self._metrics.registry.collect()
        meta.update(m)
        samples.extend(s)
        return meta, samples

    def prom_metrics(self) -> str:
        """Prometheus text exposition of :meth:`prom_collect` (the
        fleet ``/metrics`` body)."""
        from ..obs.registry import render
        return render(*self.prom_collect())
