"""Replica membership and admission routing for a serving fleet.

One :class:`~.generate.GenerationEngine` (or single-shot
:class:`~.engine.Engine`) is a *replica*: one decode batch over its own
slots and KV block pool. The ROADMAP's "millions of users" traffic does
not fit one replica, and simply running N engines behind N ports pushes
the load-balancing problem onto every client. :class:`FleetRouter` is
the missing layer: ONE front door that owns admission for the whole
fleet and fans requests out to N replicas.

Design rules, each load-bearing:

* **The router is the single admission point, not a second buffer.**
  Every replica already owns a bounded admission queue with
  overload-at-the-door semantics (PR 2); parking requests in a router
  queue in front of those would strand them when their eventual replica
  dies and would hide queue pressure from the autoscaler. Admission
  happens once, at :meth:`FleetRouter.submit`: pick the least-loaded
  READY replica, hand the request to its queue, and fail over to the
  next replica if that door is shut. The fleet rejects only when EVERY
  ready replica rejected — one saturated replica never bounces traffic
  the rest could serve.
* **Least-queue-depth dispatch reads the metrics the replicas already
  export.** :meth:`ReadinessMixin.load` is the same number `/metrics`
  publishes as ``hvd_queue_depth`` (+ active decode rows); no parallel
  bookkeeping that could drift from what the operator's dashboard says.
* **Readiness is the PR-4 ``/healthz`` contract, per replica.** A
  ``warming`` replica (engine built, ``warmup()`` still compiling)
  takes NO traffic — routing to it would make a user pay the compile. A
  ``draining`` replica takes no NEW traffic but finishes every stream
  already admitted — scale-down may never lose an admitted stream
  (the bit-identity drill in tests/test_fleet.py and the ci.sh
  autoscaler leg pin exactly this).
* **Liveness is the existing ``coord/`` heartbeat plane, not a second
  protocol.** Thread replicas are in-process: their loop thread — plus
  the engine's own loop-beat staleness probe
  (:meth:`~.generate.GenerationEngine.loop_alive`, which also catches a
  loop that is ALIVE but wedged mid-stream) — is the ground truth.
  Multi-process replicas form a coordinator world whose heartbeat
  timeouts (PR 1) already detect silence; a :class:`ReplicaHandle`
  wires ``liveness=`` to that plane
  (:func:`~.fleet.heartbeat_liveness`) and the router EVICTS on its
  verdict.
* **A dead replica strands no stream: deterministic failover.** The
  router records every admitted generation stream's full submission
  envelope (prompt tokens, sampling params + seed, max_new, eos,
  adapter, the ABSOLUTE deadline resolved at submit) and the tokens
  already relayed to the client. When a replica is declared dead —
  liveness verdict, loop death, or a stream-level engine failure — its
  in-flight streams are re-dispatched to surviving ready replicas and
  REPLAYED from the envelope: seeded generation makes the replayed
  tokens bit-identical, the already-emitted prefix is suppressed (and
  VERIFIED token-by-token — a diverging replay fails loudly rather
  than double- or mis-emitting), so the client's single chunked HTTP
  response simply continues. Replay keeps the submit-time absolute
  deadline (failover never resets a clock). A per-stream retry budget
  with backoff bounds the churn: a stream that failed on its budget's
  worth of replicas terminates with
  :class:`~horovod_tpu.exceptions.FailoverExhaustedError` (counted as
  ``hvd_failover_total{outcome="exhausted"}``, separate from overload)
  instead of retry-storming the fleet. Single-shot (``Future``) fleets
  keep the old fail-fast behavior — only generation streams carry
  enough determinism to resume.

The router duck-types the engine surface (``submit`` / ``generate`` /
``infer`` / ``stats`` / ``health`` / ``prom_collect`` / ``warmup`` /
``shutdown``), so :class:`~.server.HttpServer` mounts a fleet exactly
where it mounted one engine: ``POST /generate`` routes through the
router, ``GET /metrics`` merges every replica's samples (each carrying
a ``replica=`` label) with the fleet series into ONE valid exposition,
``GET /healthz`` reports fleet readiness (>= 1 ready replica).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..exceptions import (DeadlineExceededError, FailoverExhaustedError,
                          PreemptedError, ReplicaTimeoutError,
                          ServerClosedError, ServerOverloadedError,
                          WorkerFailureError)
from ..obs import flightrec
from ..parallel.kv_blocks import prefix_route_digest
from .generate import GenerationHandle
from .metrics import FleetMetrics

_log = logging.getLogger("horovod_tpu.serve.fleet")

# Replica states, in dispatch-priority order of meaning:
#   warming  — engine exists, warmup() not finished: takes NO traffic
#   ready    — routable
#   draining — scale-down in progress: finishes admitted streams, no new
#   dead     — liveness said gone (heartbeat abort / loop thread died)
REPLICA_STATES = ("ready", "warming", "draining", "dead")


class ReplicaHandle:
    """One fleet member: a name, an engine, and the membership verdicts
    the router needs (state, load, liveness).

    ``liveness`` is an optional zero-arg callable returning False once
    the replica's backing process is gone — for multi-process replicas
    this is the coord heartbeat plane
    (:func:`~.fleet.heartbeat_liveness`); thread replicas default to
    the engine's in-process probe
    (:meth:`~.generate.GenerationEngine.loop_alive` where the engine
    has one — it catches a loop that is alive but WEDGED mid-stream,
    ``stall_timeout_s`` being the staleness verdict — else the loop
    thread's plain aliveness). The handle never invents its own poller.
    """

    def __init__(self, name: str, engine: Any,
                 liveness: Optional[Callable[[], bool]] = None,
                 stall_timeout_s: float = 60.0):
        self.name = name
        self.engine = engine
        self._liveness = liveness
        self._stall_timeout = stall_timeout_s
        self._draining = False
        self._dead = False
        self._drain_thread: Optional[threading.Thread] = None

    def alive(self) -> bool:
        if self._dead:
            return False
        if self._liveness is not None:
            try:
                return bool(self._liveness())
            except Exception:  # noqa: BLE001 — a broken probe is "gone"
                return False
        # Thread replicas: the engine's own loop-beat probe where it has
        # one (thread death AND a beat stale past stall_timeout_s with
        # work pending both read dead) …
        la = getattr(self.engine, "loop_alive", None)
        if callable(la):
            try:
                return bool(la(self._stall_timeout))
            except Exception:  # noqa: BLE001 — a broken probe is "gone"
                return False
        # … else the loop thread is the ground truth — it only exits on
        # drain-complete or abort, both terminal.
        thread = getattr(self.engine, "_thread", None)
        if thread is not None and not thread.is_alive() \
                and not getattr(self.engine, "_closed", False):
            return False
        return True

    def state(self) -> str:
        if not self.alive():
            return "dead"
        if self._draining or getattr(self.engine, "_closed", False):
            return "draining"
        ready, _, _ = self.engine.health()
        return "ready" if ready else "warming"

    def load(self) -> int:
        """Dispatch pressure: queued + executing rows — the same number
        this replica's ``/metrics`` exports (``hvd_queue_depth`` +
        ``hvd_active_slots``).

        A generic stats-surface failure reads as the busy sentinel
        (route around it; the liveness plane owns the dead verdict on
        its own cadence). A transport TIMEOUT is different: for a
        subprocess replica it means the child may be HUNG, and the busy
        sentinel alone would route around a wedged process forever —
        so the handle marks the engine suspect and runs an immediate
        liveness check, turning a hung child into a dead handle within
        one poll."""
        try:
            return int(self.engine.load())
        except ReplicaTimeoutError:
            suspect = getattr(self.engine, "mark_suspect", None)
            if callable(suspect):
                try:
                    suspect()
                except Exception:  # noqa: BLE001 — advisory only
                    pass
            if not self.alive():
                self._dead = True
            return 1 << 30
        except Exception:  # noqa: BLE001 — a dying replica reads as busy
            return 1 << 30


class _FleetStream:
    """One tracked generation stream: the client-facing handle, the full
    submission envelope for deterministic replay, and the replay
    bookkeeping (tokens already relayed, the suppression cursor over
    them, the retry budget used). The pump thread owns all mutation
    after construction; the sweeper only reads ``inner`` (under the
    router's stream lock) to deliver a death verdict."""

    __slots__ = ("sid", "args", "kwargs", "deadline_at", "inner",
                 "client", "expect", "expect_i", "retries",
                 "replica", "unconfirmed")

    def __init__(self, sid: int, args: tuple, kwargs: dict,
                 deadline_at: Optional[float], inner: GenerationHandle):
        self.sid = sid
        self.args = args
        self.kwargs = kwargs                 # WITHOUT a rewritten deadline
        self.deadline_at = deadline_at       # absolute, resolved at submit
        self.inner = inner                   # current replica-side handle
        self.client = GenerationHandle()     # what the caller holds
        # (Tokens already relayed to the client live in
        # ``client._tokens`` — the pump is the only writer, so a second
        # copy here would just be an invariant to keep in sync.)
        self.expect: List[int] = []          # replay-suppression reference
        self.expect_i = 0
        self.retries = 0
        self.replica: Optional[str] = None   # current host replica name
        # Re-dispatches whose replayed prefix has not yet VERIFIED: the
        # "resumed" outcome is only counted once the replay catches up
        # to the client's emitted tokens — a diverging replay must count
        # exhausted, never both.
        self.unconfirmed = 0


class FleetRouter:
    """Admission router + replica membership for N serving engines.

    Args:
      engines: pre-built engines to wrap (replica names ``r0..rN-1``).
      factory: ``factory(name) -> engine`` for membership changes —
        required by :meth:`add_replica` (and therefore by the
        :class:`~.fleet.FleetAutoscaler`).
      initial: replicas to build from ``factory`` at construction.
      liveness_factory: optional ``liveness_factory(name) -> callable``
        wiring each new replica's liveness to the coord heartbeat plane
        (multi-process fleets); thread replicas leave it None.
      drain_timeout: seconds a drain-on-evict waits for the replica to
        finish its admitted streams before the handle is force-reaped.
      adapter_source: optional ``adapter_source(name) -> adapter tree``
        backing the adapter-affine dispatch's lazy-load path: a request
        whose adapter is resident on NO ready replica is dispatched
        least-load and the adapter hot-loaded there first (typically a
        closure over ``parallel.checkpoint.restore_adapter`` — the
        manifest-CRC walk then guards every lazy load). Without it, a
        non-resident adapter is a ``ValueError`` naming the remedy.
        Also the prewarm source on scale-up: :meth:`add_replica` seeds
        a grown replica's registry from the fleet's resident set.
      failover_retries: per-stream failover budget — how many SUCCESSFUL
        re-dispatches a stranded generation stream gets (i.e. how many
        replicas it may fail ON) before it terminates with
        ``failover_exhausted`` (never a retry storm). Overload
        rejections do not consume this budget — they wait.
      failover_backoff_s: floor/fallback sleep between failover
        re-dispatch attempts that hit overload; a ``retry_after_ms``
        hint on the rejection overrides it (capped at 2 s per nap).
      failover_overload_wait_s: wall-clock budget a stranded stream may
        spend waiting out fleet overload before it terminates with
        ``failover_exhausted`` (a stream with a deadline is additionally
        bounded by that deadline — load shedding must not convert a
        30 s-deadline stream into a terminal error 0.3 s after a
        replica death).
      stall_timeout_s: the in-process liveness probe's staleness
        verdict — an engine loop with work pending but no completed
        iteration for this long reads dead (must cover the engine's
        worst legitimate single iteration, e.g. a lazy first compile).
      poll_interval_s: period of the router's own membership sweep
        thread (started lazily with the first tracked generation
        stream, so fault detection does not depend on an autoscaler
        being attached); 0 disables — callers drive :meth:`poll`.
    """

    def __init__(self, engines: Optional[List[Any]] = None, *,
                 factory: Optional[Callable[[str], Any]] = None,
                 initial: int = 0,
                 liveness_factory: Optional[Callable] = None,
                 drain_timeout: float = 60.0,
                 adapter_source: Optional[Callable[[str], Any]] = None,
                 failover_retries: int = 3,
                 failover_backoff_s: float = 0.05,
                 failover_overload_wait_s: float = 30.0,
                 stall_timeout_s: float = 60.0,
                 poll_interval_s: float = 0.5):
        if failover_retries < 1:
            raise ValueError(
                f"failover_retries must be >= 1 (a stranded stream "
                f"needs at least one re-dispatch attempt), got "
                f"{failover_retries}")
        self._factory = factory
        self._liveness_factory = liveness_factory
        self._drain_timeout = drain_timeout
        self._adapter_source = adapter_source
        self._failover_retries = failover_retries
        self._failover_backoff = failover_backoff_s
        self._failover_overload_wait = failover_overload_wait_s
        self._stall_timeout = stall_timeout_s
        self._poll_interval = poll_interval_s
        self._lock = threading.Lock()
        self._metrics = FleetMetrics()
        self._replicas: List[ReplicaHandle] = []
        self._seq = 0
        self._closed = False
        self._t0 = time.monotonic()
        # The failover plane's stream registry: replica name -> live
        # tracked streams (generation fleets only; Future fleets are
        # not tracked). The sweeper thread starts with the first
        # tracked stream.
        self._streams_lock = threading.Lock()
        self._live_streams: Dict[str, Dict[int, _FleetStream]] = {}
        self._stream_seq = itertools.count()
        self._sweeper: Optional[threading.Thread] = None
        self._sweep_stop = threading.Event()
        # Final counter totals of replicas that LEFT the membership:
        # the fleet aggregates in stats() add these baselines so
        # cumulative fields (requests_total, tokens_generated_total,
        # prefix hits, rejections) never go BACKWARDS across a shrink —
        # the same monotonicity rule FleetMetrics.forget_replica keeps
        # for the dispatch counter.
        self._retired_totals: Dict[str, float] = {}
        self._retired_gen_totals: Dict[str, float] = {}
        self._retired_spec_totals: Dict[str, float] = {}
        self._retired_tenant_totals: Dict[str, Dict[str, float]] = {}
        # Fleet-wide concurrency high-water, sampled at dispatch and
        # stats boundaries. Summing per-replica peaks would add maxima
        # that never coincided (and the sum would DROP when a replica
        # retires) — a "peak" must be monotone and fleet-coincident.
        self._peak_active = 0
        for eng in engines or []:
            self._attach(eng)
        for _ in range(initial):
            if factory is None:
                raise ValueError(
                    "FleetRouter(initial=N) needs a factory= to build "
                    "replicas from")
            name = self._next_name()
            self._attach(factory(name), name=name)
        self._refresh_gauges()

    # -- membership --------------------------------------------------------

    def _next_name(self) -> str:
        name = f"r{self._seq}"
        self._seq += 1
        return name

    def _attach(self, engine: Any, name: Optional[str] = None
                ) -> ReplicaHandle:
        with self._lock:
            if name is None:
                name = self._next_name()
            liveness = (self._liveness_factory(name)
                        if self._liveness_factory else None)
            handle = ReplicaHandle(name, engine, liveness=liveness,
                                   stall_timeout_s=self._stall_timeout)
            self._replicas.append(handle)
        try:
            # Stamp the fleet name onto the engine: fault clauses
            # (replica_kill=<name>@stream=) and the flight recorder's
            # serving events key on it.
            engine.serve_name = name
        except Exception:  # noqa: BLE001 — duck-typed engines may refuse
            pass
        return handle

    def replicas(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._replicas)

    def counts(self) -> Dict[str, int]:
        """Membership by state (``{"ready": ..., "warming": ...,
        "draining": ..., "dead": ...}``)."""
        out = {s: 0 for s in REPLICA_STATES}
        for h in self.replicas():
            out[h.state()] += 1
        return out

    def add_replica(self, warm: bool = True) -> ReplicaHandle:
        """Grow the fleet by one replica. The engine is built
        synchronously (cheap — allocations, no compiles); ``warmup()``
        runs on a background thread, during which the replica reads
        ``warming`` and takes no traffic. Scale-up is therefore
        hitless: current replicas keep serving while the newcomer
        compiles."""
        if self._closed:
            raise ServerClosedError("fleet router is shut down")
        if self._factory is None:
            raise RuntimeError(
                "add_replica needs FleetRouter(factory=...) — the router "
                "cannot build engines it was never taught to build")
        with self._lock:
            name = self._next_name()
        handle = self._attach(self._factory(name), name=name)
        self._seed_adapters(handle)

        def _warm():
            try:
                handle.engine.warmup()
            except Exception as e:  # noqa: BLE001 — a failed warm = dead
                _log.warning("replica %s failed warmup: %r", handle.name, e)
                handle._dead = True
            self._refresh_gauges()

        if warm:
            t = threading.Thread(target=_warm,
                                 name=f"hvd-fleet-warm-{name}", daemon=True)
            t.start()
        self._refresh_gauges()
        return handle

    def _seed_adapters(self, handle: ReplicaHandle) -> None:
        """Adapter prewarming on scale-up (ROADMAP item 5): seed a
        grown replica's ``AdapterRegistry`` from the fleet's CURRENTLY
        resident adapter set — quotas carried along (the PR-14 rule: a
        seeded copy must not mint a quota-free tenant) — instead of
        filling by affinity misses. Needs ``adapter_source=`` (the only
        way the router can mint adapter trees); without it, or for
        registry-less engines, the replica fills on demand as before.
        A replica SHARING another replica's registry is already warm
        and skipped."""
        if self._adapter_source is None:
            return
        load = getattr(handle.engine, "load_adapter", None)
        reg_new = getattr(handle.engine, "adapters", None)
        if not callable(load) or reg_new is None:
            return
        wanted: Dict[str, Optional[int]] = {}
        for h in self.replicas():
            reg = getattr(h.engine, "adapters", None)
            if reg is None:
                continue
            if reg is reg_new and h is not handle:
                return      # shared registry: already resident
            if reg is reg_new:
                continue    # the new replica itself
            try:
                for n in (reg.resident() or ()):
                    if n not in wanted:
                        wanted[n] = reg.quota(n)
            except Exception:  # noqa: BLE001 — a dying replica has no say
                continue
        try:
            already = set(reg_new.resident() or ())
        except Exception:  # noqa: BLE001
            already = set()
        for n, q in sorted(wanted.items()):
            if n in already:
                continue
            try:
                load(n, self._adapter_source(n), quota=q)
            except Exception as e:  # noqa: BLE001 — prewarm is best-effort
                _log.warning("adapter prewarm of %r on %s failed: %r",
                             n, handle.name, e)

    def remove_replica(self, name: Optional[str] = None) -> ReplicaHandle:
        """Shrink the fleet by one replica, drain-on-evict: the replica
        stops taking NEW traffic immediately, finishes every stream it
        already admitted (the engine's ``shutdown(drain=True)``
        contract), and only then leaves the membership — no admitted
        stream is ever lost on scale-down. Returns the draining handle
        (``handle._drain_thread.join()`` to wait)."""
        with self._lock:
            candidates = [h for h in self._replicas if not h._draining]
            if name is not None:
                candidates = [h for h in candidates if h.name == name]
            if not candidates:
                raise ValueError(
                    f"no evictable replica"
                    f"{' named ' + name if name else ''} "
                    f"(states: {[ (h.name, h.state()) for h in self._replicas ]})")
            # Prefer a READY replica with the least to drain; fall back
            # to whatever is left (a warming replica drains instantly).
            ready = [h for h in candidates if h.state() == "ready"]
            pool = ready or candidates
            handle = min(pool, key=lambda h: h.load())
            handle._draining = True

        def _drain():
            try:
                handle.engine.shutdown(drain=True,
                                       timeout=self._drain_timeout)
            except Exception as e:  # noqa: BLE001
                _log.warning("replica %s drain raised: %r", handle.name, e)
            self._retire(handle)
            self._refresh_gauges()

        t = threading.Thread(target=_drain,
                             name=f"hvd-fleet-drain-{handle.name}",
                             daemon=True)
        handle._drain_thread = t
        t.start()
        self._refresh_gauges()
        return handle

    def poll(self) -> Dict[str, int]:
        """One membership sweep (the autoscaler calls this every tick):
        evict replicas whose liveness verdict says gone — a dead replica
        cannot drain, so its streams fail fast instead of hanging their
        clients — and refresh the ``hvd_fleet_replicas`` gauges.
        Returns :meth:`counts` after the sweep."""
        for h in self.replicas():
            if h.state() == "dead":
                self._evict_dead(h)
        self._refresh_gauges()
        return self.counts()

    def _evict_dead(self, handle: ReplicaHandle) -> None:
        _log.warning("replica %s is dead (liveness verdict) — evicting "
                     "without drain", handle.name)
        handle._dead = True
        if not self._retire(handle):
            # A concurrent poller (the router's own sweeper racing an
            # autoscaler tick) won the eviction and already delivered
            # the death verdicts; a second pass could _fail a stream's
            # REPLACEMENT handle on a healthy replica.
            return
        # Strand-and-resume: deliver the death verdict through each
        # tracked stream's inner handle — the pump thread (possibly
        # parked in next_event on a handle that will never speak again)
        # wakes and runs the failover. Idempotent on streams that
        # already finished (_fail no-ops once done). Failing INSIDE the
        # streams lock pins each verdict to the handle the stream holds
        # while still registered under this replica: the pump's own
        # failover unregisters before it swaps ``inner``, so a verdict
        # can never land on a replacement handle.
        with self._streams_lock:
            stranded = list(self._live_streams.get(handle.name,
                                                   {}).values())
            for s in stranded:
                s.inner._fail(WorkerFailureError(
                    f"serving replica {handle.name} declared dead with "
                    f"stream {s.sid} in flight"))

        def _reap():
            try:
                handle.engine.shutdown(drain=False, timeout=5.0)
            except Exception:  # noqa: BLE001
                pass

        threading.Thread(target=_reap, name=f"hvd-fleet-reap-{handle.name}",
                         daemon=True).start()

    def _retire(self, handle: ReplicaHandle) -> bool:
        """Remove ``handle`` from membership, folding its final counter
        totals into the retired baselines so the fleet aggregates stay
        monotone (best-effort for a dead replica whose stats raise).
        Exactly-once: the fold happens only on the call that wins the
        membership removal — a drain completing while a liveness
        verdict evicts the same replica must not double-count its
        history. Returns True iff this call won the removal."""
        snap: Dict[str, Any] = {}
        try:
            snap = handle.engine.stats()
        except Exception:  # noqa: BLE001 — a dead replica keeps what it had
            pass
        with self._lock:
            if handle not in self._replicas:
                return False
            self._replicas.remove(handle)
            for key in self._COUNTER_KEYS:
                v = snap.get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._retired_totals[key] = (
                        self._retired_totals.get(key, 0) + v)
            for key in self._GEN_SUM_KEYS:
                v = (snap.get("generation") or {}).get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._retired_gen_totals[key] = (
                        self._retired_gen_totals.get(key, 0) + v)
            for key in self._SPEC_SUM_KEYS:
                v = (snap.get("spec") or {}).get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._retired_spec_totals[key] = (
                        self._retired_spec_totals.get(key, 0) + v)
            for tenant, tv in (snap.get("tenants") or {}).items():
                base = self._retired_tenant_totals.setdefault(tenant, {})
                for key in self._TENANT_SUM_KEYS:
                    v = tv.get(key)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        base[key] = base.get(key, 0) + v
        self._metrics.forget_replica(handle.name)
        return True

    def _note_peak(self) -> None:
        """Sample the fleet's CURRENT total active streams into the
        high-water mark (called at dispatch and stats boundaries —
        approximate between samples; per-replica exact peaks stay in
        the nested snapshots)."""
        active = 0
        for h in self.replicas():
            try:
                active += h.engine._active_rows()
            except Exception:  # noqa: BLE001 — a dying replica counts 0
                pass
        with self._lock:
            # Compare+assign under the lock: two dispatch threads racing
            # the check-then-set could otherwise publish the SMALLER
            # sample last and regress the high-water.
            if active > self._peak_active:
                self._peak_active = active

    def adapters_resident(self) -> Optional[int]:
        """DISTINCT adapters resident across live replicas (the
        ``/healthz`` and fleet-line number), or None when no replica
        carries a registry (an adapter-free fleet)."""
        names: set = set()
        any_registry = False
        for h in self.replicas():
            fn = getattr(h.engine, "adapter_names", None)
            if not callable(fn):
                continue
            try:
                res = fn()
            except Exception:  # noqa: BLE001 — a dying replica counts 0
                continue
            if res is not None:
                any_registry = True
                names.update(res)
        return len(names) if any_registry else None

    def replica_metrics_endpoints(self) -> Dict[str, str]:
        """``{replica name: "host:port"}`` for every member whose engine
        serves its OWN ``/metrics`` (subprocess replicas). Advertised in
        the router's ``/healthz`` so a scraper
        (:class:`horovod_tpu.obs.summary.FleetPoller`) can walk the
        children directly — federation, not proxying: the child samples
        are never relayed through the router's own render."""
        out: Dict[str, str] = {}
        for h in self.replicas():
            fn = getattr(h.engine, "metrics_endpoint", None)
            if not callable(fn):
                continue
            try:
                ep = fn()
            except Exception:  # noqa: BLE001 — booting/dying child = none
                continue
            if ep:
                out[h.name] = str(ep)
        return out

    def _refresh_gauges(self) -> None:
        self._metrics.set_replicas(self.counts())
        self._metrics.set_adapters_resident(self.adapters_resident())
        self._metrics.set_replica_procs(
            sum(1 for h in self.replicas()
                if getattr(h.engine, "pid", None) is not None))

    # -- dispatch ----------------------------------------------------------

    @staticmethod
    def _resident_names(handle: ReplicaHandle) -> Tuple[str, ...]:
        """A replica's resident adapters (empty for engines without a
        registry — they can never serve an adapter request)."""
        fn = getattr(handle.engine, "adapter_names", None)
        if not callable(fn):
            return ()
        try:
            return tuple(fn() or ())
        except Exception:  # noqa: BLE001 — a dying replica reads empty
            return ()

    @staticmethod
    def _resident_digests(handle: ReplicaHandle) -> frozenset:
        """A replica's advertised registered-prefix route digests
        (empty for engines without a prefix registry — they can never
        serve a prefix hit, so they never sort as prefix-affine)."""
        fn = getattr(handle.engine, "prefix_digests", None)
        if not callable(fn):
            return frozenset()
        try:
            return frozenset(fn() or ())
        except Exception:  # noqa: BLE001 — a dying replica reads empty
            return frozenset()

    def _prefix_affinity(self, ready: List[ReplicaHandle], tokens,
                         adapter: Optional[str]) -> Dict[str, bool]:
        """Which ready replicas already hold this prompt's first-block
        prefix (``{name: affine}``; a name is present only when routing
        was actually in play — the replica advertised digests AND the
        prompt had a routable first block at that replica's block size).
        Purely advisory: a stale digest costs one cache miss downstream,
        never a wrong byte, so errors and absences all read as
        non-affine."""
        affine: Dict[str, bool] = {}
        if tokens is None:
            return affine
        digest_cache: Dict[int, Optional[str]] = {}
        for h in ready:
            digests = self._resident_digests(h)
            if not digests:
                continue
            bs = getattr(h.engine, "route_block_size", None)
            if not isinstance(bs, int) or bs <= 0:
                continue
            if bs not in digest_cache:
                try:
                    digest_cache[bs] = prefix_route_digest(
                        tokens, bs, adapter)
                except Exception:  # noqa: BLE001 — advisory only
                    digest_cache[bs] = None
            d = digest_cache[bs]
            if d is None:
                continue
            affine[h.name] = d in digests
        return affine

    @staticmethod
    def _slo_burning(ready: List[ReplicaHandle],
                     adapter: Optional[str]) -> Dict[str, bool]:
        """Which ready replicas are currently burning this tenant's
        TTFT SLO (``{name: burning}``) — the engine's own
        ``slo_burn(tenant)`` fraction, > 0 meaning recent first tokens
        (or deadline expiries) missed the tenant's declared target
        there. Requests without an adapter are the ``"base"`` tenant.
        Purely advisory like prefix affinity: engines without the
        probe, tenants without an SLO, and a dying replica's read error
        all report not-burning (no key), so a fleet with no SLOs sorts
        exactly as before."""
        burning: Dict[str, bool] = {}
        tenant = adapter if adapter is not None else "base"
        for h in ready:
            fn = getattr(h.engine, "slo_burn", None)
            if not callable(fn):
                continue
            try:
                if fn(tenant) > 0.0:
                    burning[h.name] = True
            except Exception:  # noqa: BLE001 — advisory only
                continue
        return burning

    def _lazy_load(self, handle: ReplicaHandle, adapter: str) -> None:
        """The affinity-miss path: fetch the adapter from
        ``adapter_source`` and hot-load it into ``handle`` before the
        dispatch. Raises ``ValueError`` when this replica cannot take it
        (no source, no registry, table full) — the dispatch loop then
        fails over."""
        if adapter in self._resident_names(handle):
            return      # a concurrent submit already loaded it here
        if self._adapter_source is None:
            raise ValueError(
                f"adapter {adapter!r} is not resident on any ready "
                f"replica and the router has no adapter_source= to "
                f"lazy-load it from — load it on a replica or pass "
                f"adapter_source=")
        load = getattr(handle.engine, "load_adapter", None)
        if not callable(load):
            raise ValueError(
                f"replica {handle.name} cannot host adapters "
                f"(engine has no load_adapter)")
        # Propagate the tenant's quota from a replica that already hosts
        # it: a lazy load must not mint a quota-free copy of the adapter
        # (one saturated replica would otherwise let the tenant run
        # unlimited streams through every replica it seeds).
        quota = None
        for other in self.replicas():
            reg = getattr(other.engine, "adapters", None)
            if reg is None:
                continue
            try:
                if adapter in (reg.resident() or ()):
                    quota = reg.quota(adapter)
                    if quota is not None:
                        break
            except Exception:  # noqa: BLE001 — a dying replica has no say
                continue
        try:
            load(adapter, self._adapter_source(adapter), quota=quota)
        except RuntimeError:
            # Raced a concurrent submit that loaded the same adapter
            # (and already has a live stream refcounting its row, so the
            # registry refused our redundant reload): it IS resident —
            # the dispatch can proceed.
            if adapter not in self._resident_names(handle):
                raise

    def submit(self, *args, **kwargs):
        """Admit one request to the fleet: least-loaded READY replica
        first, failing over across the ready set. A request carrying
        ``adapter=`` dispatches adapter-AFFINE: ready replicas that
        already have the adapter resident come first (least-load
        tiebreak unchanged — their KV/compile state is equally warm, so
        load still orders within the resident set), the rest fall back
        to least-load + lazy hot-load via ``adapter_source``. Raises
        :class:`ServerOverloadedError` only when EVERY ready replica
        rejected (or none is ready yet — a warming fleet is a retryable
        condition; the error carries a ``retry_after_ms`` backoff hint,
        the minimum over the replicas' own drain estimates),
        :class:`ServerClosedError` once the router (or the whole
        membership) is shut down, ``ValueError`` when an adapter is
        resident nowhere and cannot be lazy-loaded.

        Generation fleets return a fleet-owned
        :class:`~.generate.GenerationHandle` backed by the
        deterministic-failover plane: the stream's envelope is recorded
        and a replica death mid-stream re-dispatches it, replaying
        bit-identically with the emitted prefix suppressed — the caller
        never sees the migration. Single-shot fleets return the
        replica's ``Future`` unchanged (no failover)."""
        if self._closed:
            raise ServerClosedError("fleet router is shut down")
        out, handle = self._dispatch(args, kwargs)
        if not isinstance(out, GenerationHandle):
            return out      # Future fleets: nothing deterministic to replay
        return self._track(out, handle, args, kwargs)

    def _dispatch(self, args: tuple, kwargs: dict,
                  avoid: Optional[str] = None):
        """One admission attempt over the current ready set (the shared
        core of :meth:`submit` and the failover replay). Returns
        ``(replica submit result, ReplicaHandle)`` or raises the fleet
        verdict. ``avoid`` demotes that replica to the END of the walk
        (a failover replay tries every OTHER door first, but a fleet
        whose only ready replica is the avoided one still gets it)."""
        adapter = kwargs.get("adapter")
        tokens = args[0] if args else kwargs.get("tokens")
        snapshot = self.replicas()
        ready = [h for h in snapshot if h.state() == "ready"]
        resident: Dict[str, bool] = {}
        # Prefix-affine routing: replicas already holding this prompt's
        # registered first block sort ahead of equally-ready peers —
        # adapter residency still outranks it (a lazy adapter load is
        # strictly costlier than a cold prefill), load still tiebreaks.
        affine = self._prefix_affinity(ready, tokens, adapter)
        # SLO-aware dispatch: a replica already BURNING this tenant's
        # TTFT SLO (its local burn fraction > 0) sorts after clean peers
        # — below affinity (warm state still wins: a cold prefill or
        # lazy adapter load would burn the SLO harder than a queue) but
        # above raw load, so equally-warm replicas shed a struggling
        # tenant toward doors that are still meeting its target.
        burning = self._slo_burning(ready, adapter)
        if adapter is not None:
            resident = {h.name: adapter in self._resident_names(h)
                        for h in ready}
            ready.sort(key=lambda h: (h.name == avoid,
                                      not resident[h.name],
                                      not affine.get(h.name, False),
                                      burning.get(h.name, False),
                                      h.load()))
        else:
            ready.sort(key=lambda h: (h.name == avoid,
                                      not affine.get(h.name, False),
                                      burning.get(h.name, False),
                                      h.load()))
        if not ready:
            warming = sum(1 for h in snapshot if h.state() == "warming")
            if warming:
                err = ServerOverloadedError(
                    f"no ready replicas yet ({warming} warming) — retry "
                    f"after backoff")
                err.retry_after_ms = 1000.0   # a warm-up, not a queue
                raise err
            if self._factory is not None:
                # An open router with a factory is one autoscaler tick
                # away from a below-min refill — a terminal "closed"
                # here would tell well-behaved clients to stop retrying
                # a fleet about to heal.
                err = ServerOverloadedError(
                    "no live replicas right now (the fleet can refill) "
                    "— retry after backoff")
                err.retry_after_ms = 1000.0
                raise err
            raise ServerClosedError(
                "fleet has no live replicas (all drained or dead)")
        last: Optional[BaseException] = None
        hosting_error: Optional[ValueError] = None
        saw_backpressure = False
        lazy_loaded = False
        hints: List[float] = []
        for h in ready:
            if adapter is not None and not resident.get(h.name):
                if lazy_loaded:
                    # At most ONE lazy load per dispatch: a burst that
                    # overloads the freshly-loaded replica must read as
                    # retryable overload, not replicate the adapter into
                    # every table on the failover walk (rows are never
                    # auto-evicted — proliferation would turn transient
                    # backpressure into permanently full tables). Spread
                    # stays demand-driven: each retry may seed one more
                    # replica while the resident set stays saturated.
                    continue
                try:
                    self._lazy_load(h, adapter)
                    lazy_loaded = True
                except ValueError as e:
                    # This replica can't take the adapter (no source /
                    # no registry / table full): fail over.
                    last = hosting_error = e
                    continue
            try:
                out = h.engine.submit(*args, **kwargs)
            except ServerOverloadedError as e:
                last = e
                saw_backpressure = True
                ra = getattr(e, "retry_after_ms", None)
                if isinstance(ra, (int, float)):
                    hints.append(float(ra))
                continue
            except ServerClosedError as e:
                # Raced a drain decision between the snapshot and the
                # submit: that replica's door is shut, not the fleet's.
                last = e
                saw_backpressure = True
                continue
            except ValueError as e:
                if adapter is None:
                    raise
                # An adapter submit can lose an evict race: the adapter
                # was resident when this loop snapshotted residency, and
                # gone by the time submit retained it. Other replicas may
                # still host it — fail over instead of erroring the
                # request terminally. (A genuinely malformed request
                # raises the same ValueError on EVERY replica with no
                # backpressure seen, and surfaces below unchanged.)
                last = hosting_error = e
                continue
            self._metrics.on_dispatch(h.name)
            if adapter is not None:
                self._metrics.on_adapter_dispatch(
                    "affine" if resident.get(h.name) else "miss")
            if affine:
                # Routing was in play (>= 1 replica advertised digests
                # and the prompt was routable): record the outcome.
                self._metrics.on_prefix_dispatch(
                    "affine" if affine.get(h.name) else "miss")
            self._note_peak()
            return out, h
        if adapter is not None and hosting_error is not None \
                and not saw_backpressure:
            # EVERY ready replica failed to even HOST the adapter — a
            # config problem, not backpressure; retrying would never
            # help. (If any hosting-capable replica merely rejected on
            # load, the condition IS retryable — fall through to the
            # overload below.)
            raise hosting_error
        err = ServerOverloadedError(
            f"all {len(ready)} ready replicas rejected the request "
            f"(last: {last}) — grow the fleet or shed load")
        # The fleet-level backoff hint: the SOONEST any replica expects
        # to drain its queue (the client only needs one door to open).
        err.retry_after_ms = min(hints) if hints else 1000.0
        raise err

    # -- deterministic stream failover --------------------------------------

    def _track(self, inner: GenerationHandle, handle: ReplicaHandle,
               args: tuple, kwargs: dict) -> GenerationHandle:
        """Wrap a freshly-dispatched generation stream in the failover
        plane: record its envelope (with the deadline resolved to an
        ABSOLUTE instant — the clock a replay must NOT reset), register
        it under its host replica, and start the relay pump. Returns
        the client-facing handle."""
        now = time.monotonic()
        deadline_ms = kwargs.get("deadline_ms")
        if deadline_ms is None:
            # The engine would apply its own default relative to ITS
            # submit time; resolve it here so a replay keeps the
            # original clock instead of restarting the default.
            cfg = getattr(handle.engine, "_cfg", None)
            deadline_ms = getattr(cfg, "default_deadline_ms", None)
        stream = _FleetStream(
            sid=next(self._stream_seq), args=args, kwargs=dict(kwargs),
            deadline_at=(None if deadline_ms is None
                         else now + deadline_ms / 1e3),
            inner=inner)
        self._register(stream, handle.name)
        self._confirm_membership(stream, handle)
        flightrec.record("serve_dispatch", stream=stream.sid,
                         replica=handle.name)
        self._ensure_sweeper()
        # One relay thread per in-flight stream: bounded by the fleet's
        # admission capacity (every stream lives in some replica's
        # bounded queue/slots — the no-second-buffer rule), never by
        # request rate.
        threading.Thread(target=self._pump, args=(stream,),
                         name=f"hvd-fleet-stream-{stream.sid}",
                         daemon=True).start()
        return stream.client

    def _confirm_membership(self, stream: _FleetStream,
                            handle: ReplicaHandle) -> None:
        """Close the dispatch→register race with an eviction: a replica
        declared dead between the submit that admitted this stream and
        its registration was retired BEFORE ``_evict_dead`` snapshotted
        the streams to strand, so nobody else will ever deliver its
        death verdict (membership removal is exactly-once, and the
        reaper may have drained the engine's queue before the submit
        landed). ``_retire`` removes membership FIRST, so either the
        eviction sees our registration or we see the eviction here —
        there is no interleaving that misses both. Idempotent against
        every competing verdict (``_fail`` no-ops on a done handle; a
        drained replica's finished stream already has its events
        queued)."""
        with self._lock:
            present = handle in self._replicas
        if not present:
            stream.inner._fail(WorkerFailureError(
                f"serving replica {handle.name} left the membership "
                f"while stream {stream.sid} was being dispatched to "
                f"it"))

    def _register(self, stream: _FleetStream, name: str) -> None:
        with self._streams_lock:
            stream.replica = name
            self._live_streams.setdefault(name, {})[stream.sid] = stream

    def _unregister(self, stream: _FleetStream) -> None:
        with self._streams_lock:
            if stream.replica is not None:
                m = self._live_streams.get(stream.replica)
                if m is not None:
                    m.pop(stream.sid, None)
                    if not m:
                        self._live_streams.pop(stream.replica, None)
            stream.replica = None

    def _ensure_sweeper(self) -> None:
        """Start the router's own membership sweep (lazily, with the
        first tracked stream): liveness verdicts must fire even when no
        autoscaler polls this router — a static 2-replica fleet still
        promises failover."""
        if self._sweeper is not None or self._poll_interval <= 0:
            return
        with self._lock:
            if self._sweeper is not None or self._closed:
                return
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name="hvd-fleet-sweep",
                daemon=True)
        self._sweeper.start()

    def _sweep_loop(self) -> None:
        while not self._sweep_stop.wait(self._poll_interval):
            if self._closed:
                return
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — a bad sweep must not stop
                _log.exception("fleet membership sweep failed")

    def _pump(self, stream: _FleetStream) -> None:
        """Relay one stream's events from its current replica handle to
        the client handle, surviving replica deaths: replica-level
        failures trigger :meth:`_failover` (which swaps ``stream.inner``
        and the relay continues), request-level verdicts (deadline,
        malformed input) pass through. During a replay the suppression
        cursor swallows — and VERIFIES — the already-emitted prefix, so
        the client never sees a duplicate or diverging token."""
        client = stream.client
        while True:
            kind, val = stream.inner.next_event()
            if kind == "token":
                if stream.expect_i < len(stream.expect):
                    want = stream.expect[stream.expect_i]
                    stream.expect_i += 1
                    if val != want:
                        # Bit-identity is the contract failover stands
                        # on; a diverging replay must fail loudly, never
                        # mis-continue a stream the client half-has.
                        self._diverged(stream, client, FailoverExhaustedError(
                            f"stream {stream.sid}: replayed token "
                            f"{stream.expect_i - 1} diverged "
                            f"({val} != {want}) — deterministic replay "
                            f"broken, refusing to continue the stream"))
                        return
                    if stream.expect_i == len(stream.expect):
                        self._confirm_resumes(stream)
                    continue    # suppressed: the client already has it
                self._confirm_resumes(stream)   # 0-token-prefix resumes
                client._emit(val)
            elif kind == "done":
                if stream.expect_i < len(stream.expect):
                    # The replay finished BEFORE reproducing the prefix
                    # the client already holds — divergence by omission,
                    # as terminal as a wrong token.
                    self._diverged(stream, client, FailoverExhaustedError(
                        f"stream {stream.sid}: replay finished after "
                        f"{stream.expect_i} of {len(stream.expect)} "
                        f"already-emitted tokens — deterministic replay "
                        f"broken, refusing to continue the stream"))
                    return
                self._confirm_resumes(stream)
                self._unregister(stream)
                info = dict(val)
                info["failovers"] = stream.retries
                client._finish(info)
                return
            else:   # ("error", exc)
                if isinstance(val, DeadlineExceededError):
                    # The REQUEST's own verdict, not the replica's: the
                    # deadline is absolute — a replay would expire at
                    # the same instant, so there is nothing to resume.
                    # (A malformed request, by contrast, is rejected at
                    # SUBMIT time, synchronously, and never reaches the
                    # pump — an error event from a replica that already
                    # ADMITTED the stream is the replica's fault
                    # whatever the exception type, and fails over.)
                    self._unregister(stream)
                    client._fail(val)
                    return
                if self._closed:
                    # Fleet shutdown cancelled it — not a strand.
                    self._unregister(stream)
                    client._fail(val)
                    return
                # PreemptedError (the replica's priority plane evicted
                # this stream past its LOCAL retry budget,
                # ``preempted_exhausted``) deliberately falls through to
                # failover: the verdict is one replica's congestion, not
                # the stream's fault — another replica may have priority
                # headroom, and the replay is the same bit-identical
                # suppressed-prefix machinery preemption resume uses.
                if not self._failover(stream, val):
                    return      # terminal: the client was failed

    def _confirm_resumes(self, stream: _FleetStream) -> None:
        """The replayed prefix has fully VERIFIED: count the pending
        re-dispatches as ``resumed`` outcomes. Deferred from the
        re-dispatch itself so a diverging replay counts ``exhausted``
        alone — the outcome labels partition verdicts, never overlap."""
        while stream.unconfirmed:
            stream.unconfirmed -= 1
            self._metrics.on_failover("resumed")

    def _diverged(self, stream: _FleetStream, client: GenerationHandle,
                  err: FailoverExhaustedError) -> None:
        stream.unconfirmed = 0      # these re-dispatches did NOT resume
        self._unregister(stream)
        self._metrics.on_failover("exhausted")
        client._fail(err)

    def _failover(self, stream: _FleetStream, cause: BaseException) -> bool:
        """Re-dispatch a stranded stream onto a surviving replica,
        replaying its envelope with the emitted prefix suppressed.
        Returns True when the stream resumed (the pump continues on the
        new ``stream.inner``), False when it terminated. Bounded by the
        per-stream retry budget — only a SUCCESSFUL re-dispatch consumes
        it (the budget counts replicas the stream may fail ON) — and,
        for overload rejections, by the ``failover_overload_wait_s``
        wall clock with hint-driven naps. Either bound exhausting (or a
        terminal hosting error on every replica) fails the client with
        :class:`FailoverExhaustedError` — counted as
        ``hvd_failover_total{outcome="exhausted"}``, never a loop."""
        prev = stream.replica
        self._unregister(stream)
        if stream.client.done():
            return False
        self._metrics.on_stranded()
        flightrec.record("serve_failover", stream=stream.sid,
                         replica=prev, cause=repr(cause))
        last: BaseException = cause
        overload_t0: Optional[float] = None
        while stream.retries < self._failover_retries:
            if self._closed:
                stream.client._fail(ServerClosedError(
                    f"fleet shut down while failing over stream "
                    f"{stream.sid}"))
                return False
            if stream.deadline_at is not None \
                    and time.monotonic() >= stream.deadline_at:
                # The ORIGINAL absolute deadline — replay never resets
                # the clock, so expiry during failover is the same
                # verdict the stream would have met in a queue.
                stream.client._fail(DeadlineExceededError(
                    f"deadline expired while failing over stream "
                    f"{stream.sid} (stranded on {prev}: {cause!r})"))
                return False
            kwargs = dict(stream.kwargs)
            if stream.deadline_at is not None:
                kwargs["deadline_ms"] = max(
                    1.0, (stream.deadline_at - time.monotonic()) * 1e3)
            try:
                # Avoid the replica the stream just failed on: a SICK
                # but alive replica (loop errors every stream, thread
                # survives) empties its own queue, so a plain least-load
                # pick would hand the stream straight back and burn the
                # whole budget on one broken member while healthy
                # replicas sit idle.
                out, handle = self._dispatch(stream.args, kwargs,
                                             avoid=prev)
            except ServerOverloadedError as e:
                # The FLEET's condition, not this stream's fault:
                # waiting out overload spends the overload wall clock,
                # never the re-dispatch budget (a 3-retry stream must
                # not turn terminal 3 naps after a replica death just
                # because the survivors were momentarily full). The nap
                # honors the rejection's own ``retry_after_ms`` hint,
                # floored at the configured backoff, capped at 2 s and
                # at the stream's remaining deadline.
                last = e
                now = time.monotonic()
                if overload_t0 is None:
                    overload_t0 = now
                elif now - overload_t0 >= self._failover_overload_wait:
                    break       # waited the whole overload budget
                ra = getattr(e, "retry_after_ms", None)
                nap = (float(ra) / 1e3
                       if isinstance(ra, (int, float)) and ra > 0
                       else self._failover_backoff)
                nap = min(2.0, max(nap, self._failover_backoff))
                if stream.deadline_at is not None:
                    nap = min(nap, max(0.0, stream.deadline_at - now))
                time.sleep(nap)
                continue
            except ServerClosedError as e:
                stream.client._fail(e)
                return False
            except ValueError as e:
                # Terminal hosting/config error on every replica (the
                # _dispatch contract) — more attempts cannot help.
                last = e
                break
            if not isinstance(out, GenerationHandle):
                last = TypeError(
                    f"failover re-dispatch returned {type(out).__name__},"
                    f" not a generation stream")
                break
            stream.retries += 1
            stream.inner = out
            stream.expect = list(stream.client._tokens)
            stream.expect_i = 0
            # "resumed" is NOT counted yet: the pump confirms it once
            # the replayed prefix verifies against the client's tokens.
            stream.unconfirmed += 1
            self._register(stream, handle.name)
            self._confirm_membership(stream, handle)
            flightrec.record("serve_failover_resumed", stream=stream.sid,
                             replica=handle.name, attempt=stream.retries,
                             suppressed=len(stream.expect))
            _log.warning(
                "stream %d: failed over %s -> %s (attempt %d, replaying "
                "%d emitted tokens suppressed) after %r", stream.sid,
                prev, handle.name, stream.retries, len(stream.expect),
                cause)
            return True
        stream.unconfirmed = 0      # nothing re-dispatched stuck
        self._metrics.on_failover("exhausted")
        # A stream stranded by PREEMPTION (not replica death) carries
        # the engine's terminal reason through the fleet verdict: the
        # client distinguishes "the fleet is priority-congested for my
        # class" (back off, or raise priority) from "replicas kept
        # dying" (page the operator).
        reason = ("preempted_exhausted"
                  if isinstance(cause, PreemptedError) else "exhausted")
        err = FailoverExhaustedError(
            f"stream {stream.sid} could not be resumed "
            f"({reason}; re-dispatched {stream.retries} time(s); "
            f"stranded on {prev} by {cause!r}; last: {last!r}) — "
            f"re-submit from scratch")
        err.reason = reason
        stream.client._fail(err)
        return False

    def generate(self, tokens, timeout: Optional[float] = None, **kw):
        """Synchronous generation through the fleet (submit + result)."""
        return self.submit(tokens, **kw).result(timeout)

    def infer(self, inputs, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None):
        """Synchronous single-shot inference through a fleet of
        :class:`~.engine.Engine` replicas (the ``/predict`` path)."""
        return self.submit(inputs, deadline_ms=deadline_ms).result(timeout)

    # -- lifecycle ---------------------------------------------------------

    def warmup(self) -> Tuple[str, ...]:
        """Warm every current replica (sequentially — deploy-time code;
        mid-run growth warms on its own thread via
        :meth:`add_replica`). Returns the replica names warmed."""
        warmed = []
        for h in self.replicas():
            if h.state() == "warming":
                h.engine.warmup()
            warmed.append(h.name)
        self._refresh_gauges()
        return tuple(warmed)

    def shutdown(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the fleet. ``drain=True`` finishes every admitted stream
        on every replica (drained concurrently) first. Idempotent."""
        if self._closed:
            return
        self._closed = True
        # Stop the membership sweeper FIRST and wait for it: a daemon
        # thread left sleeping into interpreter teardown can abort the
        # process from the C++ runtime's static destructors.
        self._sweep_stop.set()
        if self._sweeper is not None:
            self._sweeper.join(timeout)
        handles = self.replicas()
        threads = []
        for h in handles:
            t = threading.Thread(
                target=lambda h=h: h.engine.shutdown(drain=drain,
                                                     timeout=timeout),
                name=f"hvd-fleet-stop-{h.name}", daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout)
        for h in handles:
            if h._drain_thread is not None:
                h._drain_thread.join(timeout)
        self._refresh_gauges()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- health / stats / metrics ------------------------------------------

    def health(self) -> Tuple[bool, str, int]:
        """Fleet-level ``/healthz``: ready iff >= 1 replica is ready.
        Status mirrors the per-engine vocabulary (``ok`` / ``warming`` /
        ``draining``) so load balancers need no new parser; the per-state
        breakdown lives in :meth:`fleet_health`."""
        c = self.counts()
        # Exclude dead replicas from the depth sum: their load() reads
        # as the 1<<30 dispatch-ordering sentinel, which would turn the
        # /healthz queue_depth into a nonsense spike until the next
        # membership sweep evicts them.
        depth = sum(h.load() for h in self.replicas()
                    if h.state() != "dead")
        if self._closed:
            return False, "draining", depth
        if c["ready"] >= 1:
            return True, "ok", depth
        if c["warming"] >= 1:
            return False, "warming", depth
        return False, "draining", depth

    def fleet_health(self) -> Dict[str, int]:
        """Membership breakdown for the ``/healthz`` body."""
        return self.counts()

    def ttft_totals(self) -> Tuple[float, int]:
        """Fleet-cumulative ``(ttft_seconds_sum, count)`` summed from
        each replica's ``hvd_generate_ttft_seconds`` histogram — the
        rate()-able pair the autoscaler differences between polls."""
        s, n = 0.0, 0
        for h in self.replicas():
            m = getattr(h.engine, "_metrics", None)
            if m is None or not hasattr(m, "ttft_totals"):
                continue
            ds, dn = m.ttft_totals()
            s += ds
            n += dn
        return s, n

    # /stats keys summed across replicas (the fleet-aggregate view the
    # bench and dashboards read; per-replica truth nests under
    # "replicas"). Percentile fields cannot be summed and are omitted —
    # scrape the histograms for fleet quantiles. _COUNTER_KEYS are the
    # CUMULATIVE subset: a retiring replica's final values fold into the
    # retired baseline so they never go backwards across a shrink;
    # gauges (queue depth, slots) reflect live membership only.
    _COUNTER_KEYS = ("requests_total", "responses_total",
                     "rejected_overload", "rejected_slots_full",
                     "rejected_blocks_exhausted", "rejected_tenant_quota",
                     "expired_deadline",
                     "cancelled_shutdown", "batches_total",
                     "batch_rows_total", "batch_live_rows_total")
    # (peak_active_slots is NOT summed: the fleet peak is the router's
    # own sampled high-water — see _note_peak.)
    _GAUGE_KEYS = ("queue_depth", "active_slots", "max_slots")
    _SUM_KEYS = _COUNTER_KEYS + _GAUGE_KEYS
    _GEN_SUM_KEYS = ("generations_total", "tokens_generated_total",
                     "prefix_hits_total", "prefix_misses_total",
                     "prefix_hit_blocks_total", "prefix_lookup_blocks_total",
                     "kv_offload_blocks_total", "kv_prefetch_blocks_total",
                     "prefill_chunks_total", "prefill_chunks_skipped_total",
                     "preemptions_total", "preempt_resumed_total",
                     "preempt_exhausted_total")
    # Per-tenant counters summed across replicas (+ retired baselines —
    # same monotonicity rule); tenant percentile fields cannot be summed
    # and stay in the nested per-replica snapshots (scrape the
    # hvd_tenant_* histograms for fleet-wide tenant quantiles). The
    # fleet-wide SLO burn is RECOMPUTED from these summed counters in
    # stats() — averaging per-replica burn fractions would weight an
    # idle replica's one miss equally with a busy replica's thousand
    # hits.
    _TENANT_SUM_KEYS = ("generations_total", "tokens_generated_total",
                        "first_tokens_total", "ttft_slo_miss_total",
                        "deadline_miss_total", "preemptions_total")
    # Speculative-decoding counters summed across replicas (+ retired
    # baselines). The derived ratios (accept_rate, tokens_per_step) are
    # recomputed fleet-wide from the summed counters — averaging
    # per-replica ratios would weight idle replicas equally with busy
    # ones. Timing percentiles stay per-replica (scrape the
    # hvd_spec_*_seconds histograms for fleet quantiles).
    _SPEC_SUM_KEYS = ("steps_total", "draft_tokens_total",
                      "accepted_tokens_total", "emitted_tokens_total")

    def stats(self) -> Dict:
        """The fleet ``/stats`` snapshot: aggregate counters at the top
        (same key names as one engine, so existing consumers keep
        reading), per-replica snapshots under ``"replicas"``, and the
        fleet plane (membership, dispatch, scale events) under
        ``"fleet"``."""
        self._note_peak()
        per: Dict[str, Dict] = {}
        states: Dict[str, str] = {}
        for h in self.replicas():
            try:
                per[h.name] = h.engine.stats()
            except Exception as e:  # noqa: BLE001 — a dying replica's
                per[h.name] = {"error": repr(e)}   # stats must not 500 /stats
            states[h.name] = h.state()
        snap: Dict[str, Any] = {
            "uptime_seconds": time.monotonic() - self._t0,
            "kv_layout": None,
            "max_len": None,
        }
        with self._lock:
            retired = dict(self._retired_totals)
            retired_gen = dict(self._retired_gen_totals)
            retired_spec = dict(self._retired_spec_totals)
            retired_tenants = {t: dict(v) for t, v in
                               self._retired_tenant_totals.items()}
        for key in self._SUM_KEYS:
            vals = [p.get(key) for p in per.values()
                    if isinstance(p.get(key), (int, float))]
            snap[key] = sum(vals) + retired.get(key, 0) if (
                vals or key in retired) else 0
        gen: Dict[str, Any] = {}
        for key in self._GEN_SUM_KEYS:
            vals = [p.get("generation", {}).get(key) for p in per.values()
                    if isinstance(p.get("generation", {}).get(key),
                                  (int, float))]
            gen[key] = sum(vals) + retired_gen.get(key, 0)
        snap["generation"] = gen
        snap["peak_active_slots"] = self._peak_active
        rows, live = snap.get("batch_rows_total", 0), snap.get(
            "batch_live_rows_total", 0)
        snap["batch_fill_ratio"] = (live / rows) if rows else None
        for p in per.values():
            if snap["kv_layout"] is None and "kv_layout" in p:
                snap["kv_layout"] = p["kv_layout"]
            if "max_len" in p:
                snap["max_len"] = max(snap["max_len"] or 0, p["max_len"])
        blocks = [p["blocks"] for p in per.values() if "blocks" in p]
        if blocks and len(blocks) == len(per):
            snap["blocks"] = {k: sum(b.get(k, 0) for b in blocks)
                              for k in blocks[0]}
            sizes = {p.get("block_size") for p in per.values()}
            if len(sizes) == 1:
                snap["block_size"] = sizes.pop()
        hits, misses = gen.get("prefix_hits_total", 0), gen.get(
            "prefix_misses_total", 0)
        snap["prefix_hit_rate"] = (hits / (hits + misses)
                                   if hits + misses else None)
        # Speculative-decoding fleet aggregate: engines always emit a
        # "spec" block (zeros when speculation is off), so this mirrors
        # the single-engine shape; absent only for an empty fleet with
        # no retired history.
        spec_snaps = [p.get("spec") for p in per.values()
                      if isinstance(p.get("spec"), dict)]
        if spec_snaps or retired_spec:
            spec: Dict[str, Any] = {}
            for key in self._SPEC_SUM_KEYS:
                vals = [s.get(key) for s in spec_snaps
                        if isinstance(s.get(key), (int, float))]
                spec[key] = sum(vals) + retired_spec.get(key, 0)
            prop = spec.get("draft_tokens_total", 0)
            spec["accept_rate"] = (
                spec.get("accepted_tokens_total", 0) / prop
                if prop else None)
            steps = spec.get("steps_total", 0)
            spec["tokens_per_step"] = (
                spec.get("emitted_tokens_total", 0) / steps
                if steps else None)
            snap["spec"] = spec
            ks = [p.get("spec_k") for p in per.values()
                  if isinstance(p.get("spec_k"), int)]
            snap["spec_k"] = max(ks) if ks else 0
        # Per-tenant counter aggregates (multi-tenant adapters): summed
        # across live replicas plus retired baselines, keyed exactly as
        # one engine's snapshot keys them.
        tenants: Dict[str, Dict[str, float]] = {
            t: dict(v) for t, v in retired_tenants.items()}
        for p in per.values():
            for name, tv in (p.get("tenants") or {}).items():
                agg = tenants.setdefault(name, {})
                for key in self._TENANT_SUM_KEYS:
                    v = tv.get(key)
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        agg[key] = agg.get(key, 0) + v
        # Fleet-wide SLO burn per tenant, recomputed from the summed
        # counters (see _TENANT_SUM_KEYS): misses over SLO-scoped
        # outcomes, exactly the per-engine ServeMetrics._burn formula.
        fleet_slo: Dict[str, float] = {}
        for name, agg in tenants.items():
            outcomes = (agg.get("first_tokens_total", 0)
                        + agg.get("deadline_miss_total", 0))
            if outcomes:
                burn = (agg.get("ttft_slo_miss_total", 0)
                        + agg.get("deadline_miss_total", 0)) / outcomes
                agg["slo_burn"] = burn
                if burn > 0:
                    fleet_slo[name] = burn
        if tenants:
            snap["tenants"] = tenants
        k = self.adapters_resident()
        if k is not None:
            snap["adapters_resident"] = k
        snap["replicas"] = per
        adapter_dispatch = self._metrics.adapter_dispatch_counts()
        prefix_dispatch = self._metrics.prefix_dispatch_counts()
        snap["fleet"] = {
            "replicas": len(per),
            "states": states,
            **{f"n_{s}": n for s, n in self.counts().items()},
            "dispatch_total": self._metrics.dispatch_counts(),
            "scale_events": self._metrics.scale_counts(),
            "failover_total": self._metrics.failover_counts(),
            "streams_stranded_total": self._metrics.stranded_count(),
            **({"adapter_dispatch": adapter_dispatch}
               if adapter_dispatch else {}),
            **({"prefix_dispatch": prefix_dispatch}
               if prefix_dispatch else {}),
            # Tenants currently burning their SLO fleet-wide (burn > 0)
            # — the at-a-glance overload triage block; per-tenant detail
            # (targets, misses, percentiles) lives under "tenants".
            **({"slo_burning": fleet_slo} if fleet_slo else {}),
        }
        return snap

    def prom_collect(self):
        """The fleet's ``(meta, samples)``: every replica's samples with
        a ``replica=`` label added, merged with the fleet-plane series
        (``hvd_fleet_replicas{state=}``,
        ``hvd_fleet_dispatch_total{replica=}``,
        ``hvd_fleet_scale_events_total{direction=}``) — ONE render is
        one valid exposition (grouped by metric name, single ``# TYPE``
        each; the reason this cannot be string concatenation)."""
        self._refresh_gauges()
        meta: Dict = {}
        samples: List = []
        for h in self.replicas():
            try:
                m, s = h.engine.prom_collect()
            except Exception:  # noqa: BLE001 — scrape what still answers
                continue
            meta.update(m)
            samples.extend((name, {**labels, "replica": h.name}, v)
                           for name, labels, v in s)
        m, s = self._metrics.registry.collect()
        meta.update(m)
        samples.extend(s)
        return meta, samples

    def prom_metrics(self) -> str:
        """Prometheus text exposition of :meth:`prom_collect` (the
        fleet ``/metrics`` body)."""
        from ..obs.registry import render
        return render(*self.prom_collect())
