"""Thin HTTP front end over :class:`~horovod_tpu.serve.engine.Engine`.

Stdlib-only (``http.server``) by design: the engine is the product, the
wire protocol is a demo/testing surface, and the container must not grow
a web-framework dependency for it. Production fronting belongs on a real
ingress; this one maps the engine's backpressure contract onto HTTP
status codes so clients see conventional semantics:

* ``POST /predict`` with ``{"inputs": <nested list>}`` → 200
  ``{"outputs": ...}``
* queue full (:class:`ServerOverloadedError`) → **503** (retryable)
* deadline expired (:class:`DeadlineExceededError`) → **504**
* shut down (:class:`ServerClosedError`) → 503 with a terminal hint
* bad shape/JSON → 400
* ``POST /generate`` (when a :class:`~.generate.GenerationEngine` is
  attached) → 200 with **chunked** streaming: one JSON line per sampled
  token (``{"token": 17}``) the moment the engine emits it, then a final
  ``{"done": true, "finish_reason": ..., ...}`` line. ``"stream": false``
  buffers into one ``{"tokens": [...], ...}`` object. Backpressure maps
  exactly as ``/predict`` (the stream only starts once the first token
  exists, so deadline/overload failures still get real status codes).
* ``GET /stats`` → 200, the engine's snapshot dict as JSON
* ``GET /metrics`` → 200, the same numbers in Prometheus text
  exposition (stable ``hvd_*`` series, ``engine=`` label per attached
  engine; ``docs/observability.md`` holds the inventory)
* ``GET /healthz`` → readiness probe: **503** before ``warmup()``
  completes and once drain/shutdown begins, 200 with the current queue
  depth otherwise — so a load balancer stops routing to a cold engine
  (first bucket hits pay a compile) or a dying one (new requests would
  race the drain)

Either slot (``engine=`` / ``generate=``) also accepts a
:class:`~.router.FleetRouter` — the router duck-types the engine
surface, so mounting a replicated fleet changes nothing here:
``POST /generate`` / ``POST /predict`` route through the router's
least-depth dispatch, ``GET /stats`` nests per-replica snapshots under
``"replicas"``, ``GET /metrics`` is ONE merged exposition whose
per-replica samples carry a ``replica=`` label next to the fleet-plane
series, and ``GET /healthz`` reports fleet-level readiness (>= 1 ready
replica) with the membership breakdown in the body.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..exceptions import (DeadlineExceededError, PreemptedError,
                          ServerClosedError, ServerOverloadedError)
from .engine import Engine


class _Handler(BaseHTTPRequestHandler):
    engine: Engine = None        # installed by HttpServer
    gen_engine = None            # optional GenerationEngine
    # HTTP/1.1 for Transfer-Encoding: chunked (the /generate stream);
    # every non-chunked reply carries Content-Length, so keep-alive works.
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet: the engine's metrics are the log
        pass

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _overloaded(self, e) -> None:
        """The 503 for :class:`ServerOverloadedError`, carrying the
        engine's ``retry_after_ms`` hint (queue depth ÷ measured
        service rate) in the body AND as a conventional ``Retry-After``
        header — so well-behaved clients back off proportionally to the
        actual drain time instead of hammering a full door."""
        body = {"error": str(e), "retryable": True}
        headers = None
        ra = getattr(e, "retry_after_ms", None)
        if isinstance(ra, (int, float)) and not isinstance(ra, bool):
            body["retry_after_ms"] = float(ra)
            headers = {"Retry-After": str(max(1, int(-(-ra // 1000))))}
        self._reply(503, body, headers)

    def _primary(self):
        """The engine whose health/stats this server reports: the
        single-shot engine when present, else the generation engine."""
        return self.engine if self.engine is not None else self.gen_engine

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/stats":
            snap = self._primary().stats()
            if self.engine is not None and self.gen_engine is not None:
                snap["generate"] = self.gen_engine.stats()
            self._reply(200, snap)
        elif path == "/metrics":
            # Prometheus text exposition: everything /stats knows, on
            # the stable hvd_* series names. With both engines attached
            # the samples MERGE before rendering (each carries its
            # engine= label) — concatenating two renders would repeat
            # # TYPE lines and split name groups, which the exposition
            # format forbids.
            meta, samples = {}, []
            for eng in (self.engine, self.gen_engine):
                if eng is not None:
                    m, s = eng.prom_collect()
                    meta.update(m)
                    samples.extend(s)
            from ..obs.registry import render
            body = render(meta, samples).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            primary = self._primary()
            ready, status, depth = primary.health()
            if ready and self.gen_engine is not None \
                    and self.gen_engine is not primary:
                ready, status, depth = self.gen_engine.health()
            body = {"status": status, "queue_depth": depth}
            # A mounted FleetRouter knows more than ok/warming/draining:
            # include the membership breakdown so a probe (or operator
            # curl) sees HOW ready the fleet is, not just whether. A
            # router may sit in EITHER slot (e.g. a single-shot primary
            # with a generation fleet) — ask both.
            for eng in (primary, self.gen_engine):
                fleet_health = getattr(eng, "fleet_health", None)
                if callable(fleet_health):
                    body["replicas"] = fleet_health()
                    # Subprocess members serve their own /metrics; the
                    # router never relays those samples (federation, not
                    # proxying), so advertise the endpoints here for a
                    # scraper (FleetPoller) to walk — one scrape per
                    # endpoint per poll.
                    eps = getattr(eng, "replica_metrics_endpoints", None)
                    if callable(eps):
                        endpoints = eps()
                        if endpoints:
                            body["replica_metrics"] = endpoints
                    break
            # Adapter-table residency (multi-tenant serving): how many
            # fine-tunes this endpoint can serve right now. Engines and
            # routers both answer adapters_resident(); None (no registry
            # anywhere) keeps the field out of the body.
            for eng in (primary, self.gen_engine):
                fn = getattr(eng, "adapters_resident", None)
                if callable(fn):
                    k = fn()
                    if k is not None:
                        body["adapters_resident"] = int(k)
                        break
            self._reply(200 if ready else 503, body)
        else:
            self._reply(404, {"error": f"no such path {self.path}"})

    # -- generation streaming ----------------------------------------------

    def _chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

    def _do_generate(self):
        if self.gen_engine is None:
            self._reply(404, {"error": "no generation engine attached"})
            return
        from .generate import SamplingParams
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(req, dict):
                raise ValueError(
                    f"body must be a JSON object, got {type(req).__name__}")
            tokens = [int(t) for t in req["tokens"]]
            sampling = SamplingParams(
                temperature=float(req.get("temperature", 0.0)),
                top_k=int(req.get("top_k", 0)),
                seed=int(req.get("seed", 0)))
            kw = {}
            if req.get("adapter") is not None:
                # Multi-tenant serving: the tenant's resident LoRA
                # fine-tune (docs/inference.md "Multi-tenant adapters").
                kw["adapter"] = str(req["adapter"])
            if req.get("max_new_tokens") is not None:
                kw["max_new_tokens"] = int(req["max_new_tokens"])
            if "eos" in req:
                kw["eos_id"] = (None if req["eos"] is None
                                else int(req["eos"]))
            if req.get("deadline_ms") is not None:
                kw["deadline_ms"] = float(req["deadline_ms"])
            stream = bool(req.get("stream", True))
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        streaming = False
        try:
            handle = self.gen_engine.submit(tokens, sampling=sampling, **kw)
            if not stream:
                self._reply(200, handle.result())
                return
            # Hold the headers until the first event: a request that dies
            # in the queue (deadline/shutdown) still gets a real status
            # code instead of a 200 that breaks mid-stream.
            kind, val = handle.next_event()
            if kind == "error":
                raise val
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            streaming = True
            while True:
                if kind == "token":
                    self._chunk(json.dumps({"token": val}).encode() + b"\n")
                elif kind == "done":
                    done = dict(val)
                    done["done"] = True
                    self._chunk(json.dumps(done).encode() + b"\n")
                    break
                else:   # error after tokens already streamed: terminal line
                    self._chunk(json.dumps(
                        {"error": repr(val), "done": True}).encode() + b"\n")
                    break
                kind, val = handle.next_event()
            self._chunk(b"")    # 0-length chunk terminates the stream
        except ServerOverloadedError as e:
            self._overloaded(e)
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
        except PreemptedError as e:
            # Preempted past the retry budget BEFORE the first token
            # (headers not sent yet): a retryable 503, with the typed
            # repr in the body so a subprocess-replica client can map it
            # back to PreemptedError (mid-stream exhaustion already rides
            # the terminal error line as a repr).
            self._reply(503, {"error": repr(e), "retryable": True})
        except ServerClosedError as e:
            self._reply(503, {"error": str(e), "retryable": False})
        except ValueError as e:
            self._reply(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — the engine funnels ALL its
            # failures (XLA runtime errors included) into the handle, so
            # arbitrary exception types re-raise here; without this the
            # client sees a connection reset instead of a status code.
            if streaming:
                raise   # headers already sent: let the server close the
                        # socket (the in-loop error branch covers handle
                        # failures; only wfile errors reach here)
            self._reply(500, {"error": f"generation failed: {e!r}"})

    def do_POST(self):
        if self.path == "/generate":
            self._do_generate()
            return
        if self.path != "/predict" or self.engine is None:
            self._reply(404, {"error": f"no such path {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(req, dict):
                raise ValueError(
                    f"body must be a JSON object, got {type(req).__name__}")
            x = np.asarray(req["inputs"])
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)   # "abc" -> 400 below
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        try:
            out = self.engine.infer(x, deadline_ms=deadline_ms)
            self._reply(200, {"outputs": np.asarray(out).tolist()})
        except ServerOverloadedError as e:
            self._overloaded(e)
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
        except ServerClosedError as e:
            self._reply(503, {"error": str(e), "retryable": False})
        except ValueError as e:   # shape mismatch from Engine.submit
            self._reply(400, {"error": str(e)})


class HttpServer:
    """Serve an :class:`Engine` (and/or a
    :class:`~.generate.GenerationEngine` via ``generate=``) over HTTP on
    a background thread. With both attached, ``/predict`` hits the
    single-shot engine and ``/generate`` the generation engine;
    ``/healthz`` is ready only when every attached engine is, and
    ``/stats`` nests the generation snapshot under ``"generate"``.

    ``port=0`` binds an ephemeral port (read it back from ``.port``) —
    the test-friendly default.
    """

    def __init__(self, engine: Optional[Engine] = None,
                 host: str = "127.0.0.1", port: int = 0, *,
                 generate=None):
        if engine is None and generate is None:
            raise ValueError(
                "HttpServer needs an engine= and/or a generate= engine")
        handler = type("BoundHandler", (_Handler,),
                       {"engine": engine, "gen_engine": generate})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="hvd-serve-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
