"""Thin HTTP front end over :class:`~horovod_tpu.serve.engine.Engine`.

Stdlib-only (``http.server``) by design: the engine is the product, the
wire protocol is a demo/testing surface, and the container must not grow
a web-framework dependency for it. Production fronting belongs on a real
ingress; this one maps the engine's backpressure contract onto HTTP
status codes so clients see conventional semantics:

* ``POST /predict`` with ``{"inputs": <nested list>}`` → 200
  ``{"outputs": ...}``
* queue full (:class:`ServerOverloadedError`) → **503** (retryable)
* deadline expired (:class:`DeadlineExceededError`) → **504**
* shut down (:class:`ServerClosedError`) → 503 with a terminal hint
* bad shape/JSON → 400
* ``GET /stats`` → 200, the engine's snapshot dict as JSON
* ``GET /healthz`` → readiness probe: **503** before ``warmup()``
  completes and once drain/shutdown begins, 200 with the current queue
  depth otherwise — so a load balancer stops routing to a cold engine
  (first bucket hits pay a compile) or a dying one (new requests would
  race the drain)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..exceptions import (DeadlineExceededError, ServerClosedError,
                          ServerOverloadedError)
from .engine import Engine


class _Handler(BaseHTTPRequestHandler):
    engine: Engine = None  # installed by HttpServer

    def log_message(self, *a):  # quiet: the engine's metrics are the log
        pass

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/")
        if path == "/stats":
            self._reply(200, self.engine.stats())
        elif path == "/healthz":
            ready, status, depth = self.engine.health()
            self._reply(200 if ready else 503,
                        {"status": status, "queue_depth": depth})
        else:
            self._reply(404, {"error": f"no such path {self.path}"})

    def do_POST(self):
        if self.path != "/predict":
            self._reply(404, {"error": f"no such path {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(req, dict):
                raise ValueError(
                    f"body must be a JSON object, got {type(req).__name__}")
            x = np.asarray(req["inputs"])
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)   # "abc" -> 400 below
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            self._reply(400, {"error": f"bad request: {e!r}"})
            return
        try:
            out = self.engine.infer(x, deadline_ms=deadline_ms)
            self._reply(200, {"outputs": np.asarray(out).tolist()})
        except ServerOverloadedError as e:
            self._reply(503, {"error": str(e), "retryable": True})
        except DeadlineExceededError as e:
            self._reply(504, {"error": str(e)})
        except ServerClosedError as e:
            self._reply(503, {"error": str(e), "retryable": False})
        except ValueError as e:   # shape mismatch from Engine.submit
            self._reply(400, {"error": str(e)})


class HttpServer:
    """Serve an :class:`Engine` over HTTP on a background thread.

    ``port=0`` binds an ephemeral port (read it back from ``.port``) —
    the test-friendly default.
    """

    def __init__(self, engine: Engine, host: str = "127.0.0.1",
                 port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"engine": engine})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="hvd-serve-http", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
