"""Continuous-batching autoregressive generation over the KV-cache model
layer (``parallel.transformer.prefill``/``decode_step``).

The single-shot :class:`~.engine.Engine` batches whole requests; a
generation workload cannot — requests finish at different times, and
per-request batching would idle every slot until the slowest stream ends.
This engine does Orca-style *iteration-level* scheduling over vLLM-style
slot-managed KV memory instead:

* **Slots, not batches.** The decode step always executes at the fixed
  ``[max_slots]`` shape — ONE compiled program regardless of occupancy —
  and requests join/leave the batch at every decode-step boundary. A new
  request prefills into a free slot while its neighbors are mid-stream;
  a finished request frees its slot without anyone else noticing. Slot
  rows are numerically independent (each row of every matmul / softmax /
  cache read-write depends only on that row), so a request's token stream
  is **bit-identical** whether it runs alone or joins a busy batch — the
  invariance contract tests/test_generate.py pins.
* **KV memory is a layout knob** (``GenerationConfig.kv_layout``):
  ``"contiguous"`` reserves ``max_len`` rows per slot (capacity bounded
  by worst-case length), ``"paged"`` carves the same bytes into a
  fixed-size block pool with per-slot block tables
  (:mod:`horovod_tpu.parallel.kv_blocks`) — a stream holds only the
  blocks it fills, "cache full" becomes "block pool empty", and
  admission tracks free BLOCKS next to free slots. ``prefix_reuse=True``
  additionally shares full block-aligned prompt prefixes copy-on-write
  across streams (a system prompt's K/V lives once). Streams stay
  bit-identical across all three configurations
  (tests/test_paged_kv.py).
* **Compile cache** (the PR-2 pattern): one AOT-compiled decode
  executable for the engine's (max_slots, max_len), plus one prefill
  executable per power-of-two prompt bucket; :meth:`GenerationEngine.
  warmup` pre-compiles and pre-executes all of them so no user request
  ever pays a compile.
* **Sampling is per-request and host-side**: greedy / temperature /
  top-k, each request seeded with its own ``numpy`` Generator so a
  stream is reproducible no matter what shares its batch.
* **Backpressure carries over from PR 2** unchanged: bounded admission
  queue (:class:`~horovod_tpu.exceptions.ServerOverloadedError` at the
  door), deadlines checked when a request is dequeued into a slot
  (:class:`~horovod_tpu.exceptions.DeadlineExceededError` through the
  handle), graceful drain on shutdown, ``/healthz`` readiness via
  :class:`~.engine.ReadinessMixin`.
* **Overload degrades fairly, not FIFO-unfairly**: admission into free
  decode slots is ordered by :class:`~.sched.FairScheduler` (weighted
  deficit round-robin over tenants, strict priority classes above it) —
  pure host-side data, zero new compiled programs. Per-tenant KV block
  budgets (``tenant_block_budgets``) make one tenant's
  ``blocks_exhausted`` reject only THAT tenant; a higher-priority
  admission that finds no slot or blocks may preempt-by-evict the
  lowest-priority stream, capturing its envelope exactly like a
  replica-death failover and replaying it bit-identically in place
  (terminal reason ``preempted_exhausted`` only past
  ``preempt_retries``).

The loop is one background thread: the decode step is a single
accelerator program, and one consumer keeps slot assignment and the
queue's FIFO semantics trivially correct (fairness reorders held
requests ACROSS tenants only; within a tenant, FIFO holds).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue as std_queue
import signal
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import (DeadlineExceededError, PreemptedError,
                          ServerClosedError, ServerOverloadedError)
from ..obs import flightrec
from ..testing import faults
from ..parallel.kv_blocks import (TRASH_BLOCK, BlockManager, blocks_for,
                                  init_paged_kv_cache,
                                  paged_chunked_prefill, paged_decode_step,
                                  paged_prefill, paged_verify_step,
                                  prefix_route_digest)
from ..parallel.transformer import (TransformerConfig, decode_step,
                                    init_kv_cache, prefill, verify_step)
from .adapters import AdapterRegistry
from .batcher import RequestQueue, bucket_for
from .engine import ReadinessMixin
from .metrics import ServeMetrics
from .sched import FairScheduler
from .spec import SpecConfig, accept_greedy, accept_sampled

_DEFAULT = object()    # "knob not passed" sentinel (None is a real value)


def prefill_buckets(max_len: int) -> Tuple[int, ...]:
    """Prompt-padding buckets: powers of two below ``max_len``, topped by
    ``max_len`` itself — so the compile cache is ``log2(max_len)+1``
    programs and every bucket fits the cache."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    sizes: List[int] = []
    b = 1
    while b < max_len:
        sizes.append(b)
        b *= 2
    sizes.append(max_len)
    return tuple(sizes)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs. ``temperature <= 0`` is greedy (argmax;
    ``top_k``/``seed`` ignored). ``top_k=0`` samples the full vocab.
    ``seed`` makes the stream reproducible: the request owns a private
    ``numpy`` Generator, so identical (prompt, params, seed) produce an
    identical stream regardless of what else shares the batch."""

    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class GenerationConfig:
    """Engine knobs. ``max_slots`` is the decode batch width (the number
    of concurrently generating requests) and ``max_len`` the per-request
    cache depth cap (prompt + generated tokens). How much HBM that costs
    depends on ``kv_layout``:

    * ``"contiguous"`` reserves ``max_len`` rows per slot up front —
      ``2 · n_layers · max_slots · max_len · d_model`` cache elements,
      capacity bounded by the WORST-case sequence length.
    * ``"paged"`` allocates ``2 · n_layers · n_blocks · block_size ·
      d_model`` elements once and hands slots blocks as they fill them;
      a short stream holds only ``ceil(len/block_size)`` blocks, so the
      same bytes admit more concurrent short streams, and admission is
      bounded by free blocks (``blocks_exhausted``) as well as free
      slots (``slots_full``).

    ``block_size`` (paged) is the positions-per-block knob — a
    TPU-lane-friendly power of two; 16 default. ``n_blocks`` (paged)
    sizes the pool INCLUDING the reserved trash block; ``None`` matches
    the contiguous footprint (``max_slots · ceil(max_len/block_size) +
    1``). ``prefix_reuse`` (paged) shares full block-aligned prompt
    prefixes copy-on-write across streams. ``paged_kernel`` gathers
    decode attention through the Pallas paged kernel where supported
    (``ops.pallas_paged_attention``); off = the pure-lax gather
    fallback, the bit-identity reference, everywhere-green path.

    ``chunked_prefill`` (paged + prefix_reuse) switches EVERY admission
    to :func:`~horovod_tpu.parallel.kv_blocks.paged_chunked_prefill`: a
    prefix-hit admission compiles/executes a SUFFIX-sized program that
    reads the hit blocks' K/V out of the pool instead of recomputing
    them, and a cold admission is the same scan started at block 0 — so
    hit and cold streams stay bitwise identical (the chunked engine's
    bit-identity reference is ITSELF, not the non-chunked layouts; see
    the kv_blocks docstring). ``chunk_blocks`` is the scan's chunk width
    in blocks; ``max_len`` must hold at least two chunks and divide
    evenly by the chunk.

    ``host_blocks`` (paged + prefix_reuse) adds a host-memory tier of
    that many blocks: cold registered prefixes offload there instead of
    being dropped at reclaim, and an admission whose chain continues in
    the host tier kicks an async prefetch — the decode step NEVER
    blocks on a fetch. ``host_admission`` picks what that admission does
    meanwhile: ``"wait"`` holds it in the queue until the prefetch lands
    (FIFO preserved), ``"miss"`` admits immediately with the device-tier
    hits only (recompute, never a stale read).

    The multi-tenant scheduling policy (all host-side data — none of
    these knobs is a compile key): ``tenant_weights`` /
    ``tenant_priorities`` / ``tenant_slo_ttft_ms`` map tenant names
    ("base" included) to their fair-share weight (> 0, default 1),
    strict priority class (higher admits first and may preempt lower;
    default 0) and TTFT SLO target in ms (feeds the
    ``hvd_tenant_slo_*`` burn series). An attached
    :class:`~.adapters.AdapterRegistry` row's own weight/priority/SLO
    overrides these engine defaults per tenant. ``tenant_block_budgets``
    (paged only) caps how many KV pool blocks a tenant may hold — over
    budget, a tenant's admissions are rejected (``blocks_exhausted``
    with a ``retry_after_ms`` hint) or starved WITHOUT holding any
    other tenant's line, and the tenant offloads/reclaims its OWN
    coldest blocks first. ``preempt``/``preempt_retries`` gate
    preempt-by-evict: whether a higher-priority admission may evict the
    lowest-priority active stream, and how many evictions one stream
    survives before failing with terminal reason
    ``preempted_exhausted``.

    The rest mirrors :class:`~.engine.ServeConfig`'s backpressure
    contract."""

    max_slots: int = 8
    max_len: int = 512
    max_queue: int = 256
    default_deadline_ms: Optional[float] = None
    default_max_new_tokens: int = 64
    eos_id: Optional[int] = None
    kv_layout: str = "contiguous"
    block_size: int = 16
    n_blocks: Optional[int] = None
    prefix_reuse: bool = False
    paged_kernel: bool = False
    chunked_prefill: bool = False
    chunk_blocks: int = 1
    host_blocks: int = 0
    host_admission: str = "wait"
    tenant_weights: Optional[Dict[str, float]] = None
    tenant_priorities: Optional[Dict[str, int]] = None
    tenant_block_budgets: Optional[Dict[str, int]] = None
    tenant_slo_ttft_ms: Optional[Dict[str, float]] = None
    preempt: bool = True
    preempt_retries: int = 3

    def __post_init__(self):
        if self.max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {self.max_slots}")
        if self.max_len < 1:
            raise ValueError(f"max_len must be >= 1, got {self.max_len}")
        if self.default_max_new_tokens < 1:
            raise ValueError("default_max_new_tokens must be >= 1")
        if self.kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'contiguous' or 'paged', got "
                f"{self.kv_layout!r}")
        if self.block_size < 1 or (self.block_size & (self.block_size - 1)):
            raise ValueError(
                f"block_size must be a power of two, got {self.block_size}")
        if self.chunk_blocks < 1 or (self.chunk_blocks
                                     & (self.chunk_blocks - 1)):
            raise ValueError(
                f"chunk_blocks must be a power of two, got "
                f"{self.chunk_blocks}")
        if self.host_blocks < 0:
            raise ValueError(
                f"host_blocks must be >= 0, got {self.host_blocks}")
        if self.host_admission not in ("wait", "miss"):
            raise ValueError(
                f"host_admission must be 'wait' or 'miss', got "
                f"{self.host_admission!r}")
        if self.kv_layout != "paged":
            for knob in ("prefix_reuse", "paged_kernel", "chunked_prefill",
                         "host_blocks"):
                if getattr(self, knob):
                    raise ValueError(
                        f"{knob} requires kv_layout='paged'")
            if self.n_blocks is not None:
                raise ValueError(
                    "n_blocks applies to kv_layout='paged' only")
        elif self.n_blocks is not None and self.n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is the reserved trash "
                f"block), got {self.n_blocks}")
        if self.chunked_prefill:
            if not self.prefix_reuse:
                raise ValueError(
                    "chunked_prefill=True requires prefix_reuse=True "
                    "(its whole point is skipping prefix-hit compute)")
            c = self.chunk_tokens
            if self.max_len % c or self.max_len < 2 * c:
                raise ValueError(
                    f"chunked_prefill needs max_len divisible by the "
                    f"chunk ({self.chunk_blocks} blocks × "
                    f"{self.block_size} = {c} tokens) and at least two "
                    f"chunks, got max_len={self.max_len}")
        if self.host_blocks and not self.prefix_reuse:
            raise ValueError(
                "host_blocks > 0 requires prefix_reuse=True (only "
                "registered prefixes ever offload)")
        for t, w in (self.tenant_weights or {}).items():
            if w <= 0:
                raise ValueError(
                    f"tenant_weights[{t!r}] must be > 0, got {w} (use "
                    f"tenant_priorities, not zero weights, to de-class "
                    f"a tenant)")
        for t, s in (self.tenant_slo_ttft_ms or {}).items():
            if s <= 0:
                raise ValueError(
                    f"tenant_slo_ttft_ms[{t!r}] must be > 0, got {s}")
        if self.tenant_block_budgets:
            if self.kv_layout != "paged":
                raise ValueError(
                    "tenant_block_budgets requires kv_layout='paged' "
                    "(contiguous slots have no block pool to budget)")
            for t, b in self.tenant_block_budgets.items():
                if b < 1:
                    raise ValueError(
                        f"tenant_block_budgets[{t!r}] must be >= 1, "
                        f"got {b}")
        if self.preempt_retries < 0:
            raise ValueError(
                f"preempt_retries must be >= 0, got {self.preempt_retries}")

    @property
    def chunk_tokens(self) -> int:
        """Tokens per chunked-prefill scan trip."""
        return self.chunk_blocks * self.block_size

    @property
    def blocks_per_slot(self) -> int:
        """Blocks a full-depth (``max_len``) sequence occupies."""
        return blocks_for(self.max_len, self.block_size)

    @property
    def resolved_n_blocks(self) -> int:
        """``n_blocks`` with the default applied (contiguous-footprint
        pool + the trash block)."""
        if self.n_blocks is not None:
            return self.n_blocks
        return self.max_slots * self.blocks_per_slot + 1


class GenerationHandle:
    """Streaming result of one generation request.

    Consume incrementally (``for tok in handle: ...`` yields token ids as
    they are sampled; raises the failure exception if the request dies)
    or wait for completion: ``handle.result(timeout)`` returns
    ``{"tokens", "finish_reason" ("eos"|"length"), "n_tokens",
    "ttft_ms", "tokens_per_sec"}``. Both can be used together — the
    iterator drains a private event queue, ``result`` reads the
    accumulated state.
    """

    def __init__(self):
        self._events: std_queue.Queue = std_queue.Queue()
        self._done = threading.Event()
        self._tokens: List[int] = []
        self._error: Optional[BaseException] = None
        self._info: Optional[Dict] = None
        self.request: Any = None    # the engine's _GenRequest (debug/test)

    # -- engine side -------------------------------------------------------

    def _emit(self, tok: int) -> None:
        self._tokens.append(tok)
        self._events.put(("token", tok))

    def _finish(self, info: Dict) -> None:
        self._info = info
        self._done.set()
        self._events.put(("done", info))

    def _fail(self, exc: BaseException) -> None:
        if self._done.is_set():
            return
        self._error = exc
        self._done.set()
        self._events.put(("error", exc))

    # -- client side -------------------------------------------------------

    def next_event(self, timeout: Optional[float] = None):
        """``("token", id)`` / ``("done", info)`` / ``("error", exc)`` in
        emission order; raises ``queue.Empty`` on timeout."""
        return self._events.get(timeout=timeout)

    def __iter__(self):
        while True:
            kind, val = self._events.get()
            if kind == "token":
                yield val
            elif kind == "done":
                return
            else:
                raise val

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Dict:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"generation not finished within {timeout} s")
        if self._error is not None:
            raise self._error
        return dict(self._info)


@dataclasses.dataclass
class _GenRequest:
    """One queued/in-flight generation request."""

    tokens: np.ndarray               # [L] int32 prompt
    max_new: int
    sampling: SamplingParams
    eos: Optional[int]
    handle: GenerationHandle
    enqueued_at: float               # time.monotonic()
    deadline_at: Optional[float]
    rng: np.random.Generator
    n_out: int = 0
    t_admit: Optional[float] = None     # dequeued into a slot
    t_first: Optional[float] = None     # first token sampled
    # Multi-tenant adapter identity: tenant is the quota/metrics key
    # ("base" for adapter-less traffic), adapter the registry name (None
    # = base), adapter_slot the table row the stream's adapter_idx pins
    # for its whole lifetime (resolved at submit, protected by the
    # registry refcount until _req_done releases it).
    tenant: str = "base"
    adapter: Optional[str] = None
    adapter_slot: int = -1
    # Engine-local stream id: the flight recorder's serving events
    # (admit/complete/crash) key on it, so a dead replica's post-mortem
    # can name exactly which streams were in flight.
    stream_id: int = -1
    # Prefix-reuse registry salt: a prompt's cached K/V is a function of
    # the weights that wrote it, so tenants must never hit each other's
    # prefixes (nor a reloaded adapter its predecessor's). Base traffic
    # carries the reserved NUL frame, NOT b"": adapter salts start with
    # a name character ([A-Za-z0-9], never NUL), so a base key can never
    # byte-equal an adapter key even when crafted token values spell an
    # adapter's salt — with an unframed b"" it could.
    prefix_salt: bytes = b"\x00"
    _done_accounted: bool = False
    # Speculation accounting (engine-filled when spec decoding is on):
    # drafts proposed for / accepted into this stream.
    spec_proposed: int = 0
    spec_accepted: int = 0
    # Priority class resolved at submit (registry row, else the
    # config map, else 0) — data the scheduler and the preemption
    # plane read; never a compile key.
    priority: int = 0
    # Preemption envelope (the engine-local analog of the fleet
    # failover replay): times this stream was evicted from its slot,
    # and — while resuming — the already-emitted prefix to regenerate
    # suppressed-and-verified before anything new reaches the handle.
    retries: int = 0
    replay_expect: Optional[List[int]] = None
    replay_i: int = 0
    # Held-line bookkeeping: whether this request holds a max_queue
    # admission ticket (False for preempted re-held streams — they were
    # admitted once already), and the host-tier prefetch keys it staged
    # (released if it expires while parked in the held line).
    held_ticket: bool = False
    prefetch_keys: set = dataclasses.field(default_factory=set)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_at is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline_at

    def sample(self, logits: np.ndarray) -> int:
        t = self.sampling.temperature
        if t <= 0:
            return int(np.argmax(logits))
        x = logits.astype(np.float64) / float(t)
        k = self.sampling.top_k
        keep = None
        if k and k < x.size:
            keep = np.argpartition(x, -k)[-k:]
            x = x[keep]
        e = np.exp(x - np.max(x))
        p = e / e.sum()
        j = int(self.rng.choice(p.size, p=p))
        return int(keep[j]) if keep is not None else j

    def probs(self, logits: np.ndarray) -> np.ndarray:
        """Full-vocab probabilities under this request's temperature /
        top-k transform — the TARGET distribution :meth:`sample` draws
        from, as the speculative rejection rule needs it (an arbitrary
        draft token's probability must be addressable; outside top-k it
        is exactly 0, so off-support drafts always reject). Callers
        guarantee ``temperature > 0``."""
        t = self.sampling.temperature
        x = logits.astype(np.float64) / float(t)
        k = self.sampling.top_k
        if k and k < x.size:
            keep = np.argpartition(x, -k)[-k:]
            xk = x[keep]
            e = np.exp(xk - np.max(xk))
            p = np.zeros(x.size, np.float64)
            p[keep] = e / e.sum()
            return p
        e = np.exp(x - np.max(x))
        return e / e.sum()


class GenerationEngine(ReadinessMixin):
    """Continuous-batching generation server over one transformer.

    Args:
      params: the ``parallel.transformer`` param pytree — from
        ``init_params``, or ``restore_for_inference(..., dtype=)`` (plain
        fp32/bf16 leaves, or int8 :class:`~horovod_tpu.ops.quant.
        QuantizedTensor` leaves, dequantized inside the compiled forward).
        Pre-sharded global ``jax.Array`` leaves serve as laid out.
      model_cfg: the :class:`~horovod_tpu.parallel.transformer.
        TransformerConfig` the params belong to (dense FFN only).
      config: :class:`GenerationConfig`.
      adapters: optional :class:`~.adapters.AdapterRegistry` — the
        multi-tenant plane. With it, ``submit(adapter="name")`` serves
        that tenant's LoRA fine-tune: the per-slot ``adapter_idx``
        gathers the tenant's table row inside the SAME compiled
        prefill/decode programs (one compile cache whether the batch is
        base-only or mixed-adapter), per-tenant quotas gate admission,
        and ``/stats``/``/metrics`` split TTFT and tokens by tenant.
    """

    def __init__(self, params: Any, model_cfg: TransformerConfig,
                 config: GenerationConfig = GenerationConfig(), *,
                 adapters: Optional[AdapterRegistry] = None,
                 spec: Optional[SpecConfig] = None):
        if model_cfg.n_experts:
            raise NotImplementedError(
                "generation supports dense FFNs only (n_experts=0)")
        self._params = params
        self._model_cfg = model_cfg
        self._cfg = config
        self._adapters = adapters
        # Per-slot adapter table row, the decode program's gather index
        # (-1 = base). Data, not a compile key.
        self._adapter_idx = np.full((config.max_slots,), -1, np.int32)
        self._tenant_lock = threading.Lock()
        self._tenant_inflight: Dict[str, int] = {}
        self._queue = RequestQueue(config.max_queue)
        self._metrics = ServeMetrics()
        if adapters is not None:
            # Tenant churn must not grow per-tenant metric state without
            # bound: fold an evicted tenant's counters into "retired".
            adapters.add_evict_listener(self._metrics.forget_tenant)
        # Fair admission: WDRR over tenants + strict priority classes.
        # Weight/priority lookups go through the engine resolvers so a
        # registry set_weight/set_priority applies from the next pick.
        self._sched = FairScheduler(self._weight_of, self._priority_of)
        # Block DEMAND a tenant has in flight (reserved at the door,
        # freed at _req_done) — the budget's admission-time half; the
        # pool's owner ledger is the occupancy half. Under _tenant_lock.
        self._tenant_blocks: Dict[str, int] = {}
        self._paged = config.kv_layout == "paged"
        s = config.max_slots
        if self._paged:
            from ..ops.pallas_paged_attention import paged_attention_supported
            self._n_blocks = config.resolved_n_blocks
            self._cache = init_paged_kv_cache(
                model_cfg, self._n_blocks, config.block_size, s)
            self._blocks = BlockManager(self._n_blocks, config.block_size,
                                        host_blocks=config.host_blocks)
            for t, b in (config.tenant_block_budgets or {}).items():
                self._blocks.set_budget(t, int(b))
            max_blocks = config.blocks_per_slot
            self._tables = np.full((s, max_blocks), TRASH_BLOCK, np.int32)
            self._slot_blocks: List[List[int]] = [[] for _ in range(s)]
            d_head = model_cfg.d_model // model_cfg.n_heads
            self._use_kernel = bool(
                config.paged_kernel
                and paged_attention_supported(d_head, config.block_size))
        else:
            self._cache = init_kv_cache(model_cfg, s, config.max_len)
            self._blocks = None
        self._chunked = self._paged and config.chunked_prefill
        # Host-tier prefetch plumbing: entries staged by admission
        # attempts, APPLIED at the top of each loop iteration — the
        # decode step itself never waits on a host→device copy.
        self._host_cap = config.host_blocks if self._paged else 0
        self._prefetch_q: deque = deque()
        self._prefetch_inflight: set = set()
        self._last_prefill_bucket: Optional[int] = None
        # Speculative decoding plane (spec.py): draft k tokens host-side,
        # verify k+1 positions in one compiled program, accept per slot.
        # An optimization, never a liveness dependency — a step with no
        # drafts anywhere is exactly the plain decode program.
        self._spec = spec
        if spec is not None:
            if spec.k + 1 > config.max_len:
                raise ValueError(
                    f"spec k={spec.k} needs k+1 <= max_len="
                    f"{config.max_len}")
            if self._paged and self._use_kernel:
                # The Pallas decode kernel is allclose- (not bitwise-)
                # pinned against the gather path; mixing it with the
                # gather-based verify would break the greedy
                # spec-on == spec-off digest contract mid-stream.
                raise ValueError(
                    "speculative decoding requires the gather decode "
                    "path; set paged_kernel=False")
            self._drafter = spec.make_drafter()
        self._buckets = prefill_buckets(config.max_len)
        # Chunked buckets are the SAME power-of-two grid restricted to
        # multiples of the chunk holding >= 2 chunks (the scan-unroll
        # floor), so the compile-cache count stays bounded by the grid.
        c = config.chunk_tokens
        self._chunked_buckets = tuple(
            b for b in self._buckets if b % c == 0 and b >= 2 * c)
        # Requests popped from the admission queue but not yet in a slot
        # (the paged layout can be slot-free but block-starved; FIFO is
        # preserved — a head request short on blocks holds the line).
        self._held: deque = deque()
        self._peak_active = 0
        self._slots: List[Optional[_GenRequest]] = [None] * s
        self._positions = np.full((s,), -1, np.int32)
        self._last = np.zeros((s,), np.int32)
        self._compiled: Dict[Any, Any] = {}
        self._compile_lock = threading.Lock()
        # Mirrored under a micro-lock so stats() never waits on a compile
        # (same reasoning as Engine._compiled_ids).
        self._compiled_ids: set = set()
        self._stats_lock = threading.Lock()
        self._closed = False
        self._warmed = False
        self._abort = False
        # Serving-plane identity + liveness surface. ``serve_name`` is
        # stamped by the fleet router at attach (fault clauses and
        # flight-recorder events name replicas by it); the loop beat +
        # admitted-stream counter feed loop_alive() and the fault hook.
        self.serve_name = "engine"
        self._beat = time.monotonic()
        self._stall_mark: Optional[Tuple[float, float]] = None
        self._streams_started = 0
        self._loop_error_dumped = False
        self._stream_seq = itertools.count()
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd-generate-loop",
                                        daemon=True)
        self._thread.start()

    def loop_alive(self, stall_s: float = 60.0) -> bool:
        """The in-process liveness probe a :class:`~.router.
        ReplicaHandle` polls for thread replicas: False once this
        engine's loop thread died without a shutdown (abrupt death —
        the ``replica_kill`` drill shape), or once the loop has been
        OBSERVED with work pending (live slots, held or queued
        requests) and no completed iteration for ``stall_s`` seconds
        (a wedged loop — the ``replica_hang`` drill shape). The stall
        clock starts at the first busy observation with no progress
        since, NOT at the raw loop-beat age: an IDLE loop parks in the
        untimed queue wait by design, so its beat is legitimately
        stale — a request landing in that queue must not read as a
        wedge before the loop has had ``stall_s`` to wake. ``stall_s``
        must still cover the engine's worst single iteration — a lazy
        first-bucket compile can legitimately hold the loop for tens
        of seconds on CPU."""
        if self._closed:
            return True     # a drained/shut-down loop exit is not death
        if not self._thread.is_alive():
            return False
        if not stall_s:
            return True
        busy = (any(r is not None for r in self._slots)
                or self._held or len(self._queue))
        now = time.monotonic()
        if not busy:
            self._stall_mark = None
            return True
        mark = self._stall_mark
        if mark is not None and self._beat != mark[1]:
            mark = None     # the loop iterated since the last mark
        if mark is None:
            self._stall_mark = (now, self._beat)
            return True
        return now - mark[0] <= stall_s

    # -- compile cache -----------------------------------------------------

    def _sds(self, tree):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                           np.asarray(x).dtype
                                           if not hasattr(x, "dtype")
                                           else x.dtype), tree)

    def _compile(self, key):
        """AOT-compile the ``key`` executable (idempotent): ``"decode"``
        or ``("prefill", bucket)``."""
        exe = self._compiled.get(key)
        if exe is not None:
            return exe
        with self._compile_lock:
            exe = self._compiled.get(key)
            if exe is None:
                cfg = self._model_cfg
                s = self._cfg.max_slots
                paged = self._paged
                has_ad = self._adapters is not None
                lcfg = self._adapters.lora if has_ad else None
                p_sds = self._sds(self._params)
                c_sds = self._sds(self._cache)
                a_sds = (self._sds(self._adapters.table())
                         if has_ad else None)
                i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
                nb = self._cfg.blocks_per_slot
                # One signature rule for every variant (adapter table
                # right after params, adapter_idx right after the last
                # scalar/positions, paged row/tables last) — the arg
                # builders below (_decode_args/_prefill_args/warmup)
                # follow the same rule, so adapter-enabled engines keep
                # the compile-cache KEYS (and count) of base-only ones.
                if key == "decode":
                    kern = self._use_kernel if paged else False

                    def _decode(*a):
                        it = iter(a)
                        p = next(it)
                        at = next(it) if has_ad else None
                        toks, c, pos = next(it), next(it), next(it)
                        aidx = next(it) if has_ad else None
                        if paged:
                            return paged_decode_step(
                                p, toks, c, pos, next(it), cfg,
                                kernel=kern, adapters=at,
                                adapter_idx=aidx, lora=lcfg)
                        return decode_step(p, toks, c, pos, cfg,
                                           adapters=at, adapter_idx=aidx,
                                           lora=lcfg)
                    sds = ([p_sds] + ([a_sds] if has_ad else [])
                           + [i32(s), c_sds, i32(s)]
                           + ([i32(s)] if has_ad else [])
                           + ([i32(s, nb)] if paged else []))
                    exe = jax.jit(_decode).lower(*sds).compile()
                elif isinstance(key, tuple) and key[0] == "verify":
                    w = key[1]    # k + 1 positions per slot

                    def _verify(*a):
                        it = iter(a)
                        p = next(it)
                        at = next(it) if has_ad else None
                        toks, c, pos = next(it), next(it), next(it)
                        aidx = next(it) if has_ad else None
                        if paged:
                            return paged_verify_step(
                                p, toks, c, pos, next(it), cfg,
                                adapters=at, adapter_idx=aidx, lora=lcfg)
                        return verify_step(p, toks, c, pos, cfg,
                                           adapters=at, adapter_idx=aidx,
                                           lora=lcfg)
                    # Same signature rule as "decode" — only the token
                    # operand widens to [S, W]. Exactly ONE verify
                    # executable per engine (one k), the compile-cache
                    # pin tests/test_spec.py holds.
                    sds = ([p_sds] + ([a_sds] if has_ad else [])
                           + [i32(s, w), c_sds, i32(s)]
                           + ([i32(s)] if has_ad else [])
                           + ([i32(s, nb)] if paged else []))
                    exe = jax.jit(_verify).lower(*sds).compile()
                elif (isinstance(key, tuple)
                        and key[0] == "chunked_prefill"):
                    t = key[1]    # bucket width (multiple of the chunk)
                    cb = self._cfg.chunk_blocks
                    ct = self._cfg.chunk_tokens

                    def _chunked(*a):
                        it = iter(a)
                        p = next(it)
                        at = next(it) if has_ad else None
                        toks, c, slot, length, start = (
                            next(it), next(it), next(it), next(it),
                            next(it))
                        aidx = next(it) if has_ad else None
                        wrows, rrow = next(it), next(it)
                        c2, logits = paged_chunked_prefill(
                            p, toks, c, slot, wrows, rrow, start, cfg,
                            length=length, chunk_blocks=cb, adapters=at,
                            adapter_idx=aidx, lora=lcfg)
                        # Only the sampled row crosses back: the row
                        # scoring the LAST prompt position, which sits
                        # at suffix offset length - start - 1.
                        return c2, logits[length - start - 1]
                    # Same signature rule; the prefill scalars widen to
                    # (slot, length, start) and the paged tail carries
                    # the per-chunk write rows next to the read row.
                    sds = ([p_sds] + ([a_sds] if has_ad else [])
                           + [i32(t), c_sds, i32(), i32(), i32()]
                           + ([i32()] if has_ad else [])
                           + [i32(t // ct, cb), i32(nb)])
                    exe = jax.jit(_chunked).lower(*sds).compile()
                else:
                    t = key[1]

                    def _prefill(*a):
                        it = iter(a)
                        p = next(it)
                        at = next(it) if has_ad else None
                        toks, c, slot, length = (next(it), next(it),
                                                 next(it), next(it))
                        aidx = next(it) if has_ad else None
                        if paged:
                            c2, logits = paged_prefill(
                                p, toks, c, slot, next(it), cfg,
                                length=length, adapters=at,
                                adapter_idx=aidx, lora=lcfg)
                        else:
                            c2, logits = prefill(
                                p, toks, c, slot, cfg, length=length,
                                adapters=at, adapter_idx=aidx, lora=lcfg)
                        # Only the sampled row crosses back to the host —
                        # [vocab], not [T, vocab].
                        return c2, logits[length - 1]
                    sds = ([p_sds] + ([a_sds] if has_ad else [])
                           + [i32(t), c_sds, i32(), i32()]
                           + ([i32()] if has_ad else [])
                           + ([i32(nb)] if paged else []))
                    exe = jax.jit(_prefill).lower(*sds).compile()
                self._compiled[key] = exe
                with self._stats_lock:
                    self._compiled_ids.add(
                        key if key == "decode" else f"{key[0]}_{key[1]}")
        return exe

    def warmup(self) -> Tuple[Any, ...]:
        """Pre-compile AND pre-execute the decode step and every prefill
        bucket before traffic (the cache is functional state — warmup
        outputs are discarded, so it stays pristine). Returns the keys
        warmed."""
        s = self._cfg.max_slots
        nb = self._cfg.blocks_per_slot
        has_ad = self._adapters is not None
        # All-trash tables/rows and all-base (-1) adapter indices:
        # warmup scratch lands in the reserved block, pool and adapter
        # table stay pristine.
        args = [self._params]
        if has_ad:
            args.append(self._adapters.table())
        args += [np.zeros((s,), np.int32), self._cache,
                 np.full((s,), -1, np.int32)]
        if has_ad:
            args.append(np.full((s,), -1, np.int32))
        if self._paged:
            args.append(np.full((s, nb), TRASH_BLOCK, np.int32))
        out = self._compile("decode")(*args)
        jax.block_until_ready(out)
        spec_keys: Tuple[Any, ...] = ()
        if self._spec is not None:
            w = self._spec.k + 1
            args = [self._params]
            if has_ad:
                args.append(self._adapters.table())
            args += [np.zeros((s, w), np.int32), self._cache,
                     np.full((s,), -1, np.int32)]
            if has_ad:
                args.append(np.full((s,), -1, np.int32))
            if self._paged:
                args.append(np.full((s, nb), TRASH_BLOCK, np.int32))
            out = self._compile(("verify", w))(*args)
            jax.block_until_ready(out)
            spec_keys = (("verify", w),)
        if self._chunked:
            # A chunked engine never compiles the plain prefill — every
            # admission (cold or hit) runs the chunked program, so only
            # the chunked bucket grid is warmed.
            ct = self._cfg.chunk_tokens
            for t in self._chunked_buckets:
                args = [self._params]
                if has_ad:
                    args.append(self._adapters.table())
                args += [np.zeros((t,), np.int32), self._cache,
                         np.asarray(0, np.int32), np.asarray(1, np.int32),
                         np.asarray(0, np.int32)]
                if has_ad:
                    args.append(np.asarray(-1, np.int32))
                args += [np.full((t // ct, self._cfg.chunk_blocks),
                                 TRASH_BLOCK, np.int32),
                         np.full((nb,), TRASH_BLOCK, np.int32)]
                out = self._compile(("chunked_prefill", t))(*args)
                jax.block_until_ready(out)
            self._warmed = True
            return ("decode",) + spec_keys + tuple(self._chunked_buckets)
        for t in self._buckets:
            args = [self._params]
            if has_ad:
                args.append(self._adapters.table())
            args += [np.zeros((t,), np.int32), self._cache,
                     np.asarray(0, np.int32), np.asarray(1, np.int32)]
            if has_ad:
                args.append(np.asarray(-1, np.int32))
            if self._paged:
                args.append(np.full((nb,), TRASH_BLOCK, np.int32))
            out = self._compile(("prefill", t))(*args)
            jax.block_until_ready(out)
        self._warmed = True
        return ("decode",) + spec_keys + tuple(self._buckets)

    # -- client API --------------------------------------------------------

    def submit(self, tokens: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               sampling: Optional[SamplingParams] = None,
               eos_id: Any = _DEFAULT,
               deadline_ms: Optional[float] = None,
               adapter: Optional[str] = None) -> GenerationHandle:
        """Enqueue one prompt; returns a :class:`GenerationHandle`
        streaming the sampled tokens. Raises
        :class:`ServerOverloadedError` when the admission queue is full
        (or the tenant is over quota — reason ``tenant_quota``),
        :class:`ServerClosedError` after shutdown, ``ValueError`` on a
        malformed or cache-overflowing prompt, on an ``adapter`` that is
        not resident, or on an ``adapter`` without a registry (all
        eagerly, in the caller's thread).

        ``max_new_tokens`` is clamped to the cache room left after the
        prompt (the stream then finishes with reason ``"length"``);
        ``eos_id=None`` disables EOS for this request even when the
        engine has a default. ``adapter`` names the tenant's resident
        LoRA fine-tune (None = base model); the stream pins the
        adapter's table row for its whole lifetime, so an evict racing
        a live stream is refused by the registry.
        """
        toks = np.asarray(tokens, np.int32)
        if toks.ndim != 1 or toks.size == 0:
            raise ValueError(
                f"prompt must be a non-empty 1-D int sequence, got shape "
                f"{toks.shape}")
        if toks.size > self._cfg.max_len:
            raise ValueError(
                f"prompt of {toks.size} tokens exceeds max_len="
                f"{self._cfg.max_len} (prompt + generated tokens share "
                f"the KV cache)")
        max_new = (self._cfg.default_max_new_tokens
                   if max_new_tokens is None else int(max_new_tokens))
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        # Token t+1's K/V lands at position L+t; the last sampled token
        # needs no cache write, so room caps new tokens at max_len-L+1.
        max_new = min(max_new, self._cfg.max_len - toks.size + 1)
        need_blocks = 0
        if self._paged:
            need_blocks = need = self._blocks_needed(toks.size, max_new)
            if need > self._blocks.usable:
                raise ValueError(
                    f"request needs {need} KV blocks (prompt "
                    f"{toks.size} + up to {max_new} generated, "
                    f"block_size={self._cfg.block_size}) but the pool "
                    f"holds only {self._blocks.usable} usable blocks — "
                    f"raise n_blocks or lower max_new_tokens")
        sampling = SamplingParams() if sampling is None else sampling
        eos = self._cfg.eos_id if eos_id is _DEFAULT else eos_id
        if deadline_ms is None:
            deadline_ms = self._cfg.default_deadline_ms
        tenant = "base" if adapter is None else adapter
        a_slot = -1
        salt = b"\x00"      # base frame — see _GenRequest.prefix_salt
        if adapter is not None:
            if self._adapters is None:
                raise ValueError(
                    f"submit(adapter={adapter!r}) on an engine without an "
                    f"AdapterRegistry — pass adapters= to "
                    f"GenerationEngine")
            # Retain BEFORE admission: the row must survive the queue
            # wait too (an evict of a queued tenant would otherwise free
            # the row its prefill is about to gather from).
            a_slot = self._adapters.retain(adapter)   # ValueError if absent
            # Generation read AFTER retain: the refcount blocks reloads,
            # so the salt is stable for the stream's whole lifetime.
            salt = (f"{adapter}\x00"
                    f"{self._adapters.generation(adapter)}\x00".encode())
        try:
            # Raises over-quota (tenant_quota) or over-block-budget
            # (blocks_exhausted) — both with a retry_after_ms hint.
            self._tenant_admit(tenant, need_blocks=need_blocks)
            now = time.monotonic()
            handle = GenerationHandle()
            req = _GenRequest(
                tokens=toks, max_new=max_new, sampling=sampling, eos=eos,
                handle=handle, enqueued_at=now,
                deadline_at=(None if deadline_ms is None
                             else now + deadline_ms / 1e3),
                rng=np.random.default_rng(sampling.seed),
                tenant=tenant, adapter=adapter, adapter_slot=a_slot,
                prefix_salt=salt, stream_id=next(self._stream_seq),
                priority=self._priority_of(tenant))
            handle.request = req
            try:
                depth = self._queue.put(req)   # raises Closed / Overloaded
            except ServerOverloadedError:
                self._tenant_release(tenant, blocks=need_blocks)
                reason, detail = self._overload_reason(toks.size, max_new)
                self._metrics.on_overload(reason)
                err = ServerOverloadedError(
                    f"request queue full ({self._cfg.max_queue}); "
                    f"{reason}: {detail}")
                # Backoff hint for the 503: how long until this queue
                # has drained at the engine's measured service rate.
                err.retry_after_ms = self._metrics.retry_after_ms(
                    len(self._queue))
                raise err from None
            except ServerClosedError:
                self._tenant_release(tenant, blocks=need_blocks)
                raise
        except BaseException:
            if adapter is not None:
                self._adapters.release(adapter)
            raise
        self._metrics.on_submit(depth)
        flightrec.record("serve_admit", replica=self.serve_name,
                         stream=req.stream_id, tenant=tenant,
                         prompt_len=int(toks.size))
        return handle

    def _tenant_admit(self, tenant: str, need_blocks: int = 0) -> None:
        """Count ``tenant``'s in-flight streams (queued + decoding) and
        reject over quota — atomically, so two racing submits cannot
        both squeeze under the cap. The rejection is its own reason
        (``tenant_quota``) next to ``slots_full``/``blocks_exhausted``:
        raising max_slots when one tenant is quota-bound fixes nothing.

        With a per-tenant block budget, ``need_blocks`` is additionally
        reserved against it HERE (released at :meth:`_req_done`): a
        tenant whose in-flight demand would exceed its budget is
        rejected at the door with reason ``blocks_exhausted`` — only
        THAT tenant's admissions, never another's, and with the same
        ``retry_after_ms`` backoff hint fleet 503s carry."""
        quota = (self._adapters.quota(tenant)
                 if self._adapters is not None else None)
        budget = self._blocks.budget(tenant) if self._paged else None
        if budget is not None and need_blocks > budget:
            raise ValueError(
                f"request needs {need_blocks} KV blocks but tenant "
                f"{tenant!r} has a block budget of {budget} — it can "
                f"NEVER be admitted; raise the tenant's budget or lower "
                f"max_new_tokens")
        with self._tenant_lock:
            inflight = self._tenant_inflight.get(tenant, 0)
            if quota is not None and inflight >= quota:
                self._metrics.on_overload("tenant_quota")
                err = ServerOverloadedError(
                    f"tenant {tenant!r} over quota: {inflight} streams "
                    f"in flight >= quota {quota} — finish streams or "
                    f"raise the tenant's quota")
                err.retry_after_ms = self._metrics.retry_after_ms(inflight)
                raise err
            if budget is not None:
                demand = self._tenant_blocks.get(tenant, 0)
                if demand + need_blocks > budget:
                    self._metrics.on_overload("blocks_exhausted")
                    err = ServerOverloadedError(
                        f"tenant {tenant!r} over KV block budget: "
                        f"{demand} blocks reserved in flight + "
                        f"{need_blocks} needed > budget {budget} — "
                        f"blocks_exhausted for THIS tenant only; finish "
                        f"streams or raise tenant_block_budgets")
                    err.retry_after_ms = self._metrics.retry_after_ms(
                        len(self._queue))
                    raise err
                self._tenant_blocks[tenant] = demand + need_blocks
            self._tenant_inflight[tenant] = inflight + 1

    def _tenant_label(self, req: _GenRequest) -> Optional[str]:
        """The tenant stamped into metrics: only multi-tenant engines
        (an AdapterRegistry attached) split by tenant — a base-only
        engine must not grow ``hvd_tenant_*{tenant="base"}`` series or
        a ``tenants`` /stats block it has no multi-tenant plane for."""
        return req.tenant if self._adapters is not None else None

    def _tenant_release(self, tenant: str, blocks: int = 0) -> None:
        with self._tenant_lock:
            n = self._tenant_inflight.get(tenant, 1) - 1
            if n > 0:
                self._tenant_inflight[tenant] = n
            else:
                self._tenant_inflight.pop(tenant, None)
            if blocks:
                d = self._tenant_blocks.get(tenant, 0) - blocks
                if d > 0:
                    self._tenant_blocks[tenant] = d
                else:
                    self._tenant_blocks.pop(tenant, None)

    def _req_done(self, req: _GenRequest) -> None:
        """One request left the system (finished, failed, expired or
        cancelled) — the single choke point for the tenant accounting:
        drop its in-flight count, its block-budget demand and its
        adapter-row reference.
        Idempotent (a drain timeout can walk the same request twice)."""
        if req._done_accounted:
            return
        req._done_accounted = True
        self._tenant_release(req.tenant, blocks=self._demand_of(req))
        if req.adapter is not None and self._adapters is not None:
            self._adapters.release(req.adapter)

    def _demand_of(self, req: _GenRequest) -> int:
        """The block demand :meth:`_tenant_admit` reserved for ``req``
        (0 when its tenant has no budget) — recomputed, not stored:
        deterministic in (prompt length, clamped max_new)."""
        if not self._paged or self._blocks.budget(req.tenant) is None:
            return 0
        return self._blocks_needed(req.tokens.size, req.max_new)

    # -- scheduling policy resolution ---------------------------------------
    # Registry row first (hot-settable per tenant), engine config map
    # second, neutral default last. Consulted at every pick/admission,
    # so policy changes apply at the next decode-step boundary.

    def _weight_of(self, tenant: str) -> float:
        if self._adapters is not None:
            w = self._adapters.weight(tenant)
            if w is not None:
                return w
        w = (self._cfg.tenant_weights or {}).get(tenant)
        return 1.0 if w is None else float(w)

    def _priority_of(self, tenant: str) -> int:
        if self._adapters is not None:
            p = self._adapters.priority(tenant)
            if p is not None:
                return p
        return int((self._cfg.tenant_priorities or {}).get(tenant, 0))

    def _slo_of(self, tenant: str) -> Optional[float]:
        if self._adapters is not None:
            s = self._adapters.slo_ttft_ms(tenant)
            if s is not None:
                return s
        return (self._cfg.tenant_slo_ttft_ms or {}).get(tenant)

    def slo_burn(self, tenant: str) -> float:
        """``tenant``'s SLO burn rate on this engine (0.0 when unknown)
        — the fleet router's deprioritize-burning-replicas signal."""
        return self._metrics.slo_burn(tenant)

    def _blocks_needed(self, prompt_len: int, max_new: int) -> int:
        """KV blocks a request reserves at admission: every position it
        can write (the last sampled token needs no write)."""
        total = min(prompt_len + max_new - 1, self._cfg.max_len)
        return blocks_for(total, self._cfg.block_size)

    def _overload_reason(self, prompt_len: int,
                         max_new: int) -> Tuple[str, str]:
        """Name the scarce resource behind a full admission queue:
        ``blocks_exhausted`` when slots are free but the paged pool
        cannot cover this request, else ``slots_full``. Racy reads —
        this labels an error message and a counter, it gates nothing."""
        s = self._cfg.max_slots
        if self._paged:
            free_slots = sum(r is None for r in self._slots)
            need = self._blocks_needed(prompt_len, max_new)
            free_blocks = self._blocks.free_count
            if free_slots > 0 and free_blocks < need:
                return ("blocks_exhausted",
                        f"{free_blocks}/{self._blocks.usable} KV blocks "
                        f"free, next request needs {need} — raise "
                        f"n_blocks or lower max_new_tokens")
            return ("slots_full",
                    f"all {s} decode slots busy and the queue is full — "
                    f"raise max_slots/max_queue or shed load")
        return ("slots_full",
                f"all {s} decode slots busy and the queue is full — "
                f"raise max_slots/max_queue or shed load")

    def generate(self, tokens: Sequence[int],
                 timeout: Optional[float] = None, **kw) -> Dict:
        """Synchronous :meth:`submit` (+ ``handle.result(timeout)``)."""
        return self.submit(tokens, **kw).result(timeout)

    def _active_rows(self) -> int:
        """Live decode slots plus block-starved held requests — with the
        queue depth (:meth:`~.engine.ReadinessMixin.load`), the
        fleet router's least-depth dispatch signal. Lock-free reads:
        approximate by design (it orders replicas, it gates nothing)."""
        return (sum(r is not None for r in self._slots)
                + len(self._held))

    def stats(self) -> Dict:
        """The ``/stats`` snapshot (augments :class:`ServeMetrics` with
        the slot/compile view; ``batch_fill_ratio`` here is decode-slot
        occupancy — live streams ÷ slots executed)."""
        snap = self._metrics.snapshot()
        snap["max_slots"] = self._cfg.max_slots
        snap["max_len"] = self._cfg.max_len
        snap["active_slots"] = sum(r is not None for r in self._slots)
        snap["peak_active_slots"] = self._peak_active
        snap["prefill_buckets"] = list(self._buckets)
        snap["kv_layout"] = self._cfg.kv_layout
        if self._paged:
            snap["block_size"] = self._cfg.block_size
            snap["blocks"] = self._blocks.gauges()
            hits = snap["generation"]["prefix_hits_total"]
            misses = snap["generation"]["prefix_misses_total"]
            snap["prefix_hit_rate"] = (hits / (hits + misses)
                                       if hits + misses else None)
            snap["chunked_prefill"] = self._cfg.chunked_prefill
            snap["prefix_digests"] = (
                list(self._blocks.route_digests())
                if self._cfg.prefix_reuse else [])
            # Per-tenant owned/budget block gauges — its OWN top-level
            # key (NOT inside "blocks": the fleet router sums those
            # gauges numerically across replicas).
            snap["blocks_by_tenant"] = self._blocks.tenant_gauges()
        snap["last_prefill_bucket"] = self._last_prefill_bucket
        if self._adapters is not None:
            snap["adapters_resident"] = len(self._adapters.resident())
            snap["adapter_table"] = self._adapters.gauges()
        snap["spec_k"] = self._spec.k if self._spec is not None else 0
        with self._stats_lock:
            snap["compiled"] = sorted(map(str, self._compiled_ids))
        snap["max_queue"] = self._cfg.max_queue
        return snap

    # -- multi-tenant adapter surface (fleet routing + lifecycle) ----------

    @property
    def adapters(self) -> Optional[AdapterRegistry]:
        """This engine's registry (None = base-only engine)."""
        return self._adapters

    def adapter_names(self) -> Optional[Tuple[str, ...]]:
        """Resident adapter names, or None when the engine carries no
        registry — the residency signal the fleet router's
        adapter-affine dispatch sorts on."""
        if self._adapters is None:
            return None
        return self._adapters.resident()

    def adapters_resident(self) -> Optional[int]:
        """Resident-adapter count for ``/healthz`` (None = no registry)."""
        names = self.adapter_names()
        return None if names is None else len(names)

    def prefix_digests(self) -> Tuple[str, ...]:
        """Advisory routing digests of the prefix chains this engine
        holds (either tier) — the residency signal the fleet router's
        prefix-affine dispatch sorts on. Empty for engines without a
        prefix registry."""
        if not (self._paged and self._cfg.prefix_reuse):
            return ()
        return self._blocks.route_digests()

    @property
    def route_block_size(self) -> int:
        """Block size a dispatcher must use to compute a request's
        routing digest so it matches this engine's advertised ones."""
        return self._cfg.block_size

    def load_adapter(self, name: str, adapter: Any,
                     quota: Optional[int] = None) -> int:
        """Hot-load ``adapter`` under ``name`` (the router's lazy-load
        path on an affinity miss). Raises ``ValueError`` without a
        registry or on a full table; never recompiles anything."""
        if self._adapters is None:
            raise ValueError(
                "engine has no AdapterRegistry — pass adapters= to "
                "GenerationEngine to serve adapters")
        return self._adapters.load(name, adapter, quota=quota)

    def prom_collect(self):
        """This engine's ``(meta, samples)`` in Prometheus terms —
        everything :meth:`stats` knows (TTFT, tokens/sec/user,
        block-pool gauges, prefix hit rate, rejection splits) plus the
        histograms, labeled ``engine="generate"`` (see
        :func:`~horovod_tpu.serve.metrics.collect_stats`)."""
        from .metrics import collect_stats
        return collect_stats(self.stats(), self._metrics.registry,
                             engine="generate")

    def prom_metrics(self) -> str:
        """Prometheus text exposition of :meth:`prom_collect` (the
        ``/metrics`` body when this engine serves alone)."""
        from ..obs.registry import render
        return render(*self.prom_collect())

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the engine. ``drain=True`` finishes every stream already
        admitted (queued AND mid-generation) first; ``drain=False`` fails
        pending handles with :class:`ServerClosedError` and aborts
        in-flight streams. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._adapters is not None:
            # Unhook the metric-fold listener: a registry SHARED across
            # replicas must not keep retired engines' metrics alive.
            self._adapters.remove_evict_listener(
                self._metrics.forget_tenant)
        if drain:
            self._queue.close()
        else:
            self._abort = True
            self._fail_pending()
        self._thread.join(timeout)
        # Unconditional second sweep: a DEAD loop (kill drill, loop
        # crash) joins instantly with its queue unserved, and a racing
        # submit can slip past the _closed check into an already-swept
        # queue — whatever is still pending here will never be served.
        self._fail_pending()

    def _fail_pending(self) -> None:
        cancelled = 0
        for req in self._queue.drain_pending():
            if not req.handle.done():
                req.handle._fail(ServerClosedError(
                    "server shut down before execution"))
                cancelled += 1
            self._req_done(req)
        if cancelled:
            self._metrics.on_shutdown_cancel(cancelled)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- the continuous-batching loop --------------------------------------

    def _crash_dump(self, reason: str) -> None:
        """Flight-recorder post-mortem for THIS replica: one event
        naming every in-flight stream id, then the ring dump — what an
        operator reads after a replica death to know which streams the
        failover plane had to resume."""
        inflight = [r.stream_id for r in self._slots if r is not None]
        inflight += [r.stream_id for r in self._held]
        flightrec.record("serve_crash", replica=self.serve_name,
                         inflight=inflight, queued=len(self._queue))
        flightrec.dump(reason=f"serving replica {self.serve_name}: "
                              f"{reason}")

    def _loop(self):
        while True:
            try:
                self._beat = time.monotonic()
                act = faults.serve_hook(self.serve_name,
                                        self._streams_started)
                if act == "kill":
                    # Abrupt loop death: the thread exits WITHOUT
                    # failing its handles — a crashed process cannot
                    # deliver failures. The stranded streams are the
                    # fleet failover drill's whole point; the dump is
                    # the post-mortem a real dead replica would leave.
                    self._crash_dump("fault injection: replica_kill")
                    return
                if act == "proc_kill":
                    # Real process death: dump the post-mortem first
                    # (SIGKILL gives no atexit), then SIGKILL ourselves
                    # — the parent-side client sees a dead pid and
                    # broken streams, exactly what a crashed subprocess
                    # replica leaves behind.
                    self._crash_dump("fault injection: replica_proc_kill")
                    os.kill(os.getpid(), signal.SIGKILL)
                if act == "hang":
                    # Park forever with the thread ALIVE: only the
                    # stale-beat half of loop_alive() can catch this.
                    while True:
                        time.sleep(3600)
                if self._abort:
                    err = ServerClosedError(
                        "server shut down before completion")
                    for req in self._held:
                        req.handle._fail(err)
                        self._req_done(req)
                    self._held.clear()
                    self._fail_active(err)
                    return
                if self._prefetch_q:
                    self._apply_prefetches()
                free = [i for i, r in enumerate(self._slots) if r is None]
                n_active = self._cfg.max_slots - len(free)
                idle = n_active == 0 and not self._held
                # Pull EVERYTHING queued into the held line, not just
                # enough to fill the free slots: the scheduler is only
                # fair across tenants it can SEE — a quiet tenant parked
                # behind a chatty burst in the FIFO queue would
                # otherwise be invisible to it. Held requests keep
                # their max_queue admission ticket (``hold=True``), so
                # the door's backpressure bound is unchanged.
                want = len(self._queue) or (len(free) if idle else 0)
                if want > 0:
                    # Blocks ONLY when fully idle (no active streams,
                    # nothing held, an empty queue); with streams in
                    # flight it drains whatever is queued without waiting.
                    batch = self._queue.take_batch(want, 0.0, hold=True)
                    if not batch and idle:
                        return      # closed and drained, nothing in flight
                    for r in batch:
                        r.held_ticket = True
                    self._held.extend(batch)
                self._expire_held()
                # Admission order is the FairScheduler's pick — WDRR
                # over tenants, strict priorities above it, FIFO within
                # a tenant (one tenant degenerates to exact FIFO).
                blocked: set = set()
                budget_blocked: set = set()
                while self._held and free:
                    i = self._sched.pick(self._held,
                                         blocked=frozenset(blocked))
                    if i is None:
                        break   # every pending tenant is block-starved
                    req = self._held[i]
                    # The ticket covers the request only until its
                    # first admission ATTEMPT — from here it is "being
                    # served" (possibly block-starved), not "queued",
                    # and must not count against the door (an in-
                    # admission prefill can hold the loop for seconds).
                    if req.held_ticket:
                        req.held_ticket = False
                        self._queue.release_held()
                    outcome = self._admit(req, free[0])
                    if outcome in ("starved", "starved_budget"):
                        # This TENANT can't get KV blocks yet — decode
                        # steps below will free some. Only ITS line
                        # holds; other tenants keep admitting (the
                        # per-tenant half of blocks_exhausted).
                        blocked.add(req.tenant)
                        if outcome == "starved_budget":
                            budget_blocked.add(req.tenant)
                        continue
                    del self._held[i]
                    if outcome == "ok":
                        free.pop(0)
                preempted = False
                if (self._cfg.preempt and self._held
                        and (not free or blocked)
                        and any(r is not None for r in self._slots)):
                    preempted = self._maybe_preempt(budget_blocked)
                if any(r is not None for r in self._slots):
                    self._step_once()
                elif self._held and (self._prefetch_q or preempted):
                    # Held requests with nothing decoding but progress
                    # already in motion: a staged host-tier prefetch
                    # lands at the next iteration's top, or an eviction
                    # just freed the slot(s) the next admission pass
                    # fills. Not a stall.
                    pass
                elif self._held:
                    # Starved with nothing in flight: the submit-time
                    # pool-size and budget checks make this unreachable
                    # (every block is free or reclaimable — a tenant's
                    # own residue included — and need <= usable and
                    # <= budget). Fail loudly rather than spin.
                    req = self._held.popleft()
                    if req.held_ticket:
                        req.held_ticket = False
                        self._queue.release_held()
                    req.handle._fail(ServerOverloadedError(
                        "KV block pool cannot cover an admitted request "
                        "with the engine idle — admission accounting bug"))
                    self._req_done(req)
            except Exception as e:  # noqa: BLE001 — deliver, don't die
                # Every active stream is about to fail: leave the
                # post-mortem FIRST (the handles' owners may be remote
                # clients who only ever see a broken stream). Dumped
                # once per engine: the loop keeps serving after an
                # error, and a deterministic per-batch fault must not
                # pay an fsync'd dump on every occurrence inside the
                # hot loop (the ring keeps recording; a later DEATH —
                # kill, abort — still dumps the fresher events).
                if not self._loop_error_dumped:
                    self._loop_error_dumped = True
                    self._crash_dump(f"engine loop error: {e!r}")
                self._fail_active(e)

    def _fail_active(self, exc: BaseException) -> None:
        for i, req in enumerate(self._slots):
            if req is not None:
                req.handle._fail(exc)
                self._req_done(req)
                self._release_slot(i)

    def _release_slot(self, i: int) -> None:
        """Vacate slot ``i``: paged layouts return its blocks to the pool
        (refcount-aware — a shared prefix block frees only when its last
        reader ends) and trash-out its table row."""
        self._slots[i] = None
        self._positions[i] = -1
        self._adapter_idx[i] = -1
        if self._paged:
            self._blocks.release(self._slot_blocks[i])
            self._slot_blocks[i] = []
            self._tables[i] = TRASH_BLOCK

    # -- fair scheduling + preemption ---------------------------------------

    def _expire_held(self) -> None:
        """Fail deadline-expired requests parked in the held line NOW,
        not when they next reach a slot: an expired request must not
        keep its reserved admission position (the max_queue ticket)
        nor pin host-tier prefetches nobody else asked for."""
        now = time.monotonic()
        if not any(r.expired(now) for r in self._held):
            return
        expired = [r for r in self._held if r.expired(now)]
        self._held = deque(r for r in self._held if not r.expired(now))
        for req in expired:
            self._metrics.on_deadline_expired(
                (now - req.enqueued_at) * 1e3,
                tenant=self._tenant_label(req))
            req.handle._fail(DeadlineExceededError(
                f"deadline expired after "
                f"{(now - req.enqueued_at) * 1e3:.1f} ms in queue"))
            self._req_done(req)
            if req.held_ticket:
                req.held_ticket = False
                self._queue.release_held()
            self._release_prefetches(req)

    def _release_prefetches(self, req: _GenRequest) -> None:
        """Drop staged host-tier prefetches only ``req`` wanted (it
        left the held line unserved): each staged payload would burn a
        device block on landing, for a chain no surviving admission is
        waiting on. Keys another held request also staged stay."""
        if not req.prefetch_keys:
            return
        wanted: set = set()
        for other in self._held:
            wanted |= other.prefetch_keys
        drop = req.prefetch_keys - wanted
        req.prefetch_keys = set()
        if not drop:
            return
        self._prefetch_q = deque(
            e for e in self._prefetch_q if e[0] not in drop)
        self._prefetch_inflight -= drop

    def _maybe_preempt(self, budget_blocked: set) -> bool:
        """Preempt-by-evict: when a higher-priority pending request
        found no free slot (or no pool blocks), evict the LOWEST-
        priority active stream so the next iteration admits the high-
        priority one. Tenants starved on their OWN block budget don't
        count as waiting — evicting a neighbor frees pool blocks, never
        budget headroom. One victim per loop iteration: eviction paces
        with the decode steps, so a priority inversion cannot cascade
        into a mass eviction in one beat. Returns True when a stream
        was evicted — the loop counts that as progress (an eviction can
        empty every slot; the freed one is filled by the NEXT
        iteration's admission pass, not the idle-starvation guard)."""
        now = time.monotonic()
        waiting = [r for r in self._held
                   if r.tenant not in budget_blocked
                   and not r.expired(now)]
        if not waiting:
            return False
        top = max(self._priority_of(r.tenant) for r in waiting)
        # Victim: lowest priority class; ties evict the LATEST-admitted
        # stream (the least completed work lost to replay).
        prio, _, slot = min(
            (self._priority_of(r.tenant), -r.stream_id, i)
            for i, r in enumerate(self._slots) if r is not None)
        if top > prio:
            self._preempt(slot)
            return True
        return False

    def _preempt(self, slot: int) -> None:
        """Evict the stream in ``slot``, capturing its envelope exactly
        like a replica-death failover: everything already emitted is
        kept as an expect-prefix to regenerate suppressed-and-verified,
        the rng restarts from the seed, the ORIGINAL absolute deadline
        stays, and the request rejoins the held line (no new admission
        ticket — it was admitted once). Past ``preempt_retries``
        evictions the stream fails with terminal reason
        ``preempted_exhausted`` instead (under a fleet router that is
        additionally a failover cause — the envelope may still resume
        on another replica)."""
        req = self._slots[slot]
        req.retries += 1
        self._metrics.on_preempt("evicted",
                                 tenant=self._tenant_label(req))
        flightrec.record("serve_preempt", replica=self.serve_name,
                         stream=req.stream_id, tenant=req.tenant,
                         n_tokens=req.n_out, retries=req.retries)
        self._release_slot(slot)
        if req.retries > self._cfg.preempt_retries:
            self._metrics.on_preempt("exhausted",
                                     tenant=self._tenant_label(req))
            req.handle._fail(PreemptedError(
                f"stream {req.stream_id} (tenant {req.tenant!r}) "
                f"evicted {req.retries} times > preempt_retries="
                f"{self._cfg.preempt_retries}: preempted_exhausted — "
                f"re-submit, or raise the tenant's priority or the "
                f"retry budget"))
            self._req_done(req)
            return
        req.replay_expect = list(req.handle._tokens)
        req.replay_i = 0
        req.n_out = 0
        req.rng = np.random.default_rng(req.sampling.seed)
        req.t_admit = None
        self._held.append(req)

    def _req_emit(self, req: _GenRequest, tok: int) -> None:
        """Every sampled token flows through here. Normal streams emit
        straight to the handle (and count in the token counters); a
        stream resuming from preemption first regenerates its already-
        emitted prefix SUPPRESSED — each token verified against the
        captured envelope, none re-delivered, none re-counted — then
        emits new tokens. Divergence is impossible under the slot-row
        bit-identity contract, so it fails LOUDLY (an engine bug), like
        the admission accounting guard."""
        if req.replay_expect is not None:
            if req.replay_i < len(req.replay_expect):
                want = req.replay_expect[req.replay_i]
                if tok != want:
                    raise RuntimeError(
                        f"preemption replay diverged on stream "
                        f"{req.stream_id}: position {req.replay_i} "
                        f"regenerated {tok}, envelope expected {want} — "
                        f"the slot-row bit-identity contract is broken")
                req.replay_i += 1
                return
            req.replay_expect = None
            self._metrics.on_preempt("resumed",
                                     tenant=self._tenant_label(req))
        self._metrics.on_tokens(tenant=self._tenant_label(req))
        req.handle._emit(tok)

    def _paged_reserve(self, req: _GenRequest):
        """Reserve the blocks ``req`` needs: prefix-registry hits are
        retained (shared), the rest freshly allocated — or None when the
        pool can't cover it yet, or ``"wait"`` when the chain continues
        in the host tier under ``host_admission="wait"`` (the request
        holds the FIFO head while the kicked prefetch lands).
        Re-resolves hits after every reclaim sweep (an eviction can take
        chain entries the first lookup matched). Before hard-evicting
        registered prefixes, cold ones are OFFLOADED to the host tier
        (when configured) so a later admission can prefetch them back
        instead of recomputing.

        With a per-tenant block budget, returns ``"budget"`` when THIS
        tenant is over its cap and cannot get under it by offloading or
        reclaiming its OWN coldest blocks — a per-tenant starvation
        that must never hold another tenant's admission line."""
        n_total = self._blocks_needed(req.tokens.size, req.max_new)
        budget = self._blocks.budget(req.tenant)
        while True:
            hits = (self._blocks.lookup_prefix(req.tokens,
                                               salt=req.prefix_salt)
                    if self._cfg.prefix_reuse else [])
            hits = hits[:n_total]
            if self._host_cap:
                cont = self._blocks.host_lookup(
                    req.tokens, len(hits), salt=req.prefix_salt)
                if cont:
                    self._stage_prefetch(cont, req)
                    if self._cfg.host_admission == "wait":
                        return "wait"
                    # "miss": admit now on device-tier hits only — the
                    # suffix recomputes; the prefetch still lands for
                    # the NEXT admission. Never a stale read either way.
            if self._chunked:
                # A hit depth must be whole CHUNKS: the scan's cold and
                # hit programs share trip boundaries only at multiples
                # of the chunk, and at least one prompt token must
                # remain in the suffix to score the sampled row.
                cb = self._cfg.chunk_blocks
                cap = ((int(req.tokens.size) - 1)
                       // self._cfg.chunk_tokens) * cb
                n_hit = min(len(hits), cap)
                hits = hits[:n_hit - n_hit % cb]
            need = n_total - len(hits)
            if budget is not None:
                over = (self._blocks.owned_count(req.tenant) + need
                        - budget)
                if over > 0:
                    # Over ITS budget: this tenant frees its OWN coldest
                    # blocks first — host-tier offload, then registry
                    # reclaim — and starves ALONE if neither helps.
                    if self._host_cap and self._offload_for(
                            over, owner=req.tenant):
                        continue
                    if not self._blocks.reclaim(
                            self._blocks.free_count + over,
                            owner=req.tenant):
                        return "budget"
                    continue
            free = self._blocks.free_count
            if free >= need:
                self._blocks.retain(hits)
                fresh = self._blocks.alloc(need, owner=req.tenant)
                return hits, fresh, n_total
            if self._host_cap and self._offload_for(need - free):
                continue
            if not self._blocks.reclaim(need):
                return None

    # -- host tier (offload / prefetch) ------------------------------------

    def _offload_for(self, shortfall: int,
                     owner: Optional[str] = None) -> bool:
        """Move up to ``shortfall`` cold registered-prefix blocks to the
        host tier (device bytes snapshotted to host numpy staging, then
        committed — the manager re-validates under its lock, so a hit
        landing mid-copy cancels that block's offload). Returns whether
        any device block was freed. ``owner`` restricts the victims to
        that tenant's blocks (the over-budget self-offload path)."""
        # Per-block gathers with a SCALAR index: one compiled program
        # reused for every offload. A batched fancy-index gather would
        # recompile for each distinct victim-set size.
        moved = 0
        for key, blk in self._blocks.offload_candidates(shortfall,
                                                        owner=owner):
            payload = {"k": np.asarray(self._cache["k"][:, blk]),
                       "v": np.asarray(self._cache["v"][:, blk])}
            if self._blocks.offload_commit(key, payload):
                moved += 1
        if moved:
            self._metrics.on_kv_offload(moved)
        return moved > 0

    def _stage_prefetch(self, cont, req: _GenRequest) -> None:
        """Queue host→device copies for a chain continuation found in
        the host tier; applied at the next loop top, never inside a
        decode step. Idempotent per key while a copy is in flight.
        ``req`` records the keys it staged (released if it expires
        while parked) and owns the blocks the copies will land in."""
        now = time.monotonic()
        for key, payload in cont:
            req.prefetch_keys.add(key)
            if key in self._prefetch_inflight:
                continue
            self._prefetch_inflight.add(key)
            self._prefetch_q.append((key, payload, now, req.tenant))

    def _apply_prefetches(self) -> None:
        """Land staged prefetches: allocate a device block, write the
        staged bytes, promote the registry entry (idempotent against an
        admission that re-registered the chain cold meanwhile — see
        :meth:`BlockManager.promote`). Entries that cannot get a device
        block yet stay queued for the next iteration; the loop never
        blocks here. Writes use a SCALAR block index so the scatter
        compiles once and is reused for every prefetch."""
        for _ in range(len(self._prefetch_q)):
            key, payload, t0, owner = self._prefetch_q.popleft()
            if (self._blocks.free_count < 1
                    and not self._offload_for(1)
                    and not self._blocks.reclaim(1)):
                # Evict by OFFLOAD first: landing one chain by
                # destroying another turns the host tier's preservation
                # into mutual eviction under rotation.
                self._prefetch_q.append((key, payload, t0, owner))
                continue
            try:
                blk = self._blocks.alloc(1, owner=owner)[0]
            except RuntimeError:
                self._prefetch_q.append((key, payload, t0, owner))
                continue
            k = self._cache["k"].at[:, blk].set(
                jnp.asarray(payload["k"], self._cache["k"].dtype))
            v = self._cache["v"].at[:, blk].set(
                jnp.asarray(payload["v"], self._cache["v"].dtype))
            self._cache = {"k": k, "v": v,
                           "lengths": self._cache["lengths"]}
            self._blocks.promote(key, blk)
            self._prefetch_inflight.discard(key)
            self._metrics.on_kv_prefetch(time.monotonic() - t0)

    def _admit(self, req: _GenRequest, slot: int) -> str:
        """Prefill ``req`` into ``slot`` and emit its first token.
        Returns ``"ok"`` (slot occupied), ``"done"`` (expired, failed, or
        finished on its first token — slot stays free), ``"starved"``
        (paged only: not enough free KV blocks yet — the request stays
        held and the slot stays free), or ``"starved_budget"`` (the
        request's TENANT is over its own block budget — only its line
        blocks; the scheduler keeps admitting everyone else)."""
        now = time.monotonic()
        if req.expired(now):
            self._metrics.on_deadline_expired(
                (now - req.enqueued_at) * 1e3,
                tenant=self._tenant_label(req))
            req.handle._fail(DeadlineExceededError(
                f"deadline expired after "
                f"{(now - req.enqueued_at) * 1e3:.1f} ms in queue"))
            self._req_done(req)
            return "done"
        reservation = None
        row: List[int] = []
        read_row = None
        if self._paged:
            reservation = self._paged_reserve(req)
            if reservation == "budget":
                return "starved_budget"
            if not isinstance(reservation, tuple):
                # None = block-starved, "wait" = host-tier chain still
                # prefetching; either way the request stays held (only
                # its own tenant's line waits) and the slot stays free.
                return "starved"
        req.t_admit = now
        self._streams_started += 1     # the serve_hook @stream counter
        try:
            length = int(req.tokens.size)
            args = [self._params]
            if self._adapters is not None:
                # The table read HERE is the hot-load boundary: a load
                # committed before this admission is visible, one racing
                # it lands at the next boundary — never mid-program.
                args.append(self._adapters.table())
            if self._chunked:
                hits, fresh, n_total = reservation
                row = hits + fresh
                bs = self._cfg.block_size
                ct = self._cfg.chunk_tokens
                # The compiled program starts at the first non-shared
                # block: the bucket is drawn on the SUFFIX length, so a
                # deep hit executes a genuinely smaller program.
                start = len(hits) * bs
                suf_len = length - start
                bucket = bucket_for(suf_len, self._chunked_buckets)
                toks = np.zeros((bucket,), np.int32)
                toks[:suf_len] = req.tokens[start:]
                exe = self._compile(("chunked_prefill", bucket))
                args += [toks, self._cache, np.asarray(slot, np.int32),
                         np.asarray(length, np.int32),
                         np.asarray(start, np.int32)]
                if self._adapters is not None:
                    args.append(np.asarray(req.adapter_slot, np.int32))
                nb = self._cfg.blocks_per_slot
                read_row = np.full((nb,), TRASH_BLOCK, np.int32)
                read_row[:n_total] = row
                # Per-chunk write targets: only the fresh blocks the
                # suffix's PROMPT positions land in — hit blocks are
                # never written at all, generation blocks and bucket
                # padding write to the trash block.
                suffix_blocks = row[len(hits):blocks_for(length, bs)]
                wflat = np.full((bucket // bs,), TRASH_BLOCK, np.int32)
                wflat[:len(suffix_blocks)] = suffix_blocks
                args += [wflat.reshape(bucket // ct,
                                       self._cfg.chunk_blocks),
                         read_row]
                n_full = length // bs
                if n_full > 0:
                    self._metrics.on_prefix(len(hits), n_full)
                self._metrics.on_chunked_prefill(bucket // ct,
                                                 start // ct)
            else:
                bucket = bucket_for(length, self._buckets)
                toks = np.zeros((bucket,), np.int32)
                toks[:length] = req.tokens
                exe = self._compile(("prefill", bucket))
                args += [toks, self._cache, np.asarray(slot, np.int32),
                         np.asarray(length, np.int32)]
                if self._adapters is not None:
                    args.append(np.asarray(req.adapter_slot, np.int32))
                if self._paged:
                    hits, fresh, n_total = reservation
                    row = hits + fresh
                    nb = self._cfg.blocks_per_slot
                    read_row = np.full((nb,), TRASH_BLOCK, np.int32)
                    read_row[:n_total] = row
                    # Writes aimed at SHARED prefix blocks go to the
                    # trash block: the recomputed prefix K/V is already
                    # resident, and a sharer must never touch bytes
                    # other streams read.
                    write_row = read_row.copy()
                    write_row[:len(hits)] = TRASH_BLOCK
                    n_full = length // self._cfg.block_size
                    if self._cfg.prefix_reuse and n_full > 0:
                        self._metrics.on_prefix(len(hits), n_full)
                    args.append(write_row)
            self._last_prefill_bucket = bucket
            cache, last_logits = exe(*args)
            logits = np.asarray(last_logits)    # blocks
        except Exception as e:  # noqa: BLE001
            if reservation is not None:
                hits, fresh, _ = reservation
                self._blocks.release(hits + fresh)
            req.handle._fail(e)
            self._req_done(req)
            return "done"
        self._cache = cache
        if self._paged and self._cfg.prefix_reuse:
            # Pin the prompt's full blocks for future admissions — the
            # prefix now lives in the pool whether or not this stream
            # survives its first token.
            n_full = int(req.tokens.size) // self._cfg.block_size
            if n_full > 0:
                self._blocks.register_prefix(
                    req.tokens, row, n_full, salt=req.prefix_salt,
                    route_digest=prefix_route_digest(
                        req.tokens, self._cfg.block_size, req.adapter))
        if req.replay_expect is None:
            # A resuming stream's first token was already DELIVERED
            # (and its TTFT recorded) before the eviction — re-stamping
            # here would double-count the tenant's SLO outcomes.
            req.t_first = time.monotonic()
            self._metrics.on_first_token(
                (req.t_first - req.enqueued_at) * 1e3,
                tenant=self._tenant_label(req),
                slo_ms=self._slo_of(req.tenant))
        tok = req.sample(logits)
        req.n_out = 1
        self._req_emit(req, tok)
        reason = self._finish_reason(req, tok, next_pos=int(req.tokens.size))
        if reason:
            self._finish(req, reason)
            if self._paged:
                self._blocks.release(row)
            return "done"
        self._slots[slot] = req
        self._positions[slot] = int(req.tokens.size)
        self._last[slot] = tok
        self._adapter_idx[slot] = req.adapter_slot
        if self._paged:
            self._slot_blocks[slot] = row
            self._tables[slot] = read_row
        return "ok"

    def _step_once(self) -> None:
        """One decode-step boundary: the speculative draft→verify→accept
        step when speculation is configured, the plain one-token decode
        otherwise."""
        if self._spec is None:
            self._decode_once()
        else:
            self._spec_once()

    def _spec_once(self) -> None:
        """Draft k tokens per slot host-side, verify all k+1 positions in
        ONE compiled forward, accept per slot.

        Acceptance is per-slot VARIABLE: a slot whose drafts all miss
        still emits one token (verify row 0 is bitwise the decode-step
        logits), and a step where NO slot drafted anything falls through
        to the plain decode program — speculation is an optimization,
        never a liveness dependency. Greedy acceptance emits exactly the
        one-token stream (digest-pinned in ci.sh); sampled acceptance is
        the seeded rejection rule in :mod:`.spec`. Every accepted token
        flows through ``handle._emit`` one at a time, so fleet failover
        envelopes replay a speculated stream token-for-token unchanged.
        """
        k = self._spec.k
        w = k + 1
        t0 = time.monotonic()
        active = [i for i, r in enumerate(self._slots) if r is not None]
        # Pad columns repeat the slot's last token: always a valid id,
        # and the rows are never read by the host (their K/V writes are
        # overwritten before the mask ever exposes them).
        toks = np.repeat(self._last.copy()[:, None], w, axis=1)
        drafts: Dict[int, np.ndarray] = {}
        for i in active:
            req = self._slots[i]
            # Most tokens this stream may still emit (budget + cache
            # room); drafting past cap-1 can't be accepted AND keeps
            # every write inside the blocks admission reserved.
            cap = min(req.max_new - req.n_out,
                      self._cfg.max_len - int(self._positions[i]))
            if cap < 2:
                continue
            # [:n_out]: for a normal stream that IS the whole emitted
            # list, but a preemption replay must draft from only the
            # regenerated-so-far prefix — the envelope's future tokens
            # would otherwise change the drafts, change the rng draws
            # sampled acceptance consumes, and break bit-identity.
            ctx = np.concatenate(
                [np.asarray(req.tokens, np.int64),
                 np.asarray(req.handle._tokens[:req.n_out], np.int64)])
            d = np.asarray(self._drafter.propose(ctx, min(k, cap - 1)),
                           np.int64).ravel()[:min(k, cap - 1)]
            d = d[(d >= 0) & (d < self._model_cfg.vocab)]
            if d.size:
                drafts[i] = d
                toks[i, 1:1 + d.size] = d
        draft_ms = (time.monotonic() - t0) * 1e3
        if not drafts:
            # Plain one-token step (still counted: tokens-per-step is an
            # EFFECTIVE rate over every step speculation supervised).
            self._decode_once()
            self._metrics.on_spec_step(0, 0, len(active), draft_ms, 0.0)
            return
        t1 = time.monotonic()
        args = [self._params]
        if self._adapters is not None:
            args.append(self._adapters.table())
        args += [toks, self._cache, self._positions.copy()]
        if self._adapters is not None:
            args.append(self._adapter_idx.copy())
        if self._paged:
            args.append(self._tables.copy())
        cache, logits = self._compile(("verify", w))(*args)
        logits_np = np.asarray(logits)          # [S, W, vocab], blocks
        self._cache = cache
        exec_ms = (time.monotonic() - t1) * 1e3
        self._peak_active = max(self._peak_active, len(active))
        self._metrics.on_batch(self._cfg.max_slots, len(active), exec_ms,
                               len(self._queue) + len(self._held))
        proposed = accepted = emitted_total = 0
        for i in active:
            req = self._slots[i]
            rows = logits_np[i]
            d = drafts.get(i)
            if d is None:
                cand, hits = [req.sample(rows[0])], 0
            elif req.sampling.temperature <= 0:
                cand, hits = accept_greedy(rows, d)
            else:
                cand, hits = accept_sampled(rows, d, req.probs, req.rng)
            emitted = 0
            reason = None
            for tok in cand:
                tok = int(tok)
                req.n_out += 1
                self._req_emit(req, tok)
                self._positions[i] += 1
                self._last[i] = tok
                emitted += 1
                reason = self._finish_reason(
                    req, tok, next_pos=int(self._positions[i]))
                if reason:
                    break
            n_prop = int(d.size) if d is not None else 0
            # EOS/length can truncate mid-acceptance; only tokens that
            # actually reached the stream count as accepted drafts.
            n_hit = min(hits, emitted)
            req.spec_proposed += n_prop
            req.spec_accepted += n_hit
            proposed += n_prop
            accepted += n_hit
            emitted_total += emitted
            if reason:
                # Counters first: _finish stamps the per-request spec
                # accounting into the result info.
                self._finish(req, reason)
                self._release_slot(i)
        self._metrics.on_spec_step(proposed, accepted, emitted_total,
                                   draft_ms, exec_ms)

    def _decode_once(self) -> None:
        t0 = time.monotonic()
        args = [self._params]
        if self._adapters is not None:
            args.append(self._adapters.table())   # hot-load boundary
        args += [self._last.copy(), self._cache, self._positions.copy()]
        if self._adapters is not None:
            args.append(self._adapter_idx.copy())
        if self._paged:
            args.append(self._tables.copy())
        cache, logits = self._compile("decode")(*args)
        logits_np = np.asarray(logits)          # blocks
        self._cache = cache
        exec_ms = (time.monotonic() - t0) * 1e3
        active = [i for i, r in enumerate(self._slots) if r is not None]
        self._peak_active = max(self._peak_active, len(active))
        self._metrics.on_batch(self._cfg.max_slots, len(active), exec_ms,
                               len(self._queue) + len(self._held))
        for i in active:
            req = self._slots[i]
            tok = req.sample(logits_np[i])
            req.n_out += 1
            self._req_emit(req, tok)
            self._positions[i] += 1
            self._last[i] = tok
            reason = self._finish_reason(req, tok,
                                         next_pos=int(self._positions[i]))
            if reason:
                self._finish(req, reason)
                self._release_slot(i)

    def _finish_reason(self, req: _GenRequest, tok: int,
                       next_pos: int) -> Optional[str]:
        if req.eos is not None and tok == req.eos:
            return "eos"
        if req.n_out >= req.max_new or next_pos >= self._cfg.max_len:
            return "length"
        return None

    def _finish(self, req: _GenRequest, reason: str) -> None:
        if (req.replay_expect is not None
                and req.replay_i < len(req.replay_expect)):
            # Finishing mid-replay means the regenerated stream ended
            # EARLIER than its own recorded envelope — divergence, the
            # same impossible-by-contract condition _req_emit guards.
            raise RuntimeError(
                f"preemption replay of stream {req.stream_id} finished "
                f"({reason}) at position {req.replay_i} but its envelope "
                f"holds {len(req.replay_expect)} tokens — the slot-row "
                f"bit-identity contract is broken")
        now = time.monotonic()
        gen_s = now - req.t_first
        ttft_ms = (req.t_first - req.enqueued_at) * 1e3
        self._metrics.on_generation_end(req.n_out, gen_s,
                                        tenant=self._tenant_label(req))
        # queue_ms is the ADMISSION wait (enqueue → slot), not TTFT —
        # latency.queue_* must isolate queue pressure from prefill cost.
        self._metrics.on_response((now - req.enqueued_at) * 1e3,
                                  (req.t_admit - req.enqueued_at) * 1e3)
        self._req_done(req)
        flightrec.record("serve_complete", replica=self.serve_name,
                         stream=req.stream_id, n_tokens=req.n_out,
                         reason=reason)
        req.handle._finish({
            "tokens": list(req.handle._tokens),
            "finish_reason": reason,
            "n_tokens": req.n_out,
            "ttft_ms": ttft_ms,
            "tenant": req.tenant,
            "adapter": req.adapter,
            "tokens_per_sec": ((req.n_out - 1) / gen_s
                               if req.n_out > 1 and gen_s > 0 else None),
            # Per-request speculation accounting (None = spec off).
            "spec_accept_rate": (
                (req.spec_accepted / req.spec_proposed
                 if req.spec_proposed else 0.0)
                if self._spec is not None else None),
        })
