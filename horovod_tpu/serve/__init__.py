"""``horovod_tpu.serve`` — dynamic-batching inference over the sharded
runtime.

The serving counterpart of the training stack (ROADMAP north star:
"serves heavy traffic from millions of users"): single requests in,
padded power-of-two batches through a warm per-bucket compile cache,
params restored from training checkpoints and laid out over the
``parallel.mesh`` slice. See ``docs/inference.md`` for the operator
guide.

    from horovod_tpu import serve
    variables = serve.restore_for_inference(ckpt_dir)
    eng = serve.Engine(lambda v, x: model.apply(v, x, train=False),
                       variables, item_shape=(224, 224, 3))
    eng.warmup()
    logits = eng.infer(image)

For the transformer LM, :class:`~.generate.GenerationEngine` adds
continuous-batching KV-cache generation (requests join/leave the decode
batch every step) with streaming token delivery — with contiguous
per-slot KV reservations or a paged block pool with copy-on-write
prefix sharing (``GenerationConfig(kv_layout="paged", ...)``; see
``docs/inference.md`` "Paged KV cache"):

    params = serve.restore_for_inference(ckpt_dir, dtype="int8")["params"]
    gen = serve.GenerationEngine(params, cfg,
                                 serve.GenerationConfig(max_slots=8,
                                                        max_len=512))
    gen.warmup()
    for tok in gen.submit(prompt_ids, max_new_tokens=64):
        ...

N engine replicas serve behind ONE front door as a *fleet*
(:class:`~.router.FleetRouter`: least-queue-depth dispatch, warming
replicas take no traffic, drain-on-evict loses no admitted stream) with
a queue-depth autoscaler closing the loop
(:class:`~.fleet.FleetAutoscaler`; docs/inference.md "Serving fleet"):

    router = serve.FleetRouter(factory=lambda name: make_engine(),
                               initial=2)
    router.warmup()
    serve.FleetAutoscaler(router, min_replicas=2, max_replicas=8).start()
    serve.HttpServer(generate=router).start()

Replicas can also live OUT of process: :func:`~.proc_replica.
spawn_replica_factory` builds each member as a subprocess worker
(``python -m horovod_tpu.serve.proc_replica``) fronted by a
:class:`~.proc_replica.ProcReplicaClient` that duck-types the engine
surface over HTTP, so spawn/warm/drain/evict, the autoscaler, and
stream failover all work unchanged across the process boundary
(docs/inference.md "Process replicas"):

    factory = serve.spawn_replica_factory({"model": {...}, "seed": 0,
                                           "generation": {...}})
    router = serve.FleetRouter(factory=factory, initial=3)
"""

from .adapters import AdapterRegistry  # noqa: F401
from .batcher import (  # noqa: F401
    Request,
    RequestQueue,
    bucket_for,
    bucket_sizes,
    pad_rows,
)
from .engine import SERVE_PHASES, Engine, ServeConfig  # noqa: F401
from .generate import (  # noqa: F401
    GenerationConfig,
    GenerationEngine,
    GenerationHandle,
    SamplingParams,
    prefill_buckets,
)
from .metrics import FleetMetrics, ServeMetrics  # noqa: F401
from .sched import FairScheduler  # noqa: F401
from .spec import (  # noqa: F401
    DraftProposer,
    NgramProposer,
    SpecConfig,
)
from .router import FleetRouter, ReplicaHandle  # noqa: F401
from .fleet import FleetAutoscaler, heartbeat_liveness  # noqa: F401
from .server import HttpServer  # noqa: F401
from ..parallel.checkpoint import (  # noqa: F401
    INFERENCE_DTYPES,
    restore_adapter,
    restore_for_inference,
    save_adapter,
)
from ..parallel.lora import (  # noqa: F401
    LoraConfig,
    adapter_bytes,
    check_adapter_name,
    init_adapter,
    stack_adapters,
)
from ..parallel.kv_blocks import (  # noqa: F401
    BlockManager,
    blocks_for,
    init_paged_kv_cache,
    paged_decode_step,
    paged_kv_cache_specs,
    paged_prefill,
    paged_verify_step,
)
from ..parallel.transformer import (  # noqa: F401
    decode_step,
    init_kv_cache,
    kv_cache_specs,
    prefill,
    verify_step,
)
from ..exceptions import (  # noqa: F401
    DeadlineExceededError,
    FailoverExhaustedError,
    PreemptedError,
    ReplicaTimeoutError,
    ServerClosedError,
    ServerOverloadedError,
)

_PROC_REPLICA_NAMES = ("ProcReplicaClient", "spawn_replica_factory")


def __getattr__(name):
    # Lazy (PEP 562): `python -m horovod_tpu.serve.proc_replica` — the
    # worker entrypoint — imports this package first, and an eager
    # `from .proc_replica import ...` here would put the module in
    # sys.modules before runpy executes it as __main__ (double
    # execution + RuntimeWarning in every spawned child).
    if name in _PROC_REPLICA_NAMES:
        from . import proc_replica
        return getattr(proc_replica, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
