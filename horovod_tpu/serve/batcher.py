"""Dynamic batching: request queue, power-of-two buckets, padding.

Single requests arrive on a thread-safe bounded queue and leave as
padded, *bucketed* batches — the Orca-style iteration-batching shape
(PAPERS.md lineage) reduced to its stateless-model core:

* **Buckets** are the powers of two up to ``max_batch``. A jit cache
  keyed on raw batch size would compile one executable per distinct
  arrival count; rounding up to a bucket caps the cache at
  ``log2(max_batch)+1`` programs, all pre-compilable by ``warmup()``.
* **Flush policy**: a batch ships when it reaches ``max_batch``, or when
  the *oldest* queued request has waited ``batch_timeout_ms`` — latency
  is bounded by the head-of-line request's wait, not by arrival gaps.
* **Padding** replicates row 0 rather than writing zeros: padding rows
  are discarded on the way out, and with row 0 duplicated the padded
  batch cannot manufacture NaN/Inf rows out of thin air for models with
  row-coupled numerics (nothing in the contract requires row coupling,
  but a denormal-heavy zero row is a classic TPU perf trap too).

Everything here is backend-agnostic host code — numpy in, numpy out —
which is what makes the serving suite runnable under
``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..exceptions import ServerClosedError, ServerOverloadedError


def bucket_sizes(max_batch: int) -> Tuple[int, ...]:
    """(1, 2, 4, …, max_batch). ``max_batch`` must be a power of two so
    the top bucket and the flush threshold coincide."""
    if max_batch < 1 or (max_batch & (max_batch - 1)):
        raise ValueError(f"max_batch must be a power of two, got {max_batch}")
    sizes = []
    b = 1
    while b <= max_batch:
        sizes.append(b)
        b *= 2
    return tuple(sizes)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (callers guarantee n <= max(buckets))."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the top bucket {buckets[-1]}")


def pad_rows(rows: Sequence[np.ndarray], bucket: int) -> np.ndarray:
    """Stack single-example rows into a [bucket, *item_shape] array,
    replicating row 0 into the padding slots."""
    n = len(rows)
    if not 0 < n <= bucket:
        raise ValueError(f"{n} rows do not fit bucket {bucket}")
    out = np.stack(list(rows) + [rows[0]] * (bucket - n))
    return out


@dataclasses.dataclass
class Request:
    """One queued inference request (a single example)."""

    inputs: np.ndarray
    future: Any                      # concurrent.futures.Future
    enqueued_at: float               # time.monotonic()
    deadline_at: Optional[float]     # absolute monotonic deadline, or None
    # Filled by the engine at dispatch. ``executed_batch`` (only when
    # ``ServeConfig.record_executed_batch`` — it pins the padded array
    # for the future's lifetime) is the [bucket, *item] program input and
    # ``row`` this request's row in it:
    # ``apply(variables, executed_batch)[row]`` must be bit-identical to
    # the served output (the serving correctness contract
    # tests/test_serve.py pins).
    bucket: Optional[int] = None
    executed_batch: Optional[np.ndarray] = None
    row: Optional[int] = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_at is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline_at


class RequestQueue:
    """Bounded FIFO with the dynamic-batching dequeue policy.

    ``put`` is non-blocking admission control: a full queue raises
    :class:`ServerOverloadedError` immediately (shedding load at the door
    beats queueing requests that will only expire — the deadline would
    have burned while waiting).
    """

    def __init__(self, max_queue: int):
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._max = int(max_queue)
        self._closed = False
        # Requests a consumer took with ``hold=True`` and still owns
        # (the generation engine's held line). They left the deque but
        # have not been served, so they still count against ``max_queue``
        # — otherwise draining the queue into a host-side holding area
        # would silently disable admission backpressure.
        self._external = 0

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)

    @property
    def held_count(self) -> int:
        """Requests taken with ``hold=True`` and not yet released."""
        with self._cv:
            return self._external

    def put(self, req: Request) -> int:
        """Admit ``req``; returns the resulting queue depth."""
        with self._cv:
            if self._closed:
                raise ServerClosedError("inference server is shut down")
            if len(self._q) + self._external >= self._max:
                raise ServerOverloadedError(
                    f"request queue full ({self._max}); retry after backoff")
            self._q.append(req)
            self._cv.notify()
            return len(self._q)

    def release_held(self, n: int = 1) -> None:
        """Return ``n`` ``hold=True`` tickets (the requests were served,
        failed, or expired) — frees their admission capacity."""
        with self._cv:
            self._external = max(0, self._external - n)

    def take_batch(self, max_batch: int,
                   batch_timeout_ms: float, *,
                   hold: bool = False) -> List[Request]:
        """Block until a batch is due, then return it (possibly empty —
        an empty list means the queue was closed and fully drained).

        A batch is due when ``max_batch`` requests are queued or the
        oldest has waited ``batch_timeout_ms``. Expired requests are NOT
        filtered here — the engine drops them so the failure and the
        metrics update happen in one place.

        No polling: every producer transition (``put``, ``close``)
        notifies the condition variable, so the empty-queue wait is
        untimed (an idle engine costs zero wakeups) and the non-empty
        wait sleeps exactly to the oldest request's flush deadline — a
        burst arriving mid-wait wakes it via ``put``'s notify and flushes
        at ``max_batch`` immediately.

        ``hold=True`` keeps the returned requests counted against
        ``max_queue`` until the caller hands each ticket back via
        :meth:`release_held` — taken and returned under the same lock,
        so no submit can thread between the dequeue and the count.
        """
        deadline_of_oldest = None
        with self._cv:
            while True:
                if self._q:
                    now = time.monotonic()
                    if deadline_of_oldest is None:
                        deadline_of_oldest = (self._q[0].enqueued_at
                                              + batch_timeout_ms / 1e3)
                    if (len(self._q) >= max_batch
                            or now >= deadline_of_oldest
                            or self._closed):
                        # (A closed queue flushes immediately: a graceful
                        # drain should not serve its tail one flush
                        # timeout at a time.)
                        batch = [self._q.popleft()
                                 for _ in range(min(max_batch,
                                                    len(self._q)))]
                        if hold:
                            self._external += len(batch)
                        self._cv.notify_all()
                        return batch
                    self._cv.wait(deadline_of_oldest - now)
                else:
                    deadline_of_oldest = None
                    if self._closed:
                        return []
                    self._cv.wait()

    def close(self) -> List[Request]:
        """Stop admission. Returns [] (drain mode leaves queued requests
        for the dispatcher); call ``drain_pending`` to evict instead."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            return []

    def drain_pending(self) -> List[Request]:
        """Evict and return everything still queued (non-drain shutdown)."""
        with self._cv:
            self._closed = True
            pending = list(self._q)
            self._q.clear()
            self._cv.notify_all()
            return pending
