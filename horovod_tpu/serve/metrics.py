"""Serving observability: counters, latency quantiles, batch-fill ratio.

The serving plane's numbers answer three operational questions the
training-side metrics never ask: *how long does one request take*
(p50/p99 end-to-end and per-phase), *how full are the batches the chips
actually execute* (fill ratio — padding is paid compute), and *is the
server keeping up* (queue depth, overload/deadline drops). Everything is
exported as one plain-dict snapshot (``Engine.stats()`` / the HTTP
``/stats`` endpoint) so scrapers need no client library.

Quantiles come from a bounded reservoir (uniform replacement once full):
serving runs indefinitely, so an unbounded latency list is a slow leak;
a 4096-sample reservoir pins memory while keeping p99 estimates stable
at serving rates. The reservoir RNG is a private ``random.Random`` so
sampling never perturbs user-visible randomness.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs.registry import Meta, MetricsRegistry, Sample


class _Reservoir:
    """Fixed-size uniform reservoir of float samples (Vitter's algorithm R).

    Self-locking: every historical caller mutates under the owning
    :class:`ServeMetrics` lock, but the reservoir is also handed out as
    a building block (tests, benches) — and a ``quantile()`` racing an
    ``add()``'s list replacement would read a torn sample set. The lock
    is uncontended in the single-owner case, so it costs nothing where
    the outer lock already serializes.
    """

    def __init__(self, capacity: int = 4096, seed: int = 0):
        self._cap = int(capacity)
        self._seen = 0
        self._vals: List[float] = []
        self._rng = random.Random(seed)
        self._rlock = threading.Lock()

    def add(self, value: float) -> None:
        with self._rlock:
            self._seen += 1
            if len(self._vals) < self._cap:
                self._vals.append(value)
                return
            j = self._rng.randrange(self._seen)
            if j < self._cap:
                self._vals[j] = value

    def quantile(self, q: float) -> Optional[float]:
        with self._rlock:
            vals = sorted(self._vals)
        if not vals:
            return None
        # Nearest-rank on the sorted reservoir — monotone in q and exact
        # for small sample counts (the property tests rely on).
        idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
        return vals[idx]

    @property
    def count(self) -> int:
        with self._rlock:
            return self._seen


class ServeMetrics:
    """Thread-safe serving counters + latency recorders.

    All mutation goes through one lock: the producers (N submitter
    threads) and the consumer (the dispatch thread) race on every
    counter, and serving metrics that tear under load are worse than
    none — an operator acts on them.
    """

    # Bucket bounds for the per-user decode rate (tokens/sec): not a
    # latency, so the latency default would waste every bucket under 1.
    TPS_BUCKETS = (1, 2, 5, 10, 20, 50, 100, 200, 500, 1000)

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        # Prometheus-scrapable histograms, PRIVATE per engine (two
        # engines in one process must not collide on one registry).
        # The reservoirs above keep feeding the JSON /stats percentiles;
        # histograms are what a real scraper needs — cumulative bucket
        # counts survive counter resets and aggregate across replicas,
        # which reservoir percentiles never can.
        self.registry = MetricsRegistry()
        self._h_request = self.registry.histogram(
            "hvd_request_seconds", "End-to-end request latency")
        self._h_queue = self.registry.histogram(
            "hvd_queue_seconds", "Time from submit to execution start")
        self._h_execute = self.registry.histogram(
            "hvd_execute_seconds", "Device batch execution time")
        self._h_ttft = self.registry.histogram(
            "hvd_generate_ttft_seconds",
            "Time to first token (submit to the prefill's sampled "
            "token)")
        self._h_tps = self.registry.histogram(
            "hvd_generate_tokens_per_sec_user",
            "Per-stream decode rate (first token to last)",
            buckets=self.TPS_BUCKETS)
        # Per-tenant series (the multi-tenant adapter plane): tenant= is
        # the label rule — one series family, one label, bounded by the
        # resident-adapter count + "base", never by user count.
        self._h_tenant_ttft = self.registry.histogram(
            "hvd_tenant_ttft_seconds",
            "Per-tenant time to first token", labels=("tenant",))
        self._h_tenant_tps = self.registry.histogram(
            "hvd_tenant_tokens_per_sec_user",
            "Per-tenant per-stream decode rate", labels=("tenant",),
            buckets=self.TPS_BUCKETS)
        self._c_tenant_generations = self.registry.counter(
            "hvd_tenant_generations_total",
            "Generation streams finished, by tenant", labels=("tenant",))
        self._c_tenant_tokens = self.registry.counter(
            "hvd_tenant_tokens_generated_total",
            "Tokens sampled, by tenant", labels=("tenant",))
        # Per-tenant SLO burn: misses counted against the tenant's OWN
        # targets (registry/config), so the series only exist for
        # tenants that declared an SLO — no target, no burn to measure.
        self._c_tenant_slo_ttft_miss = self.registry.counter(
            "hvd_tenant_slo_ttft_miss_total",
            "First tokens later than the tenant's TTFT SLO target",
            labels=("tenant",))
        self._c_tenant_slo_deadline_miss = self.registry.counter(
            "hvd_tenant_slo_deadline_miss_total",
            "Requests expired past their deadline, by tenant",
            labels=("tenant",))
        self._g_tenant_slo_burn = self.registry.gauge(
            "hvd_tenant_slo_burn",
            "Fraction of the tenant's outcomes that burned its SLO "
            "(TTFT misses + deadline misses over completions + "
            "deadline misses)", labels=("tenant",))
        self._g_tenant_slo_target = self.registry.gauge(
            "hvd_tenant_slo_ttft_target_ms",
            "The tenant's configured TTFT SLO target",
            labels=("tenant",))
        self.requests_total = 0
        self.responses_total = 0
        self.rejected_overload = 0
        # Overload rejections split by the resource that was actually
        # scarce when the door closed: "slots_full" (decode width / the
        # single-shot engine's throughput) vs "blocks_exhausted" (the
        # paged engine's KV block pool) — an operator raising max_slots
        # when the pool is the binding constraint fixes nothing.
        self.rejected_slots_full = 0
        self.rejected_blocks_exhausted = 0
        self.rejected_tenant_quota = 0
        self.expired_deadline = 0
        self.cancelled_shutdown = 0
        self.batches_total = 0
        self.batch_rows_total = 0      # bucket slots executed (incl. padding)
        self.batch_live_rows_total = 0  # real requests in those slots
        self.queue_depth = 0
        self._request_ms = _Reservoir()
        self._queue_ms = _Reservoir(seed=1)
        self._execute_ms = _Reservoir(seed=2)
        # Generation-plane recorders (the continuous-batching engine):
        # unused by the single-shot Engine, zero/None in its snapshot.
        self.generations_total = 0
        self.tokens_generated_total = 0
        self._ttft_ms = _Reservoir(seed=3)
        self._tps_user = _Reservoir(seed=4)
        # Prefix-cache effectiveness (the paged engine's reuse plane):
        # a lookup counts as a hit when at least one full block of the
        # prompt was already resident; hit_blocks/lookup_blocks give the
        # block-level rate (how much prefill HBM sharing actually saves).
        self.prefix_hits_total = 0
        self.prefix_misses_total = 0
        self.prefix_hit_blocks_total = 0
        self.prefix_lookup_blocks_total = 0
        # Speculative-decoding plane: one "spec step" is one decode-step
        # boundary supervised by speculation (a verify forward, or the
        # plain one-token fallback when no slot drafted). Zero for
        # engines without a SpecConfig.
        self.spec_steps_total = 0
        self.spec_draft_tokens_total = 0      # proposed by the drafter
        self.spec_accepted_tokens_total = 0   # proposals that reached streams
        self.spec_emitted_tokens_total = 0    # all tokens out of spec steps
        self._spec_draft_ms = _Reservoir(seed=5)
        self._spec_verify_ms = _Reservoir(seed=6)
        self._h_spec_draft = self.registry.histogram(
            "hvd_spec_draft_seconds",
            "Host-side draft proposal time per spec step")
        self._h_spec_verify = self.registry.histogram(
            "hvd_spec_verify_seconds",
            "Verify-forward execution time per spec step")
        # KV memory-hierarchy plane (chunked prefill + host tier):
        # offload/prefetch counters track block traffic between the
        # device pool and pinned host memory; chunk counters pin the
        # skip-compute contract (skipped/total = the prefill compute the
        # prefix cache actually saved). Zero for non-tiered engines.
        self.kv_offload_blocks_total = 0
        self.kv_prefetch_blocks_total = 0
        self.prefill_chunks_total = 0
        self.prefill_chunks_skipped_total = 0
        # Preemption plane (priority-class evictions): evictions, their
        # verdicts. resumed + exhausted <= preemptions while an evicted
        # stream is still replaying. Zero for FIFO engines.
        self.preemptions_total = 0
        self.preempt_resumed_total = 0
        self.preempt_exhausted_total = 0
        self._h_prefetch = self.registry.histogram(
            "hvd_kv_prefetch_seconds",
            "Host-to-device prefetch latency per block chain")
        # Per-tenant recorders (multi-tenant adapters): lazily created on
        # first tenant-stamped event. Engines without an AdapterRegistry
        # never stamp one (GenerationEngine._tenant_label), so base-only
        # engines keep an empty map and expose no hvd_tenant_* series.
        self._tenants: Dict[str, Dict] = {}

    # -- producers ---------------------------------------------------------

    def on_submit(self, queue_depth: int) -> None:
        with self._lock:
            self.requests_total += 1
            self.queue_depth = queue_depth

    def on_overload(self, reason: str = "slots_full") -> None:
        """``reason`` is ``"slots_full"``, ``"blocks_exhausted"`` or
        ``"tenant_quota"`` — the engine names the scarce resource;
        ``rejected_overload`` stays the total so existing dashboards
        keep reading."""
        with self._lock:
            self.rejected_overload += 1
            if reason == "blocks_exhausted":
                self.rejected_blocks_exhausted += 1
            elif reason == "tenant_quota":
                self.rejected_tenant_quota += 1
            else:
                self.rejected_slots_full += 1

    def on_deadline_expired(self, queue_ms: float,
                            tenant: Optional[str] = None) -> None:
        """``tenant`` additionally counts the expiry against the
        tenant's SLO burn — a deadline miss is the worst burn outcome,
        target or no target."""
        with self._lock:
            self.expired_deadline += 1
            self._queue_ms.add(queue_ms)
            if tenant is not None:
                self._tenant(tenant)["deadline_miss_total"] += 1
                self._refresh_burn(tenant)
        if tenant is not None:
            self._c_tenant_slo_deadline_miss.labels(tenant=tenant).inc()

    def on_shutdown_cancel(self, n: int) -> None:
        with self._lock:
            self.cancelled_shutdown += n

    def on_batch(self, bucket: int, live_rows: int, execute_ms: float,
                 queue_depth: int) -> None:
        with self._lock:
            self.batches_total += 1
            self.batch_rows_total += bucket
            self.batch_live_rows_total += live_rows
            self.queue_depth = queue_depth
            self._execute_ms.add(execute_ms)
        self._h_execute.observe(execute_ms / 1e3)

    def on_response(self, request_ms: float, queue_ms: float) -> None:
        with self._lock:
            self.responses_total += 1
            self._request_ms.add(request_ms)
            self._queue_ms.add(queue_ms)
        self._h_request.observe(request_ms / 1e3)
        self._h_queue.observe(queue_ms / 1e3)

    # -- generation plane ----------------------------------------------------

    def _tenant(self, name: str) -> Dict:
        """The per-tenant recorder bundle (caller holds ``self._lock``)."""
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = {
                "generations_total": 0, "tokens_generated_total": 0,
                "first_tokens_total": 0, "ttft_slo_miss_total": 0,
                "deadline_miss_total": 0, "preemptions_total": 0,
                "slo_ttft_target_ms": None,
                "_ttft": _Reservoir(seed=5), "_tps": _Reservoir(seed=6)}
        return t

    @staticmethod
    def _burn(t: Dict) -> float:
        """SLO burn fraction of one tenant bundle: misses over
        outcomes. Deadline misses count in BOTH halves — an expired
        request never produced a first token, so its only trace is the
        miss itself."""
        misses = t["ttft_slo_miss_total"] + t["deadline_miss_total"]
        outcomes = t["first_tokens_total"] + t["deadline_miss_total"]
        return misses / outcomes if outcomes else 0.0

    def _refresh_burn(self, tenant: str) -> None:
        """Re-publish the tenant's burn gauge (caller holds the lock)."""
        self._g_tenant_slo_burn.labels(tenant=tenant).set(
            self._burn(self._tenants[tenant]))

    def on_first_token(self, ttft_ms: float,
                       tenant: Optional[str] = None,
                       slo_ms: Optional[float] = None) -> None:
        """Time-to-first-token: submit → the prefill's sampled token. The
        latency a generation user actually perceives as 'responsiveness'
        — decode throughput is a separate number (below). ``tenant``
        additionally records the multi-tenant split; ``slo_ms`` is the
        tenant's TTFT target — a first token past it counts one SLO
        miss."""
        with self._lock:
            self._ttft_ms.add(ttft_ms)
            if tenant is not None:
                t = self._tenant(tenant)
                t["_ttft"].add(ttft_ms)
                t["first_tokens_total"] += 1
                missed = slo_ms is not None and ttft_ms > slo_ms
                if slo_ms is not None:
                    t["slo_ttft_target_ms"] = float(slo_ms)
                    self._g_tenant_slo_target.labels(
                        tenant=tenant).set(float(slo_ms))
                if missed:
                    t["ttft_slo_miss_total"] += 1
                self._refresh_burn(tenant)
        self._h_ttft.observe(ttft_ms / 1e3)
        if tenant is not None:
            self._h_tenant_ttft.labels(tenant=tenant).observe(ttft_ms / 1e3)
            if missed:
                self._c_tenant_slo_ttft_miss.labels(tenant=tenant).inc()

    def on_tokens(self, n: int = 1, tenant: Optional[str] = None) -> None:
        with self._lock:
            self.tokens_generated_total += n
            if tenant is not None:
                self._tenant(tenant)["tokens_generated_total"] += n
        if tenant is not None:
            self._c_tenant_tokens.labels(tenant=tenant).inc(n)

    def on_spec_step(self, proposed: int, accepted: int, emitted: int,
                     draft_ms: float, verify_ms: float) -> None:
        """One speculation-supervised decode step: ``proposed`` draft
        tokens across the batch, ``accepted`` of them emitted, plus the
        non-draft tokens, ``emitted`` in total. ``verify_ms`` is 0 for
        a no-draft step that fell through to the plain decode (its
        execute time lands in the batch histogram either way)."""
        with self._lock:
            self.spec_steps_total += 1
            self.spec_draft_tokens_total += proposed
            self.spec_accepted_tokens_total += accepted
            self.spec_emitted_tokens_total += emitted
            self._spec_draft_ms.add(draft_ms)
            if verify_ms > 0:
                self._spec_verify_ms.add(verify_ms)
        self._h_spec_draft.observe(draft_ms / 1e3)
        if verify_ms > 0:
            self._h_spec_verify.observe(verify_ms / 1e3)

    def on_prefix(self, hit_blocks: int, prompt_blocks: int) -> None:
        """One prefix-cache lookup at admission: ``hit_blocks`` of the
        prompt's ``prompt_blocks`` full blocks were already resident."""
        with self._lock:
            if hit_blocks > 0:
                self.prefix_hits_total += 1
            else:
                self.prefix_misses_total += 1
            self.prefix_hit_blocks_total += hit_blocks
            self.prefix_lookup_blocks_total += prompt_blocks

    def on_kv_offload(self, n: int = 1) -> None:
        """``n`` cold registered-prefix blocks moved device -> host."""
        with self._lock:
            self.kv_offload_blocks_total += n

    def on_kv_prefetch(self, seconds: float, n: int = 1) -> None:
        """``n`` blocks landed host -> device; ``seconds`` is the
        stage-to-landing latency of the chain (admission kicked the
        fetch, the engine-loop top applied it — never a decode step)."""
        with self._lock:
            self.kv_prefetch_blocks_total += n
        self._h_prefetch.observe(seconds)

    def on_chunked_prefill(self, n_chunks: int, n_skipped: int) -> None:
        """One chunked prefill: the compiled program ran ``n_chunks``
        scan trips and the prefix cache let it skip ``n_skipped`` more
        (the shared prefix it never recomputed)."""
        with self._lock:
            self.prefill_chunks_total += n_chunks
            self.prefill_chunks_skipped_total += n_skipped

    def on_preempt(self, outcome: str,
                   tenant: Optional[str] = None) -> None:
        """One preemption-plane event: ``"evicted"`` (a lower-priority
        stream's slot was taken — ``tenant`` is the EVICTED tenant),
        ``"resumed"`` (its replay caught up and the stream continued
        bit-identically) or ``"exhausted"`` (evicted more times than
        the retry budget — terminal ``preempted_exhausted``).
        Deliberately separate from the failover counters: fleet
        failover churn and scheduling pressure are different operator
        problems."""
        if outcome not in ("evicted", "resumed", "exhausted"):
            raise ValueError(
                f"preempt outcome must be 'evicted', 'resumed' or "
                f"'exhausted', got {outcome!r}")
        with self._lock:
            if outcome == "evicted":
                self.preemptions_total += 1
                if tenant is not None:
                    self._tenant(tenant)["preemptions_total"] += 1
            elif outcome == "resumed":
                self.preempt_resumed_total += 1
            else:
                self.preempt_exhausted_total += 1

    def slo_burn(self, tenant: str) -> float:
        """The tenant's current SLO burn fraction (0.0 when unknown) —
        the router's dispatch signal: replicas already burning a
        tenant's SLO are deprioritized for that tenant's traffic."""
        with self._lock:
            t = self._tenants.get(tenant)
            return self._burn(t) if t is not None else 0.0

    def retry_after_ms(self, queue_depth: int) -> float:
        """Backoff hint for an overload rejection: roughly how long
        until the CURRENT queue has drained, from the engine's own
        measured service rate (responses ÷ uptime — for the generation
        engine that is tokens/sec divided by tokens-per-stream, the
        same number). A well-behaved client sleeping this long lands
        when its request can actually be admitted, instead of hammering
        a full door at its own retry cadence. Clamped to [50 ms, 30 s];
        1 s before the first response (no rate measured yet)."""
        with self._lock:
            done = self.responses_total
            uptime = time.monotonic() - self._t0
        if done > 0 and uptime > 0:
            hint = (queue_depth + 1) / (done / uptime) * 1e3
        else:
            hint = 1000.0
        return min(30000.0, max(50.0, hint))

    def forget_tenant(self, tenant: str) -> None:
        """The tenant's adapter was evicted: fold its COUNTERS into the
        one ``tenant="retired"`` aggregate and drop its recorders and
        labeled series — the ``FleetMetrics.forget_replica`` discipline.
        Tenant names churn over a process lifetime while table capacity
        stays fixed, so without this every name ever served would keep
        two reservoirs plus children on four ``hvd_tenant_*`` series
        forever. Counters stay monotone through the fold; histogram
        children terminate (scrapers treat disappearance as a normal
        series end). No live stream can race this: evict only succeeds
        at refcount 0, and queued streams hold refcounts."""
        if tenant == "retired":
            return
        with self._lock:
            t = self._tenants.pop(tenant, None)
            if t is None:
                return
            r = self._tenant("retired")
            r["generations_total"] += t["generations_total"]
            r["tokens_generated_total"] += t["tokens_generated_total"]
            r["first_tokens_total"] += t["first_tokens_total"]
            r["ttft_slo_miss_total"] += t["ttft_slo_miss_total"]
            r["deadline_miss_total"] += t["deadline_miss_total"]
            r["preemptions_total"] += t["preemptions_total"]
        for metric in (self._c_tenant_generations, self._c_tenant_tokens,
                       self._c_tenant_slo_ttft_miss,
                       self._c_tenant_slo_deadline_miss):
            count = metric.labels(tenant=tenant).value
            metric.remove(tenant=tenant)
            if count > 0:
                metric.labels(tenant="retired").inc(count)
        self._h_tenant_ttft.remove(tenant=tenant)
        self._h_tenant_tps.remove(tenant=tenant)
        self._g_tenant_slo_burn.remove(tenant=tenant)
        self._g_tenant_slo_target.remove(tenant=tenant)

    def ttft_totals(self) -> Tuple[float, int]:
        """Cumulative ``(seconds_sum, count)`` of the TTFT histogram —
        the rate()-able pair the fleet autoscaler differences between
        polls (what a scraper's ``rate(_sum)/rate(_count)`` computes)."""
        return self._h_ttft.sum, self._h_ttft.count

    def on_generation_end(self, n_tokens: int, seconds: float,
                          tenant: Optional[str] = None) -> None:
        """One finished request: records its tokens/sec-per-user (first
        token → last token — the per-stream decode rate, not aggregate
        throughput; a busy batch lowers it while raising the aggregate).
        ``tenant`` additionally records the multi-tenant split."""
        tps = ((n_tokens - 1) / seconds
               if n_tokens > 1 and seconds > 0 else None)
        with self._lock:
            self.generations_total += 1
            if tps is not None:
                self._tps_user.add(tps)
                self._h_tps.observe(tps)
            if tenant is not None:
                t = self._tenant(tenant)
                t["generations_total"] += 1
                if tps is not None:
                    t["_tps"].add(tps)
        if tenant is not None:
            self._c_tenant_generations.labels(tenant=tenant).inc()
            if tps is not None:
                self._h_tenant_tps.labels(tenant=tenant).observe(tps)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict:
        """The ``/stats`` dict: plain ints/floats/None only (json-ready)."""
        from ..version import __version__
        with self._lock:
            fill = (self.batch_live_rows_total / self.batch_rows_total
                    if self.batch_rows_total else None)
            return {
                # Operator context first: how long this engine has been
                # up (rate denominators, restart detection) and what
                # build produced these numbers.
                "uptime_seconds": time.monotonic() - self._t0,
                "horovod_tpu_version": __version__,
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "rejected_overload": self.rejected_overload,
                "rejected_slots_full": self.rejected_slots_full,
                "rejected_blocks_exhausted": self.rejected_blocks_exhausted,
                "rejected_tenant_quota": self.rejected_tenant_quota,
                "expired_deadline": self.expired_deadline,
                "cancelled_shutdown": self.cancelled_shutdown,
                "batches_total": self.batches_total,
                "batch_fill_ratio": fill,
                # Raw fill-ratio numerator/denominator: consumers drawing
                # per-interval curves (serve_bench) difference these —
                # the ratio alone is cumulative and smears intervals.
                "batch_rows_total": self.batch_rows_total,
                "batch_live_rows_total": self.batch_live_rows_total,
                "queue_depth": self.queue_depth,
                "latency_ms": {
                    "request_p50": self._request_ms.quantile(0.50),
                    "request_p99": self._request_ms.quantile(0.99),
                    "queue_p50": self._queue_ms.quantile(0.50),
                    "queue_p99": self._queue_ms.quantile(0.99),
                    "execute_p50": self._execute_ms.quantile(0.50),
                    "execute_p99": self._execute_ms.quantile(0.99),
                    # Generation-plane percentiles, next to the request
                    # latencies an operator already reads (None until a
                    # generation engine records into this snapshot).
                    "ttft_p50": self._ttft_ms.quantile(0.50),
                    "ttft_p99": self._ttft_ms.quantile(0.99),
                },
                "generation": {
                    "generations_total": self.generations_total,
                    "tokens_generated_total": self.tokens_generated_total,
                    "prefix_hits_total": self.prefix_hits_total,
                    "prefix_misses_total": self.prefix_misses_total,
                    "prefix_hit_blocks_total": self.prefix_hit_blocks_total,
                    "prefix_lookup_blocks_total":
                        self.prefix_lookup_blocks_total,
                    "kv_offload_blocks_total": self.kv_offload_blocks_total,
                    "kv_prefetch_blocks_total":
                        self.kv_prefetch_blocks_total,
                    "prefill_chunks_total": self.prefill_chunks_total,
                    "prefill_chunks_skipped_total":
                        self.prefill_chunks_skipped_total,
                    "preemptions_total": self.preemptions_total,
                    "preempt_resumed_total": self.preempt_resumed_total,
                    "preempt_exhausted_total":
                        self.preempt_exhausted_total,
                    "ttft_p50": self._ttft_ms.quantile(0.50),
                    "ttft_p99": self._ttft_ms.quantile(0.99),
                    "tokens_per_sec_user_p50": self._tps_user.quantile(0.50),
                    "tokens_per_sec_user_p99": self._tps_user.quantile(0.99),
                },
                # Speculation effectiveness: acceptance rate over
                # proposed drafts and the EFFECTIVE tokens-per-step
                # (>1.0 means speculation is beating one-token decode).
                "spec": {
                    "steps_total": self.spec_steps_total,
                    "draft_tokens_total": self.spec_draft_tokens_total,
                    "accepted_tokens_total":
                        self.spec_accepted_tokens_total,
                    "emitted_tokens_total":
                        self.spec_emitted_tokens_total,
                    "accept_rate": (
                        self.spec_accepted_tokens_total
                        / self.spec_draft_tokens_total
                        if self.spec_draft_tokens_total else None),
                    "tokens_per_step": (
                        self.spec_emitted_tokens_total
                        / self.spec_steps_total
                        if self.spec_steps_total else None),
                    "draft_ms_p50": self._spec_draft_ms.quantile(0.50),
                    "draft_ms_p99": self._spec_draft_ms.quantile(0.99),
                    "verify_ms_p50": self._spec_verify_ms.quantile(0.50),
                    "verify_ms_p99": self._spec_verify_ms.quantile(0.99),
                },
                # Per-tenant split (multi-tenant adapters): the latency
                # numbers a per-tenant SLO is written against. Empty dict
                # until a tenant-stamped request finishes.
                "tenants": {
                    name: {
                        "generations_total": t["generations_total"],
                        "tokens_generated_total":
                            t["tokens_generated_total"],
                        "first_tokens_total": t["first_tokens_total"],
                        "ttft_slo_miss_total": t["ttft_slo_miss_total"],
                        "deadline_miss_total": t["deadline_miss_total"],
                        "preemptions_total": t["preemptions_total"],
                        "slo_ttft_target_ms": t["slo_ttft_target_ms"],
                        "slo_burn": self._burn(t),
                        "ttft_p50": t["_ttft"].quantile(0.50),
                        "ttft_p99": t["_ttft"].quantile(0.99),
                        "tokens_per_sec_user_p50":
                            t["_tps"].quantile(0.50),
                        "tokens_per_sec_user_p99":
                            t["_tps"].quantile(0.99),
                    } for name, t in sorted(self._tenants.items())},
            }


# ---------------------------------------------------------------------------
# Prometheus exposition of the serving plane (the /metrics route).
#
# Everything /stats knows, renamed onto the stable hvd_* series inventory
# (docs/observability.md) and merged with the ServeMetrics histograms.
# The mapping is explicit, not a generic dict walker: metric names are an
# API, and a renamed snapshot key must break HERE (a KeyError in tests),
# not silently rename a series every dashboard keys on.
# ---------------------------------------------------------------------------

# snapshot key -> (series name, type, help)
_TOP = {
    "uptime_seconds": ("hvd_uptime_seconds", "gauge",
                       "Seconds since this engine's metrics started"),
    "requests_total": ("hvd_requests_total", "counter",
                       "Requests admitted to the queue"),
    "responses_total": ("hvd_responses_total", "counter",
                        "Requests answered successfully"),
    "rejected_overload": ("hvd_rejected_overload_total", "counter",
                          "Requests rejected at the door (all reasons)"),
    "expired_deadline": ("hvd_expired_deadline_total", "counter",
                         "Requests dropped at dequeue past deadline"),
    "cancelled_shutdown": ("hvd_cancelled_shutdown_total", "counter",
                           "Requests cancelled by non-drain shutdown"),
    "batches_total": ("hvd_batches_total", "counter",
                      "Device batches executed"),
    "batch_rows_total": ("hvd_batch_rows_total", "counter",
                         "Bucket slots executed (padding included)"),
    "batch_live_rows_total": ("hvd_batch_live_rows_total", "counter",
                              "Live request rows executed"),
    "batch_fill_ratio": ("hvd_batch_fill_ratio", "gauge",
                         "Live rows / executed rows (cumulative)"),
    "queue_depth": ("hvd_queue_depth", "gauge",
                    "Admission queue depth at last event"),
    "max_queue": ("hvd_max_queue", "gauge", "Admission queue capacity"),
    "max_slots": ("hvd_max_slots", "gauge", "Decode slots configured"),
    "max_len": ("hvd_max_len", "gauge", "KV positions per stream"),
    "active_slots": ("hvd_active_slots", "gauge",
                     "Streams mid-generation right now"),
    "peak_active_slots": ("hvd_peak_active_slots", "gauge",
                          "High-water concurrent streams"),
    "prefix_hit_rate": ("hvd_prefix_hit_rate", "gauge",
                        "Prefix-cache lookup hit rate"),
    "block_size": ("hvd_kv_block_size", "gauge",
                   "Tokens per KV block (paged layout)"),
    "adapters_resident": ("hvd_adapters_resident", "gauge",
                          "LoRA adapters resident in the device table"),
    "spec_k": ("hvd_spec_k", "gauge",
               "Max draft tokens per decode step (0 = speculation off)"),
}

_GENERATION = {
    "generations_total": ("hvd_generations_total", "counter",
                          "Generation streams finished"),
    "tokens_generated_total": ("hvd_tokens_generated_total", "counter",
                               "Tokens sampled across all streams"),
    "prefix_hits_total": ("hvd_prefix_hits_total", "counter",
                          "Prefix-cache lookups with >=1 resident block"),
    "prefix_misses_total": ("hvd_prefix_misses_total", "counter",
                            "Prefix-cache lookups with no resident block"),
    "prefix_hit_blocks_total": ("hvd_prefix_hit_blocks_total", "counter",
                                "Prompt blocks served from the prefix "
                                "cache"),
    "prefix_lookup_blocks_total": ("hvd_prefix_lookup_blocks_total",
                                   "counter",
                                   "Prompt blocks looked up"),
    "kv_offload_blocks_total": ("hvd_kv_offload_blocks_total", "counter",
                                "KV blocks offloaded device -> host"),
    "kv_prefetch_blocks_total": ("hvd_kv_prefetch_blocks_total", "counter",
                                 "KV blocks prefetched host -> device"),
    "prefill_chunks_total": ("hvd_prefill_chunks_total", "counter",
                             "Prefill scan chunks executed"),
    "prefill_chunks_skipped_total": ("hvd_prefill_chunks_skipped_total",
                                     "counter",
                                     "Prefill scan chunks skipped via "
                                     "prefix hits"),
    "preemptions_total": ("hvd_preemptions_total", "counter",
                          "Streams evicted from a decode slot by a "
                          "higher-priority admission"),
    "preempt_resumed_total": ("hvd_preempt_resumed_total", "counter",
                              "Preempted streams resumed "
                              "bit-identically"),
    "preempt_exhausted_total": ("hvd_preempt_exhausted_total", "counter",
                                "Preempted streams terminated on their "
                                "retry budget"),
}

_SPEC = {
    "steps_total": ("hvd_spec_steps_total", "counter",
                    "Decode steps supervised by speculation"),
    "draft_tokens_total": ("hvd_spec_draft_tokens_total", "counter",
                           "Draft tokens proposed"),
    "accepted_tokens_total": ("hvd_spec_accepted_tokens_total", "counter",
                              "Draft tokens accepted into streams"),
    "emitted_tokens_total": ("hvd_spec_emitted_tokens_total", "counter",
                             "Tokens emitted by speculation-supervised "
                             "steps"),
    "accept_rate": ("hvd_spec_accept_rate", "gauge",
                    "Accepted / proposed draft tokens (cumulative)"),
    "tokens_per_step": ("hvd_spec_tokens_per_step", "gauge",
                        "Effective tokens per decode step (cumulative)"),
}

_BLOCKS = {
    "total": ("hvd_kv_blocks_total", "gauge",
              "Usable KV blocks in the pool"),
    "free": ("hvd_kv_blocks_free", "gauge", "KV blocks free right now"),
    "used": ("hvd_kv_blocks_used", "gauge", "KV blocks allocated"),
    "registered_prefix_blocks": ("hvd_kv_prefix_registered_blocks",
                                 "gauge",
                                 "Blocks pinned by the prefix registry"),
}


class FleetMetrics:
    """The fleet plane's own series (a PRIVATE registry, same rule as
    the engines: two metric surfaces in one process must not collide).
    Three series, all under the stable-name contract of
    ``docs/observability.md``:

    * ``hvd_fleet_replicas{state=}`` — membership by state
      (``ready`` / ``warming`` / ``draining`` / ``dead``), the gauge a
      dashboard draws the fleet's size from;
    * ``hvd_fleet_dispatch_total{replica=}`` — requests routed to each
      replica (least-depth dispatch should keep these roughly level —
      a skewed split means a sick replica);
    * ``hvd_fleet_scale_events_total{direction=}`` — autoscaler
      decisions committed (``grow`` / ``shrink``), pre-seeded at 0 so
      "no event yet" is a scrapeable fact, not a missing series;
    * ``hvd_streams_stranded_total`` — streams whose serving replica
      died (or aborted) with the stream in flight;
    * ``hvd_failover_total{outcome=}`` — failover verdicts: ``resumed``
      (re-dispatched and the replayed prefix verified — the stream
      continued bit-identically) vs ``exhausted`` (failed on its whole
      retry budget, waited out the overload window, or diverged on
      replay; terminated with the ``failover_exhausted`` reason).
      Pre-seeded at 0, and deliberately NOT folded into the overload
      counters: load shedding and failover churn are different operator
      problems. NOTE: ``stranded_total`` can exceed
      ``resumed + exhausted`` — a stranded stream that meets its OWN
      verdict mid-failover (deadline expiry, fleet shutdown) is counted
      in the deadline/cancelled counters instead, not as a failover
      outcome.
    """

    def __init__(self):
        self.registry = MetricsRegistry()
        self._g_replicas = self.registry.gauge(
            "hvd_fleet_replicas", "Fleet membership by replica state",
            labels=("state",))
        self._c_dispatch = self.registry.counter(
            "hvd_fleet_dispatch_total",
            "Requests dispatched to each replica", labels=("replica",))
        self._c_scale = self.registry.counter(
            "hvd_fleet_scale_events_total",
            "Autoscaler membership changes committed",
            labels=("direction",))
        for direction in ("grow", "shrink"):
            self._c_scale.labels(direction=direction)
        self._c_stranded = self.registry.counter(
            "hvd_streams_stranded_total",
            "Streams whose replica died with the stream in flight")
        self._c_failover = self.registry.counter(
            "hvd_failover_total",
            "Stranded-stream failover outcomes", labels=("outcome",))
        for outcome in ("resumed", "exhausted"):
            self._c_failover.labels(outcome=outcome)
        # Adapter-plane series, LAZY: a fleet that never sees an adapter
        # exposes neither (the gauge registers on the first non-None
        # residency report, the counter on the first adapter dispatch).
        self._g_adapters = None
        self._c_adapter_dispatch = None
        # Prefix-affinity counter, same lazy rule: registers on the
        # first dispatch that carried a routable prefix digest.
        self._c_prefix_dispatch = None
        # Subprocess-replica gauge, LAZY too: a thread-only fleet never
        # exposes it (registers on the first nonzero count).
        self._g_procs = None
        self._replica_names: List[str] = []
        self._retired_names: set = set()
        # One lock over the dispatch-fold composite: read-value + remove
        # + re-inc in forget_replica must not interleave with an
        # on_dispatch racing a drain decision, or the raced increment is
        # dropped and the fleet dispatch total goes BACKWARDS.
        self._fold_lock = threading.Lock()

    def on_dispatch(self, replica: str) -> None:
        with self._fold_lock:
            if replica in self._retired_names:
                # The dispatch raced an eviction (submit succeeded just
                # before the replica was retired): the request WAS
                # served there — credit the retired aggregate rather
                # than resurrecting the folded named series, which
                # nothing would ever fold again.
                replica = "retired"
            if replica not in self._replica_names:
                self._replica_names.append(replica)
            self._c_dispatch.labels(replica=replica).inc()

    def forget_replica(self, name: str) -> None:
        """A replica left the membership: fold its dispatch count into
        the one ``replica="retired"`` aggregate and drop its named
        series. Replica names are never reused, so without this an
        autoscaling fleet's grow/shrink cycles would accumulate dead
        ``hvd_fleet_dispatch_total{replica=}`` children forever — the
        fold keeps the fleet-total monotone while bounding cardinality
        at live-replicas + 1."""
        with self._fold_lock:
            self._retired_names.add(name)
            if name not in self._replica_names:
                return
            count = self._c_dispatch.labels(replica=name).value
            self._c_dispatch.remove(replica=name)
            self._replica_names.remove(name)
            if count > 0:
                if "retired" not in self._replica_names:
                    self._replica_names.append("retired")
                self._c_dispatch.labels(replica="retired").inc(count)

    def set_adapters_resident(self, count: Optional[int]) -> None:
        """Refresh ``hvd_fleet_adapters_resident`` — DISTINCT adapters
        resident across the live membership (``None`` = no replica
        carries a registry; the series stays absent until one does, so
        adapter-free fleets expose nothing new)."""
        if count is None and self._g_adapters is None:
            return
        if self._g_adapters is None:
            self._g_adapters = self.registry.gauge(
                "hvd_fleet_adapters_resident",
                "Distinct LoRA adapters resident across live replicas")
        self._g_adapters.set(int(count or 0))

    def set_replica_procs(self, count: int) -> None:
        """Refresh ``hvd_fleet_replica_procs`` — live members backed by
        a subprocess worker (engines exposing a ``pid``). Lazy like the
        adapter gauge: a thread-only fleet never exposes the series, so
        its presence on a dashboard IS the topology signal."""
        if count <= 0 and self._g_procs is None:
            return
        if self._g_procs is None:
            self._g_procs = self.registry.gauge(
                "hvd_fleet_replica_procs",
                "Fleet members backed by a subprocess replica worker")
        self._g_procs.set(int(count))

    def on_adapter_dispatch(self, outcome: str) -> None:
        """One adapter-carrying dispatch:
        ``hvd_fleet_adapter_dispatch_total{outcome=}`` — ``affine``
        (the chosen replica already had the adapter resident) vs
        ``miss`` (lazy-loaded on dispatch). A rising miss share means
        the affinity plane is thrashing (table capacity too small for
        the tenant working set)."""
        if outcome not in ("affine", "miss"):
            raise ValueError(
                f"adapter dispatch outcome must be 'affine' or 'miss', "
                f"got {outcome!r}")
        if self._c_adapter_dispatch is None:
            self._c_adapter_dispatch = self.registry.counter(
                "hvd_fleet_adapter_dispatch_total",
                "Adapter-carrying dispatches by affinity outcome",
                labels=("outcome",))
            for o in ("affine", "miss"):
                self._c_adapter_dispatch.labels(outcome=o)
        self._c_adapter_dispatch.labels(outcome=outcome).inc()

    def adapter_dispatch_counts(self) -> Dict[str, int]:
        if self._c_adapter_dispatch is None:
            return {}
        return {o: int(self._c_adapter_dispatch.labels(outcome=o).value)
                for o in ("affine", "miss")}

    def on_prefix_dispatch(self, outcome: str) -> None:
        """One dispatch whose request carried a routable prefix digest:
        ``hvd_fleet_prefix_dispatch_total{outcome=}`` — ``affine`` (the
        chosen replica advertised the digest in its registry) vs
        ``miss`` (it will prefill the prefix cold). A rising miss share
        means prefix-affine routing is losing to load skew, and shared
        prompts are being recomputed across the fleet."""
        if outcome not in ("affine", "miss"):
            raise ValueError(
                f"prefix dispatch outcome must be 'affine' or 'miss', "
                f"got {outcome!r}")
        if self._c_prefix_dispatch is None:
            self._c_prefix_dispatch = self.registry.counter(
                "hvd_fleet_prefix_dispatch_total",
                "Prefix-carrying dispatches by affinity outcome",
                labels=("outcome",))
            for o in ("affine", "miss"):
                self._c_prefix_dispatch.labels(outcome=o)
        self._c_prefix_dispatch.labels(outcome=outcome).inc()

    def prefix_dispatch_counts(self) -> Dict[str, int]:
        if self._c_prefix_dispatch is None:
            return {}
        return {o: int(self._c_prefix_dispatch.labels(outcome=o).value)
                for o in ("affine", "miss")}

    def on_stranded(self, n: int = 1) -> None:
        """``n`` streams were stranded by a replica death/abort."""
        self._c_stranded.inc(n)

    def on_failover(self, outcome: str) -> None:
        """One stranded stream's terminal failover verdict: ``resumed``
        (re-dispatched, the client's stream continued bit-identically)
        or ``exhausted`` (the retry budget died — the stream terminated
        with the ``failover_exhausted`` reason, never looping)."""
        if outcome not in ("resumed", "exhausted"):
            raise ValueError(
                f"failover outcome must be 'resumed' or 'exhausted', "
                f"got {outcome!r}")
        self._c_failover.labels(outcome=outcome).inc()

    def failover_counts(self) -> Dict[str, int]:
        return {o: int(self._c_failover.labels(outcome=o).value)
                for o in ("resumed", "exhausted")}

    def stranded_count(self) -> int:
        return int(self._c_stranded.value)

    def on_scale(self, direction: str) -> None:
        if direction not in ("grow", "shrink"):
            raise ValueError(
                f"scale direction must be 'grow' or 'shrink', got "
                f"{direction!r}")
        self._c_scale.labels(direction=direction).inc()

    def set_replicas(self, counts: Dict[str, int]) -> None:
        """Refresh the membership gauge — every known state is SET
        (absent states to 0) so a shrink is visible as ready going
        down, not as a stale sample."""
        for state in ("ready", "warming", "draining", "dead"):
            self._g_replicas.labels(state=state).set(counts.get(state, 0))

    def dispatch_counts(self) -> Dict[str, int]:
        with self._fold_lock:
            return {name: int(self._c_dispatch.labels(replica=name).value)
                    for name in self._replica_names}

    def scale_counts(self) -> Dict[str, int]:
        return {d: int(self._c_scale.labels(direction=d).value)
                for d in ("grow", "shrink")}


def collect_stats(snap: Dict, registry: MetricsRegistry,
                  engine: str) -> Tuple[Meta, List[Sample]]:
    """One engine's ``(meta, samples)`` for the exposition renderer:
    the ``/stats`` snapshot mapped onto the stable series names, the
    rejection split as a labeled counter, the build info, and the
    registry's histograms — every sample carrying ``engine=<label>`` so
    two engines merge into one valid scrape."""
    labels = {"engine": engine}
    meta: Meta = {}
    samples: List[Sample] = []

    def _emit(table: Dict, src: Dict) -> None:
        for key, (name, typ, help_) in table.items():
            v = src.get(key)
            if v is None or isinstance(v, bool) or not isinstance(
                    v, (int, float)):
                continue
            meta[name] = (typ, help_)
            samples.append((name, dict(labels), float(v)))

    _emit(_TOP, snap)
    _emit(_GENERATION, snap.get("generation") or {})
    _emit(_SPEC, snap.get("spec") or {})
    blocks_src = snap.get("blocks") or {}
    _emit(_BLOCKS, blocks_src)
    # Tier-labeled split of the same block gauges: the unlabeled series
    # above stay the device pool (the pinned legacy meaning); tier=
    # samples account for EVERY block across the memory hierarchy, so
    # device + host sums match the configured capacities exactly.
    for short, (tiers) in (("total", ("total", "host_total")),
                           ("free", ("free", "host_free")),
                           ("used", ("used", "host_used"))):
        dev_key, host_key = tiers
        if host_key not in blocks_src:
            continue
        name, _typ, _help = _BLOCKS[dev_key]
        for tier, key in (("device", dev_key), ("host", host_key)):
            v = blocks_src.get(key)
            if v is None or isinstance(v, bool) or not isinstance(
                    v, (int, float)):
                continue
            samples.append((name, {**labels, "tier": tier}, float(v)))
    meta["hvd_rejected_total"] = (
        "counter", "Door rejections split by the scarce resource")
    for reason_key, reason in (("rejected_slots_full", "slots_full"),
                               ("rejected_blocks_exhausted",
                                "blocks_exhausted"),
                               ("rejected_tenant_quota", "tenant_quota")):
        if reason_key in snap:
            samples.append(("hvd_rejected_total",
                            {**labels, "reason": reason},
                            float(snap[reason_key])))
    version = snap.get("horovod_tpu_version")
    if version:
        meta["hvd_build_info"] = (
            "gauge", "Constant 1, labeled with the serving build")
        samples.append(("hvd_build_info",
                        {**labels, "version": str(version)}, 1.0))
    h_meta, h_samples = registry.collect(const_labels=labels)
    meta.update(h_meta)
    samples.extend(h_samples)
    return meta, samples
