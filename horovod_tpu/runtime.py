"""World bootstrap and process API: ``init / shutdown / size / rank / local_rank``.

Reference parity
----------------
* ``hvd.init()`` → ``InitializeHorovodOnce`` (``mpi_ops.cc:1516-1527``):
  idempotent via an atomic flag, spawns the background runtime, and the caller
  waits until initialization is done. Here, ``init()`` is idempotent under a
  lock, builds the global device **mesh** (the TPU-native "world"), and —
  in multi-process mode — starts the host coordination client (DCN control
  plane), the analog of the reference's background MPI thread
  (``BackgroundThreadLoop``, ``mpi_ops.cc:1248-1512``).
* ``size()/rank()/local_rank()`` → C ABI ``horovod_tensorflow_{size,rank,
  local_rank}`` (``mpi_ops.cc:1539-1566``), raising when uninitialized
  (``mpi_ops.py:80-124``).

TPU-native design
-----------------
Horovod's world is "1 MPI process = 1 GPU" (``README.md:62-64``). The
TPU-native world is a 1-D ``jax.sharding.Mesh`` over every chip of the slice,
with axis name ``"hvd"``:

* ``size()``  = number of chips in the mesh (== MPI world size).
* ``rank()``  = chip index. Inside compiled code (``shard_map`` over the mesh)
  this is ``lax.axis_index('hvd')`` — a per-chip value, exactly Horovod's
  per-process rank. Outside compiled code, a controller process "speaks for"
  its local chips and ``rank()`` returns the global index of its first local
  chip (so launched one-process-per-chip by ``tpurun``, it equals the MPI
  rank; single-controller, it is 0).
* ``local_rank()`` = index of the chip among chips on the same host — the
  analog of ``MPI_Comm_split_type(MPI_COMM_TYPE_SHARED)`` rank
  (``mpi_ops.cc:1263-1267``) — derived from launcher env or the process's
  local device list.

Multi-host: when the launcher has set up ``jax.distributed``, ``jax.devices()``
spans every process, compiled collectives ride ICI/DCN automatically, and the
mesh is global. No NCCL-style communicator bootstrap is needed: ICI collectives
are compiler-scheduled (SURVEY §2.5).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .exceptions import NotInitializedError
from .utils import config as _config

# The world axis name. Every collective in this framework reduces over it.
AXIS: str = "hvd"


@dataclasses.dataclass(frozen=True)
class World:
    """Global state (parity: ``HorovodGlobalState``, ``mpi_ops.cc:132-216``).

    Unlike the reference — whose global state carries a tensor table, message
    queue and CUDA stream pool — the compiled data plane needs only the mesh;
    the eager control plane (coordination client, timeline) hangs off this
    object when enabled.
    """

    mesh: Mesh
    size: int
    controller_rank: int        # global index of this process's first device
    local_rank: int
    process_index: int
    process_count: int
    coord: Any = None           # coordination client (multi-process eager plane)
    timeline: Any = None        # Timeline writer (rank 0 only)
    env_world: bool = False     # tpurun env-world (independent JAX processes)


_lock = threading.Lock()
_world: Optional[World] = None
# Monotonic world generation — bumped on every init(); used (instead of
# object identity, which can be reused after GC) to key caches of compiled
# collective executables across shutdown/re-init cycles.
_generation = 0
# Per-rank metrics HTTP listener (HVD_METRICS_PORT; horovod_tpu.obs.http).
# Module-level, not a World field: it must survive the frozen dataclass
# and be restartable across the shutdown/re-init cycle a live resize runs.
_metrics_listener = None


def init(devices: Optional[Sequence[jax.Device]] = None,
         *,
         coordinator: bool | None = None) -> World:
    """Initialize the world. Idempotent (parity: ``mpi_ops.cc:1516-1527``).

    Args:
      devices: explicit device list forming the world (defaults to every
        device visible to JAX — all chips of the slice across processes).
      coordinator: force-enable/disable the host coordination service for the
        eager op-at-a-time path. Default: enabled iff multi-process.
    """
    global _world, _generation
    with _lock:
        if _world is not None:
            return _world
        _generation += 1

        _maybe_init_jax_distributed()
        devs = list(devices) if devices is not None else list(jax.devices())
        mesh = Mesh(np.array(devs), (AXIS,))
        size = len(devs)

        process_index = jax.process_index()
        process_count = jax.process_count()

        # tpurun env-world: one *independent* JAX process per chip (the
        # reference's "1 MPI process = 1 GPU" model, README.md:62-64) —
        # jax.distributed is not set up, rank/size come from launcher env
        # and ALL cross-rank collectives ride the host coordination plane.
        env_size = _config.launcher_size(default=1)
        env_world = process_count == 1 and env_size > 1 and devices is None
        if env_world:
            size = env_size
            process_index = _config.launcher_rank(default=0)
            process_count = env_size
            controller_rank = process_index
            # 1 process = 1 chip (README.md:62-64): the local mesh is this
            # rank's own device; cross-rank exchange rides the host plane.
            local = jax.local_devices()
            own = local[_config.launcher_local_rank(default=0) % len(local)]
            mesh = Mesh(np.array([own]), (AXIS,))
        else:
            # Controller rank: global index of the first device owned by
            # this process (jax.distributed multi-host, or single
            # controller). One-process-per-chip → the MPI-style rank.
            controller_rank = 0
            for i, d in enumerate(devs):
                if d.process_index == process_index:
                    controller_rank = i
                    break

        local_rank = _config.launcher_local_rank(default=_infer_local_rank(devs, process_index))

        coord = None
        if coordinator is None:
            coordinator = process_count > 1
        elif coordinator and process_count == 1:
            raise ValueError(
                "init(coordinator=True) requires a multi-process world; "
                "single-controller mode has no cross-process negotiation "
                "to coordinate")

        timeline = None
        tl_path = _config.timeline_path()
        if tl_path and controller_rank == 0 and not coordinator:
            # Single-controller: Python writes the timeline. In coord mode
            # the native coordinator owns the file (coordinator.cc Timeline)
            # — opening it here too would corrupt it.
            from .utils.timeline import Timeline
            timeline = Timeline(tl_path)

        if coordinator and process_count > 1:
            from .coord.client import CoordClient
            coord = CoordClient.from_env(
                rank=process_index, size=process_count, timeline=timeline)

        _world = World(
            mesh=mesh,
            size=size,
            controller_rank=controller_rank,
            local_rank=local_rank,
            process_index=process_index,
            process_count=process_count,
            coord=coord,
            timeline=timeline,
            env_world=env_world,
        )
        _start_observability(_world)
        return _world


def _start_observability(w: World) -> None:
    """Bring the telemetry plane up for this world: the per-rank
    ``/metrics`` listener (HVD_METRICS_PORT; no-op when unset), the
    world-shape gauges every scrape carries, the fatal-signal
    flight-recorder dump, and the init event itself. Failures here warn
    — telemetry must never kill a training job."""
    global _metrics_listener
    from .obs import flightrec, http as _obs_http
    from .obs.registry import registry as _registry_fn
    try:
        flightrec.install_signal_dump()
        flightrec.record("init", rank=w.process_index, world=w.size,
                         env_world=w.env_world)
        reg = _registry_fn()
        reg.gauge("hvd_world_size",
                  "Number of ranks (chips) in the world").set(w.size)
        reg.gauge("hvd_rank", "This process's rank").set(w.process_index)
        if _metrics_listener is None:
            _metrics_listener = _obs_http.start_from_env(w.process_index)
        if w.timeline is not None:
            # A killed rank's chrome trace should survive alongside its
            # flight record (utils/timeline.py registers its own atexit
            # close; this covers the fatal-signal path).
            flightrec.add_crash_hook(w.timeline.flush)
    except Exception as e:  # noqa: BLE001 — observability is best-effort
        import warnings
        warnings.warn(f"observability startup failed: {e!r} — the world "
                      f"runs without a metrics listener")


def _maybe_init_jax_distributed() -> None:
    """Form the jax.distributed world from tpurun's env when requested.

    tpurun --jax-distributed exports JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID; ``jax.distributed.initialize``
    needs them passed explicitly. Idempotent; silently skipped if the
    world is already up or the env is absent.
    """
    import os
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if not (addr and nproc and pid):
        return
    # NB: do NOT probe jax.process_count() here — it would initialize the
    # backend single-process and make distributed init impossible.
    from .utils.compat import jax_distributed_is_initialized
    if jax_distributed_is_initialized():
        return
    try:
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=int(nproc),
                                   process_id=int(pid))
    except RuntimeError as e:
        # Only tolerate "backend already initialized" (the user touched
        # devices before init() — distributed formation is impossible but
        # single-process still works). A coordinator-connection failure
        # must NOT be swallowed: proceeding would silently train without
        # gradient exchange.
        if "already" in str(e).lower():
            import warnings
            warnings.warn(
                "jax backend was initialized before hvd.init(); the "
                "jax.distributed world requested by the launcher could not "
                "be formed — compiled collectives will not span processes "
                f"({e})")
        else:
            raise


def _infer_local_rank(devs: Sequence[jax.Device], process_index: int) -> int:
    """Chips-per-host index (parity: shared-comm split, mpi_ops.cc:1263-1267)."""
    try:
        first_local = next(d for d in devs if d.process_index == process_index)
    except StopIteration:
        return 0
    lid = getattr(first_local, "local_hardware_id", None)
    if lid is not None and lid >= 0:
        return int(lid)
    return 0


def shutdown(error: Optional[BaseException] = None) -> None:
    """Tear the world down (parity: ``HorovodGlobalState`` destructor →
    SHUTDOWN broadcast → ``MPI_Finalize``; ``mpi_ops.cc:207-215, 1437-1447,
    1511``). Safe to call multiple times.

    ``error=`` marks this teardown as a FAILURE path: the flight
    recorder's ring is dumped to ``hvd_flightrec.rank{N}.json`` before
    anything else is torn down, so the rank leaves a post-mortem naming
    its last completed step (:mod:`horovod_tpu.obs.flightrec`).
    :func:`horovod_tpu.elastic.run_with_recovery` routes every
    recoverable world failure through here.
    """
    global _world, _metrics_listener
    if error is not None:
        from .obs import flightrec
        flightrec.record("shutdown_error", error=repr(error))
        flightrec.dump(reason=f"runtime.shutdown(error={error!r})")
        flightrec.run_crash_hooks()
    with _lock:
        if _world is None:
            return
        if _metrics_listener is not None:
            try:
                _metrics_listener.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
            _metrics_listener = None
        if _world.timeline is not None:
            from .obs import flightrec
            flightrec.remove_crash_hook(_world.timeline.flush)
        if _world.coord is not None:
            try:
                _world.coord.shutdown()
            except Exception as e:  # noqa: BLE001 — teardown must finish
                # Crash-safe teardown: a dead coordinator (worker failure,
                # aborted world) must not wedge the rest of the teardown —
                # the timeline close and world reset below still run, so a
                # supervised restart starts from a clean slate.
                import warnings
                warnings.warn(
                    f"coordination-plane shutdown failed (coordinator "
                    f"already dead?): {e!r} — continuing world teardown")
        if _world.timeline is not None:
            try:
                _world.timeline.close()
            except Exception as e:  # noqa: BLE001
                import warnings
                warnings.warn(f"timeline close failed: {e!r} — continuing "
                              f"world teardown")
        _world = None
        # Drop compiled eager-collective executables from the dead world —
        # their cache keys (generation) can never hit again.
        from .ops import collectives as _c
        _c._eager_fn.cache_clear()


def is_initialized() -> bool:
    return _world is not None


def world() -> World:
    if _world is None:
        raise NotInitializedError()
    return _world


def mesh() -> Mesh:
    """The world mesh. Collectives reduce over its ``"hvd"`` axis."""
    return world().mesh


def size() -> int:
    """World size = number of chips (parity: ``horovod_tensorflow_size``,
    ``mpi_ops.cc:1560-1566``)."""
    return world().size


def _in_world_trace() -> bool:
    """True when called under a trace with the ``hvd`` axis bound
    (i.e. inside ``shard_map`` over the world mesh)."""
    try:
        jax.lax.axis_index(AXIS)
        return True
    except NameError:
        return False
    except Exception:
        return False


def rank():
    """This rank's index in [0, size).

    Inside compiled code over the world mesh → per-chip ``lax.axis_index``
    (a traced value). Outside → the controller's first local chip index
    (parity: ``horovod_tensorflow_rank``, ``mpi_ops.cc:1546-1552``).
    """
    w = world()
    if _in_world_trace():
        return jax.lax.axis_index(AXIS)
    return w.controller_rank


def local_rank() -> int:
    """Index of this chip among chips on the same host (parity:
    ``horovod_tensorflow_local_rank``, ``mpi_ops.cc:1553-1559``)."""
    return world().local_rank


def process_index() -> int:
    return world().process_index


def process_count() -> int:
    return world().process_count


# ---------------------------------------------------------------------------
# Sharding helpers used across the framework.
# ---------------------------------------------------------------------------

def replicated_sharding() -> NamedSharding:
    return NamedSharding(mesh(), P())


def ranked_sharding() -> NamedSharding:
    """Leading axis split one-slice-per-rank over the world axis."""
    return NamedSharding(mesh(), P(AXIS))
