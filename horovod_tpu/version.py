"""Package version.

Reference parity: ``horovod/__init__.py:1`` (``__version__ = '0.11.2'``).
This framework re-implements that capability surface TPU-natively.
"""

__version__ = "0.1.0"
