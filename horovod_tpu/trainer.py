"""Trainer: the ``model.fit`` analog driving the compiled step + callbacks.

Parity: the reference's training loops are Keras ``model.fit`` with Horovod
callbacks (``examples/keras_mnist_advanced.py:80-110``) or raw
``MonitoredTrainingSession`` loops (``examples/tensorflow_mnist.py:99-119``).
This Trainer is the thin host-side loop around the jitted SPMD train step:
epochs × steps, invoking :mod:`horovod_tpu.callbacks` hooks, rank-0-only
verbosity (``keras_imagenet_resnet50.py:59`` convention), and rank-0-only
checkpointing (SURVEY §5.4) via orbax.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import numpy as np

from . import runtime
from .testing import faults as _faults
from .training import TrainState, shard_batch


class Trainer:
    """Host training loop; owns the mutable ``state`` that callbacks adjust."""

    def __init__(self, train_step: Callable, state: TrainState,
                 *, eval_step: Optional[Callable] = None,
                 steps_per_epoch: Optional[int] = None,
                 verbose: Optional[bool] = None,
                 prefetch: int = 2):
        self.train_step = train_step
        self.eval_step = eval_step
        self.state = state
        self.steps_per_epoch = steps_per_epoch
        if verbose is None:
            verbose = (not runtime.is_initialized()
                       or runtime.world().controller_rank == 0)
        self.verbose = verbose
        # Background input staging depth (0 disables): keeps `prefetch`
        # sharded batches ahead of the step so chips never wait on host
        # input (see horovod_tpu.data).
        self.prefetch = prefetch
        self.history: List[Dict[str, float]] = []
        # Global step counter across epochs — drives the deterministic
        # fault-injection hook (testing/faults.py; no-op in production).
        self._global_step = 0

    def _stream(self, data: Iterable):
        from .data import prefetch_to_device, shard_iterator
        if self.prefetch and self.prefetch > 0:
            return prefetch_to_device(shard_iterator(data), self.prefetch)
        return shard_iterator(data)

    def fit(self, data: Callable[[], Iterable], epochs: int = 1,
            callbacks: Optional[List] = None,
            eval_data: Optional[Callable[[], Iterable]] = None,
            initial_epoch: int = 0):
        """Run the training loop.

        Args:
          data: zero-arg callable returning a fresh per-epoch iterable of
            ``(inputs, labels)`` host batches (global batch; sharded here).
          epochs: final epoch (exclusive).
          callbacks: list of :class:`horovod_tpu.callbacks.Callback`.
          eval_data: optional eval-batch iterable factory, run at epoch end.
          initial_epoch: first epoch — nonzero after checkpoint resume (the
            reference broadcasts the resume epoch from rank 0,
            ``keras_imagenet_resnet50.py:47-56``).
        """
        callbacks = list(callbacks or [])
        for cb in callbacks:
            cb.set_trainer(self)

        for cb in callbacks:
            cb.on_train_begin()
        for epoch in range(initial_epoch, epochs):
            t0 = time.perf_counter()
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            nsteps = 0
            epoch_metrics: List[Dict[str, Any]] = []
            stream = self._stream(data())
            try:
                for batch_idx, batch in enumerate(stream):
                    if self.steps_per_epoch is not None \
                            and batch_idx >= self.steps_per_epoch:
                        break
                    for cb in callbacks:
                        cb.on_batch_begin(batch_idx)
                    self.state, metrics = self.train_step(self.state, batch)
                    epoch_metrics.append(metrics)
                    for cb in callbacks:
                        cb.on_batch_end(batch_idx)
                    nsteps += 1
                    _faults.step_hook(self._global_step)
                    self._global_step += 1
            finally:
                close = getattr(stream, "close", None)
                if close is not None:
                    close()
            if self.steps_per_epoch is None:
                self.steps_per_epoch = nsteps

            # Epoch logs are the running mean over the epoch's batches (the
            # Keras fit semantics the reference callbacks assume), not the
            # last batch — ReduceLROnPlateau/MetricAverage need a stable
            # signal, not one noisy step.
            logs: Dict[str, float] = {}
            if epoch_metrics:
                for k in epoch_metrics[0]:
                    logs[k] = float(np.mean(
                        [np.asarray(m[k]) for m in epoch_metrics]))
            if eval_data is not None and self.eval_step is not None:
                evals = []
                for b in eval_data():
                    rows = int(np.shape(
                        jax.tree_util.tree_leaves(b)[0])[0])
                    evals.append((rows, self.eval_step(self.state,
                                                       shard_batch(b))))
                if evals:  # the eval iterable can be empty at large world sizes
                    total = sum(r for r, _ in evals)
                    for k in evals[0][1]:
                        logs[f"val_{k}"] = float(sum(
                            r * np.asarray(e[k]) for r, e in evals) / total)
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            self.history.append(logs)
            if self.verbose:
                dt = time.perf_counter() - t0
                msg = " ".join(f"{k}={v:.4f}" for k, v in logs.items())
                print(f"epoch {epoch + 1}/{epochs} [{dt:.1f}s, "
                      f"{nsteps} steps] {msg}")
        for cb in callbacks:
            cb.on_train_end()
        return self.history


# ---------------------------------------------------------------------------
# Checkpoint / resume — rank-0-only write + broadcast-on-restore (SURVEY §5.4).
# ---------------------------------------------------------------------------

def save_checkpoint(directory: str, state: TrainState,
                    step: Optional[int] = None,
                    max_to_keep: Optional[int] = None) -> Optional[str]:
    """Write a checkpoint — rank 0 only, like the reference
    (``checkpoint_dir=None`` on other ranks, ``README.md:78-80``).
    Returns the path written, or None on non-root ranks.

    ``max_to_keep``: after a successful write, delete the oldest
    checkpoints beyond the newest ``max_to_keep`` (retention is the
    writer's job since only rank 0 touches the directory).
    """
    if runtime.is_initialized() and runtime.world().controller_rank != 0:
        return None
    import orbax.checkpoint as ocp
    step = int(state.step) if step is None else step
    path = os.path.join(os.path.abspath(directory), f"ckpt_{step}")
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, jax.tree_util.tree_map(np.asarray, state), force=True)
    apply_retention(directory, path, max_to_keep)
    return path


def apply_retention(directory: str, just_written: str,
                    max_to_keep: Optional[int]) -> None:
    """Delete the oldest checkpoints beyond the newest ``max_to_keep``.

    Retention by WRITE recency, not step number: a run resumed from a
    rolled-back step must never have its just-written checkpoint deleted
    in favor of stale higher-step leftovers. Shared by the replicated-DP
    writer above and the sharded writer
    (:mod:`horovod_tpu.parallel.checkpoint`) — one policy, one bug
    surface.
    """
    if max_to_keep is None or max_to_keep <= 0:
        return
    import shutil
    base = os.path.abspath(directory)
    entries = []
    for n in os.listdir(base):
        if _step_of(n) is None:
            continue
        full = os.path.join(base, n)
        try:
            entries.append((os.path.getmtime(full), full))
        except OSError:
            continue
    entries.sort()
    for _, old in entries[:-max_to_keep]:
        if old != just_written:
            shutil.rmtree(old, ignore_errors=True)


def _step_of(name: str) -> Optional[int]:
    if not name.startswith("ckpt_"):
        return None
    try:
        return int(name.split("_", 1)[1])
    except ValueError:
        return None


def latest_checkpoint_step(directory: str) -> Optional[int]:
    """Find the newest checkpoint's step (the resume scan rank 0 performs
    before broadcasting the epoch, ``keras_imagenet_resnet50.py:47-56``)."""
    if not os.path.isdir(directory):
        return None
    steps = [s for s in (_step_of(n) for n in os.listdir(directory))
             if s is not None]
    return max(steps) if steps else None


def restore_checkpoint(directory: str, state: TrainState,
                       step: Optional[int] = None) -> TrainState:
    """Restore (on every rank, from the shared filesystem) then broadcast
    from rank 0 so all ranks are bit-identical — the reference's
    load-on-rank-0 + ``BroadcastGlobalVariablesCallback`` protocol
    (``keras_imagenet_resnet50.py:130-133``)."""
    import orbax.checkpoint as ocp
    from .optimizer import broadcast_global_variables
    if step is None:
        step = latest_checkpoint_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), f"ckpt_{step}")
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(
        path, item=jax.tree_util.tree_map(np.asarray, state))
    if runtime.is_initialized() and runtime.size() > 1:
        restored = broadcast_global_variables(restored, root_rank=0)
    return restored
