"""Trainer: the ``model.fit`` analog driving the compiled step + callbacks.

Parity: the reference's training loops are Keras ``model.fit`` with Horovod
callbacks (``examples/keras_mnist_advanced.py:80-110``) or raw
``MonitoredTrainingSession`` loops (``examples/tensorflow_mnist.py:99-119``).
This Trainer is the thin host-side loop around the jitted SPMD train step:
epochs × steps, invoking :mod:`horovod_tpu.callbacks` hooks, rank-0-only
verbosity (``keras_imagenet_resnet50.py:59`` convention), and rank-0-only
checkpointing (SURVEY §5.4) via orbax.
"""

from __future__ import annotations

import atexit
import dataclasses
import os
import queue
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import runtime
from .obs import flightrec as _flightrec
from .obs.registry import registry as _metrics_registry
from .testing import faults as _faults
from .training import TrainState, make_batch_placer, shard_batch
from .utils import timeline as _timeline


class Trainer:
    """Host training loop; owns the mutable ``state`` that callbacks adjust."""

    def __init__(self, train_step: Callable, state: TrainState,
                 *, eval_step: Optional[Callable] = None,
                 steps_per_epoch: Optional[int] = None,
                 verbose: Optional[bool] = None,
                 prefetch: int = 2,
                 max_bad_steps: Optional[int] = None,
                 elastic: Any = None,
                 resize: Any = None):
        self.train_step = train_step
        self.eval_step = eval_step
        self.state = state
        self.steps_per_epoch = steps_per_epoch
        if verbose is None:
            verbose = (not runtime.is_initialized()
                       or runtime.world().controller_rank == 0)
        self.verbose = verbose
        # Background input staging depth (0 disables): keeps `prefetch`
        # sharded batches ahead of the step so chips never wait on host
        # input (see horovod_tpu.data).
        self.prefetch = prefetch
        self.history: List[Dict[str, float]] = []
        # Global step counter across epochs — drives the deterministic
        # fault-injection hook (testing/faults.py; no-op in production).
        self._global_step = 0
        # Device-resident running-metric reducer (built lazily): epoch logs
        # come from one (sums, count) accumulator updated per step, not an
        # O(steps) host list of device arrays fetched in a storm at epoch
        # end. The add is a tiny jitted program so the step loop never
        # synchronizes on a metric value.
        self._metric_add = None
        self._eval_placer: Optional[Callable] = None
        # Bad-step containment (active only when the train step was built
        # with guard_nonfinite and emits the ``bad_step`` metric): a
        # device-resident consecutive-skip counter, the budget beyond
        # which a NaN storm stops being "transient" (HVD_MAX_BAD_STEPS),
        # and optionally an ElasticState to roll back onto — its verified
        # fallback walk guarantees the rollback target's bytes are good.
        from .utils import config as _config
        self.max_bad_steps = (_config.max_bad_steps()
                              if max_bad_steps is None
                              else max(1, int(max_bad_steps)))
        self.elastic = elastic
        # Live-resize quiesce hook (horovod_tpu.elastic.ResizeCoordinator):
        # polled once per completed step — one atomic load on the hot path.
        # When a resize executes, the current epoch ends early (its input
        # stream was sharded for the OLD world) and the next epoch runs on
        # the re-formed world with the rebuilt train step.
        self.resize = resize
        self._bad_counter = None
        self._bad_add = None
        # Hot-path metrics (horovod_tpu.obs): registered once here — a
        # per-step registry lookup would be dict hashing on the hot loop
        # for nothing. Names are API (docs/observability.md).
        reg = _metrics_registry()
        self._m_steps = reg.counter(
            "hvd_steps_total",
            "Train steps completed by this rank's loop (skipped "
            "bad steps included — they consumed a batch)")
        self._m_step_seconds = reg.histogram(
            "hvd_step_seconds",
            "Per-step wall time: input wait + dispatch + host-side work "
            "between consecutive step completions")
        self._m_samples = reg.counter(
            "hvd_samples_total",
            "Training examples consumed (leading batch-axis rows seen "
            "by this process's loop)")
        self._m_bad = reg.counter(
            "hvd_bad_steps_total",
            "Steps skipped by the non-finite gradient guard")
        self._m_epochs = reg.counter("hvd_epochs_total",
                                     "Epochs completed")
        self._m_gstep = reg.gauge(
            "hvd_global_step",
            "Global step counter (across epochs and restarts of this "
            "process)")

    def _stream(self, data: Iterable):
        from .data import prefetch_to_device, shard_iterator
        if self.prefetch and self.prefetch > 0:
            if runtime.is_initialized() and not runtime.world().env_world:
                # Hand the prefetch thread the world sharding so the
                # host→device copy of batch k+1 overlaps step k on the
                # device, instead of happening synchronously at next().
                return prefetch_to_device(
                    iter(data), self.prefetch,
                    sharding=runtime.ranked_sharding())
            return prefetch_to_device(shard_iterator(data), self.prefetch)
        return shard_iterator(data)

    # -- running metrics (device-resident, fetched once per epoch) ---------

    def _accumulate_metrics(self, sums, metrics):
        if sums is None:
            for k in metrics:
                for leaf in jax.tree_util.tree_leaves(metrics[k]):
                    if np.ndim(leaf) != 0:
                        raise ValueError(
                            f"train-step metric {k!r} has shape "
                            f"{np.shape(leaf)}; metrics_fn must return "
                            f"scalar leaves (reduce to a per-batch mean "
                            f"before returning) — a non-scalar here would "
                            f"silently broadcast into the epoch mean")
            sums = jax.tree_util.tree_map(
                lambda x: jnp.zeros((), jnp.float32), metrics)
        if self._metric_add is None:
            self._metric_add = jax.jit(lambda acc, m: jax.tree_util.tree_map(
                lambda a, x: a + jnp.asarray(x, jnp.float32), acc, m))
        return self._metric_add(sums, metrics)

    # -- bad-step containment (guard_nonfinite train steps) ----------------

    def _track_bad_step(self, bad_flag) -> bool:
        """Fold this step's ``bad_step`` flag into the device-resident
        consecutive-skip counter; returns True when the step was skipped.
        The ``int()`` fetch of one scalar per step is the whole host-side
        cost of containment. Exceeding ``max_bad_steps`` consecutive
        skips triggers :meth:`_contain` (rollback or raise)."""
        if self._bad_add is None:
            self._bad_add = jax.jit(
                lambda c, b: jnp.where(jnp.asarray(b) > 0, c + 1,
                                       jnp.zeros_like(c)))
            self._bad_counter = jnp.zeros((), jnp.int32)
        self._bad_counter = self._bad_add(self._bad_counter, bad_flag)
        consec = int(self._bad_counter)
        if consec == 0:
            return False
        tl = runtime.world().timeline if runtime.is_initialized() else None
        with _timeline.maybe_op(tl, "train.guard", _timeline.BAD_STEP):
            pass  # instantaneous marker: this step was skipped
        self._m_bad.inc()
        _flightrec.record("bad_step", step=self._global_step,
                          consecutive=consec)
        if self.verbose:
            print(f"[trainer] non-finite gradients at global step "
                  f"{self._global_step}: update skipped "
                  f"({consec}/{self.max_bad_steps} consecutive)",
                  file=sys.stderr, flush=True)
        if consec >= self.max_bad_steps:
            self._contain(consec)
        return True

    def _contain(self, consec: int) -> None:
        """The bad-step budget is exhausted: the params (or the data
        pipeline feeding them) are presumed poisoned beyond what
        skip-steps can absorb. With an attached
        :class:`~horovod_tpu.elastic.ElasticState`, roll back to the last
        checkpoint that PASSES integrity verification (the fallback walk)
        and keep training; without one, raise
        :class:`~horovod_tpu.exceptions.NonFiniteGradError` — skipping
        forever would burn the reservation training nothing."""
        from .exceptions import NonFiniteGradError
        if self.elastic is None:
            raise NonFiniteGradError(
                f"{consec} consecutive non-finite-gradient steps at "
                f"global step {self._global_step} and no elastic state "
                f"to roll back to — a persistent NaN source (bad data "
                f"shard, broken loss scale, flaky chip) will not fix "
                f"itself. Attach Trainer(elastic=ElasticState(...)) for "
                f"automatic rollback, or raise HVD_MAX_BAD_STEPS if "
                f"longer transients are expected")
        es = self.elastic
        # Current trees as restore templates (structure + sharding); the
        # restore overwrites every value from the verified checkpoint.
        es.params, es.opt_state = self.state.params, self.state.opt_state
        try:
            es.restore()   # latest_committed's walk skips corrupt steps
        except FileNotFoundError as e:
            # Elastic attached but nothing committed (or every commit
            # corrupt): same terminal diagnosis as the no-elastic branch
            # — a filesystem error would send the user hunting paths
            # instead of the NaN source.
            raise NonFiniteGradError(
                f"{consec} consecutive non-finite-gradient steps at "
                f"global step {self._global_step} and no verified "
                f"committed checkpoint to roll back to ({e}) — commit "
                f"via ElasticState before the storm, or fix the NaN "
                f"source (bad data shard, broken loss scale, flaky "
                f"chip)") from e
        self.state = dataclasses.replace(
            self.state, params=es.params, opt_state=es.opt_state,
            step=jnp.asarray(es.step, self.state.step.dtype))
        self._bad_counter = jnp.zeros((), jnp.int32)
        _flightrec.record("rollback", step=es.step,
                          consecutive_bad=consec)
        if self.verbose:
            print(f"[trainer] bad-step budget exhausted ({consec} "
                  f"consecutive skips) — rolled back to verified "
                  f"elastic step {es.step}", file=sys.stderr, flush=True)

    def _maybe_resize(self) -> bool:
        """The step-boundary quiesce hook of the live-resize plane: sync
        the live trees into the elastic state, let the
        :class:`~horovod_tpu.elastic.ResizeCoordinator` poll (one atomic
        load when nothing is pending) and — once the world-wide quiesce
        step is reached — execute the in-place resize. Returns True when
        the world was just re-formed (the caller must abandon the current
        epoch's input stream)."""
        import numpy as np
        step = int(self.state.step)
        rc = self.resize
        req = rc.poll(step)
        if req is None or not rc.due(step):
            return False
        # batch_stats are not part of the committed elastic state; carry
        # them across the re-form host-side (re-placed replicated — the
        # rebuild's train step re-shards them on first use if it must).
        host_bs = None
        if self.state.batch_stats is not None:
            host_bs = jax.tree_util.tree_map(np.asarray,
                                             self.state.batch_stats)
        rebuilt = rc.step_boundary(step, params=self.state.params,
                                   opt_state=self.state.opt_state)
        if rebuilt is None:
            return False
        new_bs = None
        if host_bs is not None:
            new_bs = jax.tree_util.tree_map(jnp.asarray, host_bs)
        self.state = dataclasses.replace(
            self.state, params=rc.state.params,
            opt_state=rc.state.opt_state, batch_stats=new_bs,
            step=jnp.asarray(rc.state.step, self.state.step.dtype))
        if rebuilt.train_step is not None:
            self.train_step = rebuilt.train_step
        _flightrec.record("resize_executed", step=int(self.state.step),
                          world=runtime.size()
                          if runtime.is_initialized() else None)
        # Mesh-tied host-side caches die with the old world.
        self._eval_placer = None
        self._metric_add = None
        self._bad_add = None
        self._bad_counter = None
        if self.verbose:
            print(f"[trainer] live resize executed at step "
                  f"{int(self.state.step)}; epoch ends early, training "
                  f"resumes on the new world", file=sys.stderr, flush=True)
        return True

    def fit(self, data: Callable[[], Iterable], epochs: int = 1,
            callbacks: Optional[List] = None,
            eval_data: Optional[Callable[[], Iterable]] = None,
            initial_epoch: int = 0):
        """Run the training loop.

        Args:
          data: zero-arg callable returning a fresh per-epoch iterable of
            ``(inputs, labels)`` host batches (global batch; sharded here).
          epochs: final epoch (exclusive).
          callbacks: list of :class:`horovod_tpu.callbacks.Callback`.
          eval_data: optional eval-batch iterable factory, run at epoch end.
          initial_epoch: first epoch — nonzero after checkpoint resume (the
            reference broadcasts the resume epoch from rank 0,
            ``keras_imagenet_resnet50.py:47-56``).
        """
        callbacks = list(callbacks or [])
        for cb in callbacks:
            cb.set_trainer(self)

        for cb in callbacks:
            cb.on_train_begin()
        for epoch in range(initial_epoch, epochs):
            t0 = time.perf_counter()
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            nsteps = 0
            bad_steps = 0
            guard_active = False
            resized_early = False
            metric_sums = None
            stream = self._stream(data())
            step_t0 = time.perf_counter()
            try:
                for batch_idx, batch in enumerate(stream):
                    if self.steps_per_epoch is not None \
                            and batch_idx >= self.steps_per_epoch:
                        break
                    for cb in callbacks:
                        cb.on_batch_begin(batch_idx)
                    self.state, metrics = self.train_step(self.state, batch)
                    # The guard's flag rides the metrics dict but is a
                    # count, not a mean — pop it before the epoch
                    # accumulator sees it.
                    bad_flag = (metrics.pop("bad_step", None)
                                if isinstance(metrics, dict) else None)
                    metric_sums = self._accumulate_metrics(metric_sums,
                                                           metrics)
                    if bad_flag is not None:
                        guard_active = True
                        if self._track_bad_step(bad_flag):
                            bad_steps += 1
                    for cb in callbacks:
                        cb.on_batch_end(batch_idx)
                    nsteps += 1
                    # Telemetry: per-step wall time (completion to
                    # completion — input wait included, it is the
                    # number an operator acts on), throughput counters,
                    # and one flight-recorder event naming the step a
                    # post-mortem will call "last completed".
                    now = time.perf_counter()
                    self._m_step_seconds.observe(now - step_t0)
                    step_t0 = now
                    self._m_steps.inc()
                    # Post-increment count: the gauge reads "steps this
                    # process has completed" (the fleet poller's
                    # straggler spread keys on it).
                    self._m_gstep.set(self._global_step + 1)
                    try:
                        rows = int(np.shape(
                            jax.tree_util.tree_leaves(batch)[0])[0])
                    except (IndexError, TypeError):
                        rows = 0
                    if rows:
                        self._m_samples.inc(rows)
                    _flightrec.record("step", step=self._global_step,
                                      epoch=epoch)
                    _faults.step_hook(self._global_step)
                    self._global_step += 1
                    if self.resize is not None and self._maybe_resize():
                        # World re-formed in place: the rest of this
                        # epoch's stream is sharded for the old world —
                        # end the epoch here, resume on the new world.
                        resized_early = True
                        break
            finally:
                close = getattr(stream, "close", None)
                if close is not None:
                    close()
            if self.steps_per_epoch is None and not resized_early:
                # A resize-truncated epoch must not be recorded as the
                # inferred epoch length — it would silently cap every
                # later epoch at the truncation point.
                self.steps_per_epoch = nsteps

            # Epoch logs are the running mean over the epoch's batches (the
            # Keras fit semantics the reference callbacks assume), not the
            # last batch — ReduceLROnPlateau/MetricAverage need a stable
            # signal, not one noisy step. One device fetch for the whole
            # epoch: the (sums, count) accumulator replaces the former
            # per-step list whose epoch-end np.mean forced a sync per
            # retained step.
            logs: Dict[str, float] = {}
            if metric_sums is not None:
                # Skipped steps contributed zeros to every metric sum (the
                # step zeroes NaN-bearing metrics on a skip), so the mean
                # is over the steps that actually trained.
                good = max(1, nsteps - bad_steps)
                for k, v in jax.device_get(metric_sums).items():
                    logs[k] = float(v) / good
            if guard_active:
                logs["bad_steps"] = float(bad_steps)
            if eval_data is not None and self.eval_step is not None:
                if self._eval_placer is None:
                    # Hoisted: mesh lookup + NamedSharding construction
                    # happen once, not per eval batch per epoch.
                    self._eval_placer = make_batch_placer()
                evals = []
                for b in eval_data():
                    rows = int(np.shape(
                        jax.tree_util.tree_leaves(b)[0])[0])
                    evals.append((rows, self.eval_step(
                        self.state, self._eval_placer(b))))
                if evals:  # the eval iterable can be empty at large world sizes
                    total = sum(r for r, _ in evals)
                    for k in evals[0][1]:
                        logs[f"val_{k}"] = float(sum(
                            r * np.asarray(e[k]) for r, e in evals) / total)
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            self._m_epochs.inc()
            self.history.append(logs)
            if self.verbose:
                dt = time.perf_counter() - t0
                msg = " ".join(f"{k}={v:.4f}" for k, v in logs.items())
                print(f"epoch {epoch + 1}/{epochs} [{dt:.1f}s, "
                      f"{nsteps} steps] {msg}")
        for cb in callbacks:
            cb.on_train_end()
        return self.history


# ---------------------------------------------------------------------------
# Checkpoint / resume — rank-0-only write + broadcast-on-restore (SURVEY §5.4).
# ---------------------------------------------------------------------------

class AsyncCheckpointer:
    """Background checkpoint writer: the step loop pays only the
    device→host snapshot; serialization happens off the critical path.

    The synchronous ``save_checkpoint`` stalls the TPU for the whole orbax
    write (seconds at real model sizes, every epoch). The async protocol
    splits the save at the only point that needs the live state:

    1. **snapshot** (caller thread, ``CKPT_SNAPSHOT`` timeline phase) —
       ``jax.device_get`` the state into host numpy. The training loop can
       mutate/donate device state freely afterwards.
    2. **write** (this writer's thread, ``CKPT_WRITE`` phase) — orbax
       serialization + retention GC of the immutable host copy.
    3. **durable hook** — ``on_durable`` runs only after the write
       succeeded; the elastic two-phase commit hangs its marker file here,
       so a crash mid-write can never leave a marker pointing at torn
       bytes (the PR-1 contract, :mod:`horovod_tpu.elastic`).

    ``wait()`` blocks until every submitted write is durable and re-raises
    the first writer error; ``close()`` waits, stops the thread, and makes
    further submits fail. ``max_pending`` bounds host memory: the queue
    holds at most that many snapshots before ``submit`` backpressures.

    The writer thread is a daemon (a wedged orbax write must never hang
    interpreter exit), so an exit without ``close()`` — including an
    exception unwinding past the training loop — would silently drop
    queued writes; an ``atexit`` hook drains them best-effort (bounded
    wait, errors logged not raised). Prefer an explicit ``close()`` /
    ``with`` block: only those re-raise writer failures.
    """

    def __init__(self, max_pending: int = 2,
                 timeline: Optional[Any] = None):
        if timeline is None and runtime.is_initialized():
            timeline = runtime.world().timeline
        self.timeline = timeline
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, max_pending))
        self._errors: List[BaseException] = []
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="hvd-ckpt-writer", daemon=True)
        self._thread.start()
        atexit.register(self._drain_at_exit)

    def submit(self, write_fn: Callable[[], Any],
               on_durable: Optional[Callable[[], Any]] = None) -> None:
        """Enqueue a write job (host data must already be snapshotted).
        Blocks only when ``max_pending`` writes are already in flight."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._q.put((write_fn, on_durable))

    def _run(self):
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                write_fn, on_durable = item
                try:
                    with _timeline.maybe_op(self.timeline, "ckpt.write",
                                            _timeline.CKPT_WRITE):
                        write_fn()
                    if on_durable is not None:
                        on_durable()
                except BaseException as e:  # noqa: BLE001 — to wait()
                    self._errors.append(e)
            finally:
                self._q.task_done()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Barrier: returns once every submitted write is durable on disk,
        re-raising the first writer failure. Call before any restore (or
        before trusting the directory contents) — async means the bytes
        land later, not that they may not land.

        With ``timeout`` (seconds), a write still in flight when the
        deadline expires raises
        :class:`~horovod_tpu.exceptions.CheckpointTimeoutError` instead
        of blocking forever on a hung filesystem — the write itself is
        NOT cancelled (the thread keeps going; a later ``wait()`` sees
        its eventual outcome), but the caller gets control back to page
        a human or fail over."""
        if timeout is None:
            self._q.join()
        else:
            deadline = time.monotonic() + timeout
            # queue.Queue.join() has no timeout; wait on the same
            # all_tasks_done condition it uses, with a deadline.
            with self._q.all_tasks_done:
                while self._q.unfinished_tasks:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        from .exceptions import CheckpointTimeoutError
                        raise CheckpointTimeoutError(
                            f"checkpoint write still in flight after "
                            f"{timeout:.1f}s — filesystem hung or writer "
                            f"wedged ({self._q.unfinished_tasks} job(s) "
                            f"pending); the write was NOT cancelled")
                    self._q.all_tasks_done.wait(remaining)
        if self._errors:
            raise self._errors.pop(0)

    def close(self) -> None:
        """Drain pending writes, stop the thread, surface any error.

        Like ``wait()`` this is a durability barrier: it blocks until every
        pending write lands, HOWEVER long that takes — a wedged write (dead
        NFS) holds ``close()`` rather than returning with bytes not
        durable. The bounded-exit protection lives one layer down: the
        daemon thread plus the atexit drain keep an *unclosed* writer from
        hanging interpreter shutdown."""
        atexit.unregister(self._drain_at_exit)
        if self._closed:
            self._thread.join(timeout=60)
            if self._errors:
                raise self._errors.pop(0)
            return
        self._closed = True
        self._q.put(None)
        self._q.join()
        self._thread.join(timeout=60)
        if self._errors:
            raise self._errors.pop(0)

    def _drain_at_exit(self) -> None:
        """Bounded best-effort drain at interpreter shutdown: the queue's
        pending writes run before the stop sentinel, and the join timeout
        keeps a wedged write from hanging exit (the reason the thread is
        a daemon in the first place)."""
        if self._closed or not self._thread.is_alive():
            return
        self._closed = True
        try:
            self._q.put(None, timeout=60)
        except queue.Full:
            return
        self._thread.join(timeout=60)
        for e in self._errors:
            print(f"[hvd-ckpt-writer] checkpoint write failed at exit: {e!r}",
                  file=sys.stderr)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint(directory: str, state: TrainState,
                    step: Optional[int] = None,
                    max_to_keep: Optional[int] = None,
                    writer: Optional[AsyncCheckpointer] = None
                    ) -> Optional[str]:
    """Write a checkpoint — rank 0 only, like the reference
    (``checkpoint_dir=None`` on other ranks, ``README.md:78-80``).
    Returns the path written, or None on non-root ranks.

    ``max_to_keep``: after a successful write, delete the oldest
    checkpoints beyond the newest ``max_to_keep`` (retention is the
    writer's job since only rank 0 touches the directory).

    With ``writer`` (an :class:`AsyncCheckpointer`), only the device→host
    snapshot happens here; the orbax write and retention GC run on the
    writer's thread while training continues. The returned path is durable
    only after ``writer.wait()``.
    """
    if runtime.is_initialized() and runtime.world().controller_rank != 0:
        return None
    import orbax.checkpoint as ocp
    step = int(state.step) if step is None else step
    path = os.path.join(os.path.abspath(directory), f"ckpt_{step}")
    from .parallel.checkpoint import snapshot_to_host
    tl = writer.timeline if writer is not None else (
        runtime.world().timeline if runtime.is_initialized() else None)
    host = snapshot_to_host(state, timeline=tl)

    def _write():
        # orbax writes into a tmp dir and renames on finalize, so a writer
        # killed mid-write never leaves a visible ckpt_<step> for the
        # latest-step restore scan to trust. The integrity manifest lands
        # right after the rename — before any elastic marker (which hangs
        # off the writer's on_durable hook, strictly later).
        from .parallel.checkpoint import write_manifest
        ocp.PyTreeCheckpointer().save(path, host, force=True)
        write_manifest(path, host, step=step)
        apply_retention(directory, path, max_to_keep)

    if writer is None:
        with _timeline.maybe_op(tl, "ckpt.write", _timeline.CKPT_WRITE):
            _write()
    else:
        writer.submit(_write)
    return path


def apply_retention(directory: str, just_written: str,
                    max_to_keep: Optional[int]) -> None:
    """Delete the oldest checkpoints beyond the newest ``max_to_keep``.

    Retention by WRITE recency, not step number: a run resumed from a
    rolled-back step must never have its just-written checkpoint deleted
    in favor of stale higher-step leftovers. Shared by the replicated-DP
    writer above and the sharded writer
    (:mod:`horovod_tpu.parallel.checkpoint`) — one policy, one bug
    surface.
    """
    if max_to_keep is None or max_to_keep <= 0:
        return
    import shutil
    base = os.path.abspath(directory)
    entries = []
    for n in os.listdir(base):
        if _step_of(n) is None:
            continue
        full = os.path.join(base, n)
        try:
            entries.append((os.path.getmtime(full), full))
        except OSError:
            continue
    entries.sort()
    for _, old in entries[:-max_to_keep]:
        if old != just_written:
            shutil.rmtree(old, ignore_errors=True)


def _step_of(name: str) -> Optional[int]:
    if not name.startswith("ckpt_"):
        return None
    try:
        return int(name.split("_", 1)[1])
    except ValueError:
        return None


def latest_checkpoint_step(directory: str) -> Optional[int]:
    """Find the newest checkpoint's step (the resume scan rank 0 performs
    before broadcasting the epoch, ``keras_imagenet_resnet50.py:47-56``)."""
    if not os.path.isdir(directory):
        return None
    steps = [s for s in (_step_of(n) for n in os.listdir(directory))
             if s is not None]
    return max(steps) if steps else None


def restore_checkpoint(directory: str, state: TrainState,
                       step: Optional[int] = None,
                       verify: bool = True) -> TrainState:
    """Restore (on every rank, from the shared filesystem) then broadcast
    from rank 0 so all ranks are bit-identical — the reference's
    load-on-rank-0 + ``BroadcastGlobalVariablesCallback`` protocol
    (``keras_imagenet_resnet50.py:130-133``).

    ``verify`` (default on) checks the checkpoint's integrity manifest
    first and raises
    :class:`~horovod_tpu.exceptions.CheckpointCorruptError` naming the
    offending leaf instead of resuming from torn/bit-rotted bytes;
    manifest-less legacy checkpoints restore unverified."""
    import orbax.checkpoint as ocp
    from .optimizer import broadcast_global_variables
    if step is None:
        step = latest_checkpoint_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), f"ckpt_{step}")
    if verify:
        from .parallel.checkpoint import verify_checkpoint
        verify_checkpoint(path)
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(
        path, item=jax.tree_util.tree_map(np.asarray, state))
    if runtime.is_initialized() and runtime.size() > 1:
        restored = broadcast_global_variables(restored, root_rank=0)
    return restored
