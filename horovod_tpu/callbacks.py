"""Training callbacks — parity with ``horovod/keras/callbacks.py``.

* :class:`BroadcastGlobalVariablesCallback` — sync all state from rank 0 at
  train begin (``callbacks.py:8-34``).
* :class:`MetricAverageCallback` — epoch-end allreduce of metrics so
  LR-plateau/loggers see globally averaged values (``callbacks.py:37-87``).
* :class:`LearningRateScheduleCallback` — epoch- or batch-granular LR
  multiplier with **momentum correction** (``callbacks.py:90-199``): while a
  batch runs with lr' = lr·m, momentum is scaled by ``new_lr/old_lr`` and
  restored at batch end (Goyal et al. 1706.02677, §3 "momentum correction").
* :class:`LearningRateWarmupCallback` — gradual warmup
  ``lr/size → lr`` over ``warmup_epochs`` (``callbacks.py:202-259``).

TPU-native design
-----------------
optax is functional, so "set the optimizer's lr" becomes: build the inner
optimizer with ``optax.inject_hyperparams`` (so ``learning_rate`` /
``momentum`` live in the optimizer *state*), and callbacks rewrite those
state leaves between steps with ``optax.tree_utils.tree_set``. Because the
values are state — not trace-time constants — adjusting them every batch does NOT
retrigger XLA compilation, which is what makes per-batch smooth warmup viable
under jit.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np
import optax

from . import runtime
from .ops.collectives import allreduce
from .optimizer import broadcast_global_variables
from .utils.lr_schedule import LRScheduleCore, warmup_multiplier


def hyper_sgd(learning_rate: float, momentum: float = 0.0,
              nesterov: bool = False) -> optax.GradientTransformation:
    """SGD with runtime-adjustable ``learning_rate``/``momentum`` state —
    what the LR callbacks require (the analog of mutable
    ``model.optimizer.lr`` in the reference's Keras layer)."""
    return optax.inject_hyperparams(optax.sgd)(
        learning_rate=learning_rate, momentum=momentum, nesterov=nesterov)


def get_hyperparam(opt_state, name: str):
    return float(optax.tree_utils.tree_get(opt_state, name))


def set_hyperparam(opt_state, name: str, value):
    return optax.tree_utils.tree_set(opt_state, **{name: jnp.asarray(value)})


class Callback:
    """Keras-shaped callback protocol (the reference's callbacks subclass
    ``keras.callbacks.Callback``). ``trainer`` is any object with
    ``.state`` (a :class:`~horovod_tpu.training.TrainState`) and
    ``.steps_per_epoch``."""

    trainer: Any = None

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self, logs: Optional[Dict] = None): ...
    def on_train_end(self, logs: Optional[Dict] = None): ...
    def on_epoch_begin(self, epoch: int, logs: Optional[Dict] = None): ...
    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None): ...
    def on_batch_begin(self, batch: int, logs: Optional[Dict] = None): ...
    def on_batch_end(self, batch: int, logs: Optional[Dict] = None): ...


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast params/opt state/BN stats from ``root_rank`` at train begin
    (parity: ``callbacks.py:8-34``; consistency protocol SURVEY §5.4).

    Under a replicated single-controller mesh this is a logical no-op but is
    kept as an explicit re-sync point: after a restore-on-rank-0, it makes
    every rank bit-identical again.
    """

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, logs=None):
        t = self.trainer
        t.state = broadcast_global_variables(t.state, self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch-end metrics over ranks (parity: ``callbacks.py:37-87``).
    Must precede callbacks that consume metrics (ReduceLROnPlateau-style),
    as the reference documents."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs:
            return
        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating, np.integer)) \
                    or (hasattr(v, "shape") and getattr(v, "shape") == ()):
                logs[k] = float(np.asarray(
                    allreduce(jnp.asarray(v, jnp.float32), average=True,
                              name=f"metric.{k}")))


class LearningRateScheduleCallback(Callback):
    """LR = ``initial_lr * multiplier(epoch)`` between ``start_epoch`` and
    ``end_epoch`` (parity: ``callbacks.py:90-199``).

    ``staircase=True`` adjusts once per epoch with integer epoch;
    ``staircase=False`` adjusts every batch with fractional
    ``epoch + batch/steps_per_epoch``. With ``momentum_correction``, while a
    batch runs at an adjusted LR the momentum is scaled by ``new_lr/old_lr``
    and restored after the batch.
    """

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        # The schedule/momentum-correction math is shared with the Keras
        # adapter (utils/lr_schedule.py); this class owns only the optax
        # hyperparam-state plumbing.
        self.core = LRScheduleCore(
            multiplier, start_epoch=start_epoch, end_epoch=end_epoch,
            staircase=staircase, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch)

    # -- shared-core attribute passthroughs --------------------------------
    @property
    def steps_per_epoch(self):
        return self.core.steps_per_epoch

    @property
    def end_epoch(self):
        return self.core.end_epoch

    # -- state plumbing ----------------------------------------------------
    def _get_lr(self) -> float:
        return get_hyperparam(self.trainer.state.opt_state, "learning_rate")

    def _set_lr(self, v: float):
        self.trainer.state.opt_state = set_hyperparam(
            self.trainer.state.opt_state, "learning_rate", v)

    def _get_momentum(self) -> Optional[float]:
        # tree_get returns None (not KeyError) when the key is absent.
        m = optax.tree_utils.tree_get(self.trainer.state.opt_state,
                                      "momentum")
        return None if m is None else float(m)

    def _set_momentum(self, v: float):
        self.trainer.state.opt_state = set_hyperparam(
            self.trainer.state.opt_state, "momentum", v)

    # -- hooks -------------------------------------------------------------
    def on_train_begin(self, logs=None):
        if not self.core.staircase and not self.core.steps_per_epoch:
            self.core.steps_per_epoch = getattr(
                self.trainer, "steps_per_epoch", None)
        self.core.train_begin(self._get_lr())

    def on_epoch_begin(self, epoch, logs=None):
        self.core.epoch_begin(epoch)

    def on_batch_begin(self, batch, logs=None):
        new_lr = self.core.target_lr(batch)
        if new_lr is None:
            return
        old_lr = self._get_lr()
        self._set_lr(new_lr)
        m = self.core.corrected_momentum(old_lr, new_lr,
                                         self._get_momentum())
        if m is not None:
            self._set_momentum(m)

    def on_batch_end(self, batch, logs=None):
        m = self.core.momentum_to_restore()
        if m is not None:
            self._set_momentum(m)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = self._get_lr()


class ReduceLROnPlateauCallback(Callback):
    """Reduce LR when a monitored metric plateaus — the Keras callback the
    reference's advanced example stacks AFTER ``MetricAverageCallback``
    (``keras_mnist_advanced.py:87-95``: metrics must be globally averaged
    first so every rank takes the same LR decision)."""

    def __init__(self, monitor: str = "val_loss", factor: float = 0.1,
                 patience: int = 10, min_lr: float = 0.0,
                 mode: str = "min"):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.min_lr = min_lr
        self.mode = mode
        self.best: Optional[float] = None
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        if not logs or self.monitor not in logs:
            return
        current = float(logs[self.monitor])
        improved = (self.best is None
                    or (self.mode == "min" and current < self.best)
                    or (self.mode == "max" and current > self.best))
        if improved:
            self.best = current
            self.wait = 0
            return
        self.wait += 1
        if self.wait >= self.patience:
            lr = get_hyperparam(self.trainer.state.opt_state,
                                "learning_rate")
            new_lr = max(lr * self.factor, self.min_lr)
            if new_lr < lr:
                self.trainer.state.opt_state = set_hyperparam(
                    self.trainer.state.opt_state, "learning_rate", new_lr)
            self.wait = 0


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup ``lr/size → lr`` over ``warmup_epochs``
    (parity: ``callbacks.py:202-259``; Goyal et al. 1706.02677)::

        lr'(epoch) = lr/size * (epoch * (size-1)/warmup + 1)
    """

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        self.verbose = verbose
        # steps_per_epoch resolves lazily: on_train_begin may fill it in
        # from the trainer after construction.
        super().__init__(
            warmup_multiplier(warmup_epochs,
                              lambda: self.core.steps_per_epoch,
                              runtime.size),
            start_epoch=0, end_epoch=warmup_epochs, staircase=False,
            momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0 \
                and runtime.world().controller_rank == 0:
            print(f"\nEpoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self._get_lr():g}.")
