"""DistributedOptimizer and variable broadcast — the framework adapter layer.

Reference parity
----------------
* ``hvd.DistributedOptimizer`` wraps any ``tf.train.Optimizer`` and
  allreduces each gradient before the wrapped optimizer applies it, only when
  ``size() > 1`` (``horovod/tensorflow/__init__.py:127-226``); the Keras
  variant dynamically subclasses the user's optimizer class so checkpoints
  restore without Horovod installed (``horovod/keras/__init__.py:66-87``).
* ``hvd.broadcast_global_variables(root)`` = grouped assign of
  ``broadcast(var, root)`` over every variable
  (``horovod/tensorflow/__init__.py:82-90``);
  ``BroadcastGlobalVariablesHook`` runs it right after session creation
  (``__init__.py:93-124``).

TPU-native design
-----------------
The optimizer layer is an **optax gradient transformation**: composable,
functional, and jit-traceable. ``DistributedOptimizer(opt)`` returns an optax
``GradientTransformation`` whose ``update`` first allreduces gradients over
the ``"hvd"`` ICI axis — with reference-semantics fusion bucketing
(64 MiB / same-dtype / order-preserving, see ``ops/fusion.py``) — then
defers to the wrapped transformation. Sparse gradients
(:class:`~horovod_tpu.ops.sparse.IndexedSlices` leaves) take the
two-allgather path (``horovod/tensorflow/__init__.py:61-72``) unless
``sparse_as_dense=True`` densifies them first.

Because optax state is a pure pytree, the Keras "dynamic subclass"
checkpoint-compatibility trick has a simpler equivalent: the wrapped
transformation's state **is** the inner optimizer's state, unchanged, so
checkpoints restore with plain optax, without this framework installed.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from . import runtime
from .ops.collectives import broadcast as _broadcast
from .ops.fusion import fused_allreduce
from .ops.sparse import IndexedSlices, allreduce_indexed_slices
from .runtime import AXIS


def _is_sparse_leaf(x) -> bool:
    return isinstance(x, IndexedSlices)


class Compression:
    """Gradient compression for the cross-chip allreduce.

    TPU-era extra (no analog in reference v0.11.2; later Horovod grew
    ``Compression.fp16``): ``Compression.bf16`` casts float gradients wider
    than 16 bits to bfloat16 — the MXU/ICI-native 16-bit type — before the
    fused allreduce and restores the original dtype after, halving
    interconnect bytes per step. Accumulation inside the XLA all-reduce is
    f32 on TPU, so the loss of precision is the single round-trip cast.
    """

    class none:  # noqa: N801 — enum-style namespace
        @staticmethod
        def compress(t):
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t

    class bf16:  # noqa: N801
        @staticmethod
        def compress(t):
            if (hasattr(t, "dtype")
                    and jnp.issubdtype(t.dtype, jnp.floating)
                    and jnp.dtype(t.dtype).itemsize > 2):
                return t.astype(jnp.bfloat16), t.dtype
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t.astype(ctx) if ctx is not None else t


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         *,
                         average: bool = True,
                         fusion_threshold: Optional[int] = None,
                         sparse_as_dense: bool = False,
                         compression: Any = Compression.none,
                         accum_steps: int = 1,
                         axis_name: str = AXIS
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with fused gradient allreduce.

    Parity: ``hvd.DistributedOptimizer`` (``horovod/tensorflow/__init__.py:
    127-186``) — gradients are averaged across ranks before being applied;
    a no-op when ``size() == 1`` (``__init__.py:180-182``). Call inside the
    jitted train step under ``shard_map`` over the world mesh.
    ``compression=Compression.bf16`` halves allreduce bytes (see
    :class:`Compression`).

    ``accum_steps`` is the reference's ``backward_passes_per_step``: the
    caller feeds ``update`` the *sum* of N per-microbatch gradients and one
    fused allreduce fires per accumulated step, averaged by the **global
    microbatch count** (``accum_steps × size``) — the ``1/accum_steps`` is
    folded into the fused bucket traversal (:func:`fused_allreduce`'s
    ``prescale``) and ``average=True`` supplies the ``1/size``. Drive your
    own accumulation loop with this knob, or use
    ``make_train_step(accum_steps=N)`` which scans microbatches inside the
    compiled step and performs the microbatch mean itself (do NOT set both:
    the gradients would be divided by N twice).
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(grads, state, params=None, **extra):
        # ``finite_out``: the bad-step guard's side channel. When
        # ``make_train_step(guard_nonfinite=True)`` passes a dict here,
        # the fused allreduce additionally derives the world-wide
        # all-finite flag from the ALREADY-reduced buckets (same psum
        # round, zero extra collectives — see fused_allreduce) and this
        # function deposits it under ``"all_finite"`` for the step to
        # gate params/opt_state on. In-trace only: the dict holds a
        # tracer for the duration of the surrounding trace.
        finite_out = extra.pop("finite_out", None)
        if finite_out is None:
            grads = allreduce_gradients(
                grads, average=average, fusion_threshold=fusion_threshold,
                sparse_as_dense=sparse_as_dense, compression=compression,
                accum_steps=accum_steps, axis_name=axis_name)
        else:
            grads, all_finite = allreduce_gradients(
                grads, average=average, fusion_threshold=fusion_threshold,
                sparse_as_dense=sparse_as_dense, compression=compression,
                accum_steps=accum_steps, axis_name=axis_name,
                return_finite=True)
            finite_out["all_finite"] = all_finite
        return optimizer.update(grads, state, params, **extra)

    # Stamp the knob where make_train_step can see it: setting accum_steps
    # on BOTH layers would silently divide gradients by N twice.
    update_fn.accum_steps = accum_steps
    # Capability stamp for the guard: make_train_step only threads the
    # finite_out channel into optimizers that declare it (a plain optax
    # transformation would choke on the unknown kwarg).
    update_fn.supports_finite_out = True
    return optax.GradientTransformation(init_fn, update_fn)


def allreduce_gradients(grads,
                        average: bool = True,
                        fusion_threshold: Optional[int] = None,
                        sparse_as_dense: bool = False,
                        compression: Any = Compression.none,
                        accum_steps: int = 1,
                        axis_name: str = AXIS,
                        return_finite: bool = False):
    """Allreduce a gradient pytree: dense leaves via fused flat buckets,
    sparse leaves via allgather (``horovod/tensorflow/__init__.py:61-79``).
    ``accum_steps > 1`` divides by the local microbatch count (the caller
    passes a gradient *sum* over N backward passes) as a prescale fused
    into the bucket traversal. ``return_finite=True`` additionally
    returns the world-wide all-finite scalar derived inside the same
    traversal (see :func:`~horovod_tpu.ops.fusion.fused_allreduce`)."""
    prescale = None if accum_steps <= 1 else 1.0 / accum_steps
    if runtime.is_initialized() and runtime.size() == 1 \
            and not runtime._in_world_trace():
        # size()==1 fast path (__init__.py:180-182) — but the microbatch
        # mean is not a cross-rank concern and must still happen, and
        # neither is finiteness: check the (scaled) local tree directly.
        if prescale is None and not return_finite:
            return grads
        from .ops.fusion import _prescale_array

        def _scale(l):
            if prescale is None:
                return l
            if _is_sparse_leaf(l):
                return IndexedSlices(_prescale_array(l.values, prescale),
                                     l.indices, l.dense_shape)
            return _prescale_array(l, prescale)
        scaled = jax.tree_util.tree_map(_scale, grads,
                                        is_leaf=_is_sparse_leaf)
        if not return_finite:
            return scaled
        finite = jnp.ones((), jnp.bool_)
        for l in jax.tree_util.tree_leaves(scaled,
                                           is_leaf=_is_sparse_leaf):
            v = l.values if _is_sparse_leaf(l) else l
            if jnp.issubdtype(v.dtype, jnp.inexact):
                finite = finite & jnp.all(jnp.isfinite(v))
        return scaled, finite

    if sparse_as_dense:
        grads = jax.tree_util.tree_map(
            lambda l: l.to_dense() if _is_sparse_leaf(l) else l,
            grads, is_leaf=_is_sparse_leaf)

    # Structural (tree_map) compression round-trip: the ctx tree mirrors the
    # gradient tree leaf-for-leaf (wrapped in an opaque holder so a None ctx
    # is still a leaf), so restoration cannot depend on flatten ordering.
    class _Ctx:
        __slots__ = ("dtype",)

        def __init__(self, dtype):
            self.dtype = dtype

    ctx_tree = jax.tree_util.tree_map(
        lambda l: _Ctx(None if _is_sparse_leaf(l)
                       else compression.compress(l)[1]),
        grads, is_leaf=_is_sparse_leaf)
    compressed = jax.tree_util.tree_map(
        lambda l: l if _is_sparse_leaf(l) else compression.compress(l)[0],
        grads, is_leaf=_is_sparse_leaf)
    # fused_allreduce buckets dense leaves and routes IndexedSlices leaves
    # through the two-allgather sparse path.
    reduced = fused_allreduce(compressed, average=average,
                              fusion_threshold=fusion_threshold,
                              axis_name=axis_name, prescale=prescale,
                              return_finite=return_finite)
    if return_finite:
        reduced, all_finite = reduced
    out = jax.tree_util.tree_map(
        lambda l, c: l if _is_sparse_leaf(l)
        else compression.decompress(l, c.dtype),
        reduced, ctx_tree, is_leaf=_is_sparse_leaf)
    return (out, all_finite) if return_finite else out


def broadcast_global_variables(variables, root_rank: int = 0,
                               axis_name: str = AXIS):
    """Broadcast every leaf of a pytree from ``root_rank``.

    Parity: ``hvd.broadcast_global_variables``
    (``horovod/tensorflow/__init__.py:82-90``) — used right after
    initialization or checkpoint restore so all ranks start from rank 0's
    weights (§5.4 consistency protocol).
    """
    return jax.tree_util.tree_map(
        lambda v: _broadcast(v, root_rank=root_rank, axis_name=axis_name),
        variables)


# Alias matching modern naming; same semantics.
broadcast_parameters = broadcast_global_variables


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              axis_name: str = AXIS):
    """Broadcast optimizer state (momenta etc.) from ``root_rank`` — the
    optax analog of broadcasting optimizer slot variables, which the
    reference gets for free because slots are global variables
    (``horovod/tensorflow/__init__.py:82-90``)."""
    return broadcast_global_variables(opt_state, root_rank, axis_name)
